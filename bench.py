#!/usr/bin/env python
"""Benchmark: the scheduler's placement inner loop, TPU solver vs host oracle.

Measures the north-star hot loop (BASELINE.json): per-placement feasibility +
bin-pack scoring + selection over a 10K-node fleet (config tier 3/4 shape:
cpu+mem+disk constraints), comparing
  - host oracle: the faithful reimplementation of Nomad's iterator stack
    (scheduler/rank.go BinPackIterator + selection), one Stack.Select per
    placement -- the reference algorithm at reference semantics;
  - TPU solver: the same placements solved as one dense lax.scan dispatch
    (nomad_tpu/solver/binpack.py), verified to produce IDENTICAL placements.

Both paths run the SAME number of placements from the same initial world, so
vs_baseline compares equal, parity-verified work. Parity is GATING: any
placement mismatch prints the JSON line (for the record) and exits non-zero.

Platform selection: this image's jax mis-handles the JAX_PLATFORMS env var
(the axon TPU plugin hijacks init whenever the var is set, and a broken
tunnel can HANG backend init forever, not just fail). So the var is removed,
TPU availability is probed in a subprocess with a hard timeout, and the main
process falls back to the CPU backend when the probe fails or times out.

Prints ONE JSON line {"metric","value","unit","vs_baseline",...} on stdout;
all diagnostics go to stderr.
"""
import functools
import json
import os
import statistics
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Quiet XLA's native C++ logging: persistent-cache AOT loads print a
# screenful of benign machine-feature diffs at ERROR level per entry
# (cpu_aot_loader.cc ignores TF_CPP_MIN_LOG_LEVEL), which would crowd
# the driver-captured log tail out of useful content. Filter them out at
# the fd level so native writes are caught too.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")


def _filter_native_stderr():
    import atexit
    import threading
    real = os.dup(2)
    r, w = os.pipe()
    os.dup2(w, 2)
    os.close(w)

    def emit(data: bytes) -> None:
        try:
            os.write(real, data)
        except OSError:
            pass        # real stderr gone; keep draining so fd 2 never
                        # fills and blocks the bench

    def pump():
        buf = b""
        while True:
            try:
                chunk = os.read(r, 65536)
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if b"cpu_aot_loader" not in line:
                    emit(line + b"\n")
        if buf:
            emit(buf)

    t = threading.Thread(target=pump, daemon=True)
    t.start()

    def restore():
        # point fd 2 back at the real stderr; dropping the pipe's last
        # write end EOFs the pump so it drains the tail (incl. any final
        # parity-failure lines) before interpreter teardown
        sys.stderr.flush()
        os.dup2(real, 2)
        t.join(timeout=5.0)

    atexit.register(restore)


_filter_native_stderr()

N_NODES = int(os.environ.get("BENCH_NODES", "10000"))
N_PLACEMENTS = int(os.environ.get("BENCH_PLACEMENTS", "2000"))
N_REPEATS = max(1, int(os.environ.get("BENCH_REPEATS", "5")))
N_ORACLE_RUNS = max(1, int(os.environ.get("BENCH_ORACLE_RUNS", "2")))
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "240"))

_PROBE_SRC = """
import os
os.environ.pop("JAX_PLATFORMS", None)
import jax
devs = jax.devices()
print("PLATFORM:" + devs[0].platform)
"""


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def _probe_tpu() -> str:
    """Probe backend init in its own process GROUP with a hard timeout.
    Output goes to temp files (not pipes): a hung axon init can fork helper
    processes that inherit pipe write-ends, and subprocess.run's post-kill
    communicate() would then block on EOF forever. Killing the whole group
    and reading files makes the timeout actually hard."""
    import signal
    import tempfile

    platform = ""
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    with tempfile.TemporaryFile(mode="w+") as fout, \
            tempfile.TemporaryFile(mode="w+") as ferr:
        t0 = time.time()
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE_SRC], stdout=fout, stderr=ferr,
            env=env, start_new_session=True)
        try:
            rc = proc.wait(timeout=PROBE_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()
            log(f"bench: TPU probe timed out after {PROBE_TIMEOUT_S}s; "
                "falling back to CPU backend")
            return ""
        fout.seek(0)
        for line in fout.read().splitlines():
            if line.startswith("PLATFORM:"):
                platform = line.split(":", 1)[1].strip().lower()
        log(f"bench: probe rc={rc} platform={platform!r} "
            f"in {time.time() - t0:.1f}s")
        if rc != 0:
            ferr.seek(0)
            log("bench: probe stderr tail:",
                ferr.read().strip().splitlines()[-1:] or "")
    return platform


def pick_platform() -> str:
    """Returns the platform the main process should use ('tpu' or 'cpu'),
    configuring jax accordingly BEFORE its first backend touch."""
    os.environ.pop("JAX_PLATFORMS", None)
    forced = os.environ.get("BENCH_PLATFORM", "").strip().lower()
    platform = ""
    if forced:
        platform = forced
        log(f"bench: BENCH_PLATFORM={forced} (probe skipped)")
    else:
        platform = _probe_tpu()
    import jax
    if platform != "tpu":
        platform = "cpu"
        jax.config.update("jax_platforms", "cpu")
    try:
        actual = jax.devices()[0].platform
    except RuntimeError as e:
        log(f"bench: backend init failed ({e}); forcing CPU")
        platform = "cpu"
        jax.config.update("jax_platforms", "cpu")
        actual = jax.devices()[0].platform
    log(f"bench: running on {actual} ({len(jax.devices())} device(s))")
    return actual


def build_world():
    from nomad_tpu import mock
    from nomad_tpu.scheduler import Harness

    h = Harness()
    nodes = []
    for i in range(N_NODES):
        n = mock.node()
        n.id = f"bench-node-{i:06d}"
        n.node_resources.cpu.cpu_shares = (2000, 4000, 8000)[i % 3]
        n.node_resources.memory.memory_mb = (4096, 8192, 16384)[i % 3]
        n.compute_class()
        nodes.append(n)
        h.state.upsert_node(n)
    job = mock.job(id="bench-job")
    job.task_groups[0].count = N_PLACEMENTS
    h.state.upsert_job(job)
    return h, job, nodes


def time_host_inner_loop(h, job, nodes, n_placements):
    """One Stack.Select per placement, usage carried via the plan --
    exactly the reference's per-eval inner loop."""
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.stack import GenericStack, SelectOptions
    from nomad_tpu.structs import (
        AllocatedResources, AllocatedSharedResources, Allocation, Plan,
        generate_uuid)

    plan = Plan(eval_id="bench-eval-0000000000000001", priority=50, job=job)
    snap = h.state.snapshot()
    ctx = EvalContext(snap, plan)
    stack = GenericStack(False, ctx)
    stack.set_job(job)
    stack.set_nodes(list(nodes))
    tg = job.task_groups[0]

    t0 = time.perf_counter()
    placed = {}
    for i in range(n_placements):
        name = f"{job.id}.{tg.name}[{i}]"
        option = stack.select(tg, SelectOptions(alloc_name=name))
        if option is None:
            placed[name] = None
            continue
        alloc = Allocation(
            id=generate_uuid(), name=name, job_id=job.id, job=job,
            task_group=tg.name, node_id=option.node.id,
            allocated_resources=AllocatedResources(
                tasks=dict(option.task_resources),
                shared=AllocatedSharedResources(
                    disk_mb=tg.ephemeral_disk.size_mb)))
        plan.append_alloc(alloc)
        placed[name] = option.node.id
    dt = time.perf_counter() - t0
    return dt, placed


def time_native_oracle(h, job, nodes, n_placements, runs=5):
    """The compiled-host baseline: the same inner loop as
    time_host_inner_loop but as C++ over packed arrays (native/
    pack_kernels.cc nt_solve_eval) -- the strongest plausible host
    implementation of the reference algorithm (a lower bound on what the
    Go BinPackIterator costs; the real reference walks structs/maps per
    candidate). Packing is untimed: the Go path starts from structs
    already resident in memory. Returns (best_dt, placed) or (None, None)
    when the native library can't be built."""
    from nomad_tpu import native
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.native_oracle import PackedWorld, solve
    from nomad_tpu.structs import Plan

    if not native.ensure_built():
        return None, None
    import numpy as np

    tg = job.task_groups[0]
    plan = Plan(eval_id="bench-eval-0000000000000001", priority=50, job=job)
    snap = h.state.snapshot()
    ctx = EvalContext(snap, plan)
    world = PackedWorld(nodes, ctx, job, tg)
    base = {k: (v.copy() if isinstance(v, np.ndarray) else v)
            for k, v in world.__dict__.items()}
    best = None
    placed_idx = None
    for _ in range(runs):
        w = PackedWorld.__new__(PackedWorld)
        w.__dict__.update({k: (v.copy() if isinstance(v, np.ndarray) else v)
                           for k, v in base.items()})
        t0 = time.perf_counter()
        placed_idx = solve(w, plan.eval_id, snap.latest_index(),
                           n_placements, tg.count)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    placed = {f"{job.id}.{tg.name}[{i}]": nid
              for i, nid in placed_idx.items()}
    return best, placed


def time_batched_path(n_nodes, e_evals, per_eval):
    """The production batched path (the designed TPU win): E distinct jobs
    -> E evals coalesced by the BatchWorker, their dense solves fused into
    one device dispatch at the SolveBarrier, plans serially verified by the
    applier. Measures wall time for a full warmed round. Returns
    (dt, n_evals, n_placed)."""
    from nomad_tpu import mock
    from nomad_tpu.server import Server
    from nomad_tpu.structs import SchedulerConfiguration

    server = Server(num_workers=e_evals, heartbeat_ttl=3600.0,
                    eval_batching=True, batch_width=e_evals)
    server.state.set_scheduler_config(
        SchedulerConfiguration(scheduler_algorithm="tpu-binpack"))
    server.start()
    try:
        for i in range(n_nodes):
            n = mock.node()
            n.id = f"bbench-node-{i:06d}"
            n.node_resources.cpu.cpu_shares = (2000, 4000, 8000)[i % 3]
            n.node_resources.memory.memory_mb = (4096, 8192, 16384)[i % 3]
            n.compute_class()
            server.register_node(n)

        def run_round(tag):
            jobs = []
            for i in range(e_evals):
                job = mock.job(id=f"bbench-{tag}-{i}")
                job.task_groups[0].count = per_eval
                jobs.append(job)
            t0 = time.perf_counter()
            for job in jobs:
                server.register_job(job)
            want = e_evals * per_eval
            deadline = time.time() + 600
            while time.time() < deadline:
                # O(1) index counts while waiting: the full object-list
                # scan (64K allocs at headline shape) 50x/s from this
                # thread was stealing GIL time from the pipeline it
                # measures; the exact desired_status check runs once the
                # cheap count says the round might be done
                approx = sum(
                    server.state.num_allocs_by_job(job.namespace, job.id)
                    for job in jobs)
                if approx >= want:
                    placed = sum(
                        1 for job in jobs
                        for a in server.state.allocs_by_job(
                            job.namespace, job.id)
                        if a.desired_status == "run")
                    if placed >= want:
                        break
                time.sleep(0.02)
            else:
                placed = sum(
                    1 for job in jobs
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                    if a.desired_status == "run")
            return time.perf_counter() - t0, placed, jobs

        def drain_round(jobs):
            """Free a round's capacity before the next one: at headline
            shape (32x2000x500MHz = 32M shares) one round consumes ~70% of
            the 10K-node cluster, so a measured round after an undrained
            warm round runs into capacity exhaustion and blocks forever
            (that was BENCH_r04's TRUNCATED 29,328/64,000). Matching the
            reference's semantics, capacity frees only when the CLIENT
            acknowledges the stop (ProposedAllocs filters client-terminal
            only, context.go:200); this bench has no client agents, so
            acknowledge the server-side stops here the way a fleet of
            clients would (node_endpoint.go:1322 UpdateAlloc)."""
            for job in jobs:
                server.deregister_job(job.namespace, job.id)
            deadline = time.time() + 120
            live = -1
            while time.time() < deadline:
                live = sum(
                    1 for job in jobs
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                    if a.desired_status == "run")
                if live == 0:
                    break
                time.sleep(0.25)   # full-scan poll; unmeasured, keep rare
            if live:
                # warm-round deregister plans are still in flight; a round
                # measured now would share the applier with them, so it
                # must not be published as a clean number (and acking
                # allocs the scheduler hasn't stopped yet would only
                # muddy a post-mortem of the wedged state)
                log(f"bench: WARNING warm-round drain incomplete "
                    f"({live} live); measured round would be contaminated")
                return False
            import copy
            acks = []
            for job in jobs:
                for a in server.state.allocs_by_job(job.namespace, job.id):
                    if not a.client_terminal_status():
                        ack = copy.copy(a)
                        ack.client_status = "complete"
                        acks.append(ack)
            server.update_allocs_from_client(acks)
            return True

        warm_dt, warm_placed, warm_jobs = run_round("warm")
        log(f"bench: batched warmup (incl. compile) {warm_dt:.3f}s "
            f"({warm_placed} placed)")
        if not drain_round(warm_jobs):
            # dt=0 sentinel: the measured round never ran (drain failed)
            return 0.0, e_evals, 0
        dt, placed, _ = run_round("run")
        log(f"bench: applier over the run: "
            f"applied={server.planner.plans_applied} "
            f"rejected={server.planner.plans_rejected} "
            f"group_commits={server.planner.batches_committed}")
        time_batched_path.last_planner_stats = {
            "rejected": server.planner.plans_rejected,
            "group_commits": server.planner.batches_committed,
        }
        # quality + saturation fields captured while this server (the
        # e2e measurement the ROADMAP's next bets are judged by) still
        # owns the observatory -- shutdown detaches it
        from nomad_tpu.benchkit import quality_stamp
        time_batched_path.last_quality = quality_stamp()
        return dt, e_evals, placed
    finally:
        server.shutdown()


def time_lpq(n_nodes, e_evals, per_eval):
    """The whole-queue LP-relaxation tier (ISSUE 8) end to end: E
    distinct jobs coalesced by the LPQ batch worker into joint
    alloc x node solves, rounded + repaired, committed through the
    group applier. Returns a dict of lpq_* artifact fields or None."""
    from nomad_tpu import mock
    from nomad_tpu.server import Server
    from nomad_tpu.solver import lpq as lpq_mod
    from nomad_tpu.structs import SchedulerConfiguration

    env_overrides = {
        # gather the whole registration burst into one joint solve
        "NOMAD_TPU_LPQ_BATCH": os.environ.get(
            "NOMAD_TPU_LPQ_BATCH", str(e_evals)),
        "NOMAD_TPU_LPQ_GATHER_MS": os.environ.get(
            "NOMAD_TPU_LPQ_GATHER_MS", "400"),
    }
    saved = {k: os.environ.get(k) for k in env_overrides}
    os.environ.update(env_overrides)
    server = Server(num_workers=e_evals, heartbeat_ttl=3600.0,
                    eval_batching=True, batch_width=e_evals)
    server.state.set_scheduler_config(
        SchedulerConfiguration(scheduler_algorithm="tpu-lpq"))
    server.start()
    try:
        for i in range(n_nodes):
            n = mock.node()
            n.id = f"lpq-node-{i:06d}"
            n.node_resources.cpu.cpu_shares = (2000, 4000, 8000)[i % 3]
            n.node_resources.memory.memory_mb = (4096, 8192, 16384)[i % 3]
            n.compute_class()
            server.register_node(n)
        jobs = []
        for i in range(e_evals):
            job = mock.job(id=f"lpq-bench-{i}")
            job.task_groups[0].count = per_eval
            jobs.append(job)
        lpq_mod._reset_for_tests()
        t0 = time.perf_counter()
        for job in jobs:
            server.register_job(job)
        want = e_evals * per_eval
        deadline = time.time() + 600
        placed = 0
        while time.time() < deadline:
            approx = sum(
                server.state.num_allocs_by_job(job.namespace, job.id)
                for job in jobs)
            if approx >= want:
                placed = sum(
                    1 for job in jobs
                    for a in server.state.allocs_by_job(job.namespace,
                                                        job.id)
                    if a.desired_status == "run")
                if placed >= want:
                    break
            time.sleep(0.02)
        dt = time.perf_counter() - t0
        stats = lpq_mod.lpq_stats()
        if placed < want:
            log(f"bench: lpq TRUNCATED ({placed}/{want} placed); "
                f"dropping metric")
            return None
        # zero capacity violations is an acceptance invariant: the
        # repair pass must keep the applier from ever rejecting an
        # LP-tier plan on capacity
        rejected = server.planner.plans_rejected
        log(f"bench: lpq {e_evals} evals x {per_eval} in {dt:.3f}s "
            f"({placed} placed, {placed / dt:.0f} placements/s, "
            f"{stats['evals_per_solve']:.1f} evals/solve, "
            f"repair_rate={stats['repair_rate']:.4f}, "
            f"quality_delta={stats['quality_delta']}, "
            f"applier_rejected={rejected})")
        return {
            "lpq_placements_per_sec": round(placed / dt, 2),
            "lpq_evals_per_solve": round(stats["evals_per_solve"], 2),
            "lpq_repair_rate": round(stats["repair_rate"], 5),
            "lpq_quality_delta": stats["quality_delta"],
            "lpq_frag_delta": stats["frag_delta"],
            "lpq_solves": stats["solves"],
            "lpq_greedy_lanes": stats["greedy_lanes"],
            "lpq_planner_rejected": rejected,
        }
    finally:
        server.shutdown()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def pack_fused_lanes(h, nodes, e_evals, per_eval, tag="fused-bench"):
    """E distinct jobs' lanes packed from one snapshot -- the input shape
    of the production SolveBarrier solve point. Returns None when any
    lane is solver-ineligible."""
    from nomad_tpu import mock
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.reconcile import AllocPlaceResult
    from nomad_tpu.solver.service import TpuPlacementService
    from nomad_tpu.structs import Plan

    snap = h.state.snapshot()
    lanes = []
    for i in range(e_evals):
        job = mock.job(id=f"{tag}-{i}")
        job.task_groups[0].count = per_eval
        tg = job.task_groups[0]
        plan = Plan(eval_id=f"{tag}-eval-{i:016d}"[-36:], priority=50,
                    job=job)
        ctx = EvalContext(snap, plan)
        places = [AllocPlaceResult(name=f"{job.id}.{tg.name}[{k}]",
                                   task_group=tg)
                  for k in range(per_eval)]
        service = TpuPlacementService(ctx, job, batch_mode=False,
                                      spread_alg=False)
        lane = service.pack(tg, places, nodes)
        if lane is None:
            return None
        lanes.append(lane)
    return lanes


def time_fused_solver(h, nodes, e_evals, per_eval, repeats=3):
    """Solver-only fused throughput: E distinct jobs' lanes packed from one
    snapshot, solved as ONE coalesced dispatch (the production BatchWorker
    solve point, minus the Python control plane that time_batched_path
    includes). Gated: the fused results must equal each lane's solo
    dispatch. Returns (median_dt, n_placed_per_round, mismatch)."""
    from nomad_tpu.solver.batch import fuse_and_solve
    from nomad_tpu.solver.service import dispatch_lane

    lanes = pack_fused_lanes(h, nodes, e_evals, per_eval)
    if lanes is None:
        return None, 0, 0, None

    fused = fuse_and_solve(lanes)           # warmup (incl. compile)
    mismatch = 0
    for lane, res in zip(lanes, fused):
        solo = dispatch_lane(lane)
        if not (res[0] == solo[0]).all():
            mismatch += int((res[0] != solo[0]).sum())
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fused = fuse_and_solve(lanes)
        times.append(time.perf_counter() - t0)
    placed = sum(int((res[0] >= 0).sum()) for res in fused)

    # compute-only: same fused program with device-RESIDENT inputs.
    # Separates chip capability from the host<->device link (which in
    # this environment is a tunnel ~1000x slower than local PCIe).
    compute_info = None
    try:
        blocking_dt, marginal_dt, pipelined_dt = _fused_compute_only(
            lanes, repeats)
        compute_info = {"blocking": blocking_dt, "marginal": marginal_dt,
                        "pipelined": pipelined_dt}
    except Exception as e:  # noqa: BLE001 -- report without it
        log(f"bench: fused compute-only probe failed: {e!r}")
    return statistics.median(times), placed, mismatch, compute_info


@functools.lru_cache(maxsize=1)
def _mesh_single_device_fn():
    """One pinned single-device jit of the fused greedy program,
    shared across the mesh leg's sweep shapes (jit's own trace cache
    buckets by shape; a fresh jit per call would defeat it)."""
    import jax

    from nomad_tpu.solver.binpack import solve_eval_batch

    return jax.jit(
        functools.partial(solve_eval_batch, spread_alg=False,
                          dtype_name="float32"),
        device=jax.devices()[0])


def _per_shard_actual_by_device():
    """Cumulative per-device actual bytes off the xferobs per_shard
    ledger (rows accumulate; callers diff snapshots)."""
    from nomad_tpu.solver import xferobs
    by_dev = {}
    for rows in (xferobs.state().get("per_shard") or {}).values():
        for dev, row in rows.items():
            by_dev[dev] = by_dev.get(dev, 0) + \
                int(row.get("actual_bytes", 0))
    return by_dev


def time_mesh_leg(repeats=3):
    """Multi-chip mesh solve leg (ISSUE 19): the fused greedy program
    through the registered 2D (evals, nodes) mesh factories vs the
    single-device jit of the SAME program, swept over node counts.
    Guarded on >1 attached device AND the NOMAD_TPU_MESH knob -- the
    rollback lever disables this leg exactly as it disables production
    mesh dispatch.  Parity is gating (bit-exact by construction: the
    cross-shard max/argmax is order-insensitive); per-shard shipped
    bytes come off the xferobs per_shard ledger (max over devices for
    the largest sweep shape -- the per-chip HBM ship budget).  On the
    CPU virtual mesh collectives are intra-host copies, so the
    collective overhead reads positive there by design; the walls are
    the headline only on real chips (see OPERATIONS.md "Mesh
    execution")."""
    import jax
    import numpy as np

    from nomad_tpu.parallel import mesh as meshmod

    if not meshmod.mesh_enabled() or jax.device_count() < 2:
        return None

    import __graft_entry__ as graft

    e_evals, per_eval = 8, 16
    mismatch = 0
    sweep = []
    for n_nodes in (256, 512):
        rng = np.random.default_rng(n_nodes)
        lanes = [graft._varied_inputs(rng, n_nodes, per_eval)
                 for _ in range(e_evals)]
        const, init, batch = (
            jax.tree.map(lambda *xs: np.stack(xs),
                         *[lane[i] for lane in lanes])
            for i in range(3))

        ref_fn = _mesh_single_device_fn()
        ref = jax.block_until_ready(ref_fn(const, init, batch))
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(ref_fn(const, init, batch))
            times.append(time.perf_counter() - t0)
        single_dt = statistics.median(times)

        mesh = meshmod.make_mesh(min(8, jax.device_count()))
        if mesh is None:
            return None
        shard0 = _per_shard_actual_by_device()
        with mesh:
            s_const, s_init, s_batch = meshmod.shard_solver_inputs(
                mesh, const, init, batch)
            fn = meshmod.mesh_solve_fn(mesh, False, "float32")
            out = jax.block_until_ready(fn(s_const, s_init, s_batch))
            times = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(s_const, s_init, s_batch))
                times.append(time.perf_counter() - t0)
        mesh_dt = statistics.median(times)
        shard1 = _per_shard_actual_by_device()
        shard_bytes = max(
            (shard1.get(d, 0) - shard0.get(d, 0) for d in shard1),
            default=0)

        for i in range(2):
            mismatch += int((np.asarray(out[i])
                             != np.asarray(ref[i])).sum())
        sweep.append({
            "nodes": n_nodes,
            "single_ms": round(single_dt * 1e3, 3),
            "mesh_ms": round(mesh_dt * 1e3, 3),
            "shard_bytes": shard_bytes,
        })

    head = sweep[-1]
    placements = e_evals * per_eval
    return {
        "mesh_pps": round(placements / (head["mesh_ms"] / 1e3), 2)
        if head["mesh_ms"] else 0.0,
        "mesh_shard_bytes": head["shard_bytes"],
        "mesh_collective_ms": round(
            max(0.0, head["mesh_ms"] - head["single_ms"]), 3),
        "mesh_parity_mismatch": mismatch,
        "mesh_grid": [int(x) for x in
                      meshmod.make_mesh(
                          min(8, jax.device_count())).devices.shape],
        "mesh_sweep": sweep,
    }


def _tunnel_rtt():
    """Round-trip latency of a trivial dispatch+fetch (median of 5).
    Under the axon tunnel this is ~tens of ms and dominates ANY blocking
    per-call timing; reporting it separately lets every other metric be
    read as (RTT + real work). On local-attached hardware it is ~0."""
    import jax
    import numpy as np
    # nomadlint: waive=no-callsite-jit -- one-shot RTT probe program,
    # constructed once per bench run (not a steady-state call site)
    fn = jax.jit(lambda x: x + 1.0)
    x = jax.device_put(np.zeros(8, dtype=np.float32))
    np.asarray(fn(x))
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(fn(x))
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def _fused_compute_only(lanes, repeats=3):
    """On-device cost of the fused wavefront program over E
    pre-transferred lanes.
    Returns (blocking_dt, marginal_dt, pipelined_dt): blocking_dt is
    the classic per-call median (includes one dispatch round trip --
    through the axon tunnel that is ~70ms of pure latency);
    marginal_dt chains R executions inside ONE dispatch (each feeding a
    data-dependent no-op perturbation to the next, so XLA cannot elide
    them) and takes (t(R) - t(1)) / (R - 1) -- the true steady-state
    per-execution compute, what a pipelined or local-attached
    deployment pays; pipelined_dt is the median per-round cost of a
    depth-R burst of full dispatches (transfer + execute + fetch,
    fetches deferred) -- it still includes one un-overlapped round trip
    amortized over the burst, so it upper-bounds the streaming cost."""
    import functools

    import jax
    import numpy as np
    from nomad_tpu.solver.binpack import (
        _solve_wave_block_impl, _solve_wave_compact_impl,
        _wave_block_enabled, _wave_p_bucket, wavefront_compact_host)

    if not all(lane.ptab is None and lane.wavefront_ok()
               for lane in lanes):
        return None, None, None  # ineligible lane shape: clean skip
    if lanes[0].const.spread_vidx.shape[0]:
        return None, None, None  # spread lanes carry extra tables
    B = lanes[0].wavefront_B()
    p_pad = _wave_p_bucket(max(
        lane.batch.ask_cpu.shape[0] for lane in lanes))
    packs = [wavefront_compact_host(l.const, l.init, l.batch,
                                    l.dtype_name, p_pad=p_pad, B=B)
             for l in lanes]
    compact = np.stack([p[0] for p in packs])
    scal_f = np.stack([p[1] for p in packs])
    scal_i = np.stack([p[2] for p in packs])
    pen = np.stack([p[3] for p in packs])
    # mirror the production kernel choice (solve_lane_wave's gate): the
    # run-block kernel on penalty-free no-spread lanes, else the
    # per-placement compact scan
    use_block = _wave_block_enabled() and bool((pen < 0).all())
    impl = (_solve_wave_block_impl if use_block
            else functools.partial(_solve_wave_compact_impl, sp=None))
    inner = jax.vmap(functools.partial(
        impl, B=B, spread_alg=lanes[0].spread_alg,
        dtype_name=lanes[0].dtype_name))
    # nomadlint: waive=no-callsite-jit -- one-shot bench kernel for this
    # run's fixed shapes; constructed once, timed across its warm calls
    fn = jax.jit(inner)
    dev = jax.device_put((compact, scal_f, scal_i, pen))
    out = fn(*dev)
    out[0].block_until_ready()              # compile + settle
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*dev)
        out[0].block_until_ready()
        times.append(time.perf_counter() - t0)
    blocking_dt = statistics.median(times)

    # marginal: chain R kernel executions inside one dispatch, linked
    # by a scores.sum() * 1e-12 input perturbation -- a real data
    # dependency, so the compiler runs every execution. The perturbation
    # can flip exact-zero columns (affinity, pos) in later iterations,
    # so chained results are NOT parity-grade; the op graph and
    # therefore the timing are identical, which is all this probe uses.
    import jax.numpy as jnp

    def chained(R):
        def run(cm, sf, si, pn):
            def once(x, _):
                ch, sc, ny = inner(cm + x * 1e-12, sf, si, pn)
                # finite fold: padded/unyielded steps emit -inf scores
                s = jnp.where(jnp.isfinite(sc), sc, 0.0).sum()
                return s, None
            last, _ = jax.lax.scan(once, jnp.float32(0), None, length=R)
            return last
        # nomadlint: waive=no-callsite-jit -- one-shot streaming-bench
        # program, built once per (R, shapes) measurement
        return jax.jit(run)

    # pipelined dispatch: R rounds of device_put + execute + fetch
    # submitted back-to-back (fetches deferred), the shape of a
    # production server streaming barrier generations. The dispatch
    # round trip overlaps across rounds, so per-round cost approaches
    # transfer + execute + fetch instead of RTT + everything.
    pipelined_dt = None
    try:
        R = 6
        copies = [tuple(np.array(a, copy=True)
                        for a in (compact, scal_f, scal_i, pen))
                  for _ in range(R)]
        bursts = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            outs = [fn(*jax.device_put(cp)) for cp in copies]
            for o in outs:
                np.asarray(o[0])
            bursts.append((time.perf_counter() - t0) / R)
        pipelined_dt = statistics.median(bursts)
    except Exception as e:  # noqa: BLE001 -- keep the other numbers
        log(f"bench: pipelined dispatch probe failed: {e!r}")

    marginal_dt = None
    try:
        # a 32-exec delta: tunnel-latency jitter (a few ms) lands on
        # the difference, so the wider the chain the tighter the
        # per-exec figure
        f1, f33 = chained(1), chained(33)
        np.asarray(f1(*dev)), np.asarray(f33(*dev))    # compile both
        t1s, t33s = [], []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(f1(*dev))
            t1s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            np.asarray(f33(*dev))
            t33s.append(time.perf_counter() - t0)
        marginal_dt = max(
            (statistics.median(t33s) - statistics.median(t1s)) / 32,
            1e-9)
    except Exception as e:  # noqa: BLE001 -- keep the blocking number
        log(f"bench: chained compute probe failed: {e!r}")
    return blocking_dt, marginal_dt, pipelined_dt


def time_streaming_solver(h, nodes, e_evals, per_eval, depth, rounds=6):
    """Steady-state STREAMING dispatch through the production fused path
    (solver/batch.py fuse_and_solve -> device-resident const cache,
    solver/constcache.py): the same lane batch dispatched ``rounds``
    times, first strictly sequentially (the blocking baseline), then
    with ``depth`` dispatches in flight -- the shape a pipelined
    SolveBarrier (NOMAD_TPU_DISPATCH_DEPTH > 1) drives in production,
    where round trips and host packing overlap device compute.

    Also measures the transfer cut: host->device bytes of the COLD
    first dispatch (const cache empty) vs a WARM dispatch (tables
    resident), read from the nomad.solver.dispatch_bytes counters the
    dispatch layer maintains. Returns a dict or None."""
    import threading

    from nomad_tpu.server.telemetry import metrics
    from nomad_tpu.solver import constcache
    from nomad_tpu.solver.batch import fuse_and_solve

    lanes = pack_fused_lanes(h, nodes, e_evals, per_eval,
                             tag="stream-bench")
    if lanes is None:
        return None

    def bytes_total():
        return metrics.snapshot()["counters"].get(
            "nomad.solver.dispatch_bytes_total", 0)

    constcache.invalidate_all()           # honest cold measurement
    b0 = bytes_total()
    ref = fuse_and_solve(lanes)           # cold: compile + full upload
    cold_bytes = bytes_total() - b0
    b0 = bytes_total()
    fuse_and_solve(lanes)                 # warm: const tables resident
    warm_bytes = bytes_total() - b0
    placed = sum(int((res[0] >= 0).sum()) for res in ref)

    # blocking baseline: one dispatch fully fetched before the next
    t0 = time.perf_counter()
    for _ in range(rounds):
        fuse_and_solve(lanes)
    sync_dt = (time.perf_counter() - t0) / rounds

    # pipelined: `depth` submitters keep up to depth dispatches in
    # flight (each worker's fetch overlaps the others' transfers and
    # device execution -- what the async SolveBarrier does with real
    # eval generations)
    n_rounds = rounds * max(depth, 1)   # longer window: steadier number
    todo = list(range(n_rounds))
    lock = threading.Lock()
    mism = [0]

    def pull():
        while True:
            with lock:
                if not todo:
                    return
                todo.pop()
            out = fuse_and_solve(lanes)
            if any((a[0] != b[0]).any() for a, b in zip(out, ref)):
                with lock:
                    mism[0] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=pull, daemon=True)
               for _ in range(depth)]
    for t in threads:
        t.start()
    for t in threads:
        # bounded join (nomadlint join-with-timeout): a wedged solver
        # pull must not hang the bench invisibly
        while t.is_alive():
            t.join(timeout=30.0)
    pipe_dt = (time.perf_counter() - t0) / max(n_rounds, 1)

    snap = metrics.snapshot()["counters"]
    hits = snap.get("nomad.solver.const_cache_hit", 0)
    misses = snap.get("nomad.solver.const_cache_miss", 0)
    return {
        "placed": placed,
        "depth": depth,
        "sync_dt": sync_dt,
        "pipe_dt": pipe_dt,
        "cold_bytes": cold_bytes,
        "warm_bytes": warm_bytes,
        "mismatch": mism[0],
        "const_cache_hit_rate": round(hits / max(hits + misses, 1), 4),
    }


def time_pack_tax(h, nodes, n_placements, repeats=3):
    """Host-side packing tax (ISSUE 4): cold service.pack (every pack
    cache dropped -- node matrix, feasibility/spread/affinity memos,
    usage base) vs warm (snapshot caches resident) at the headline
    shape, plus the kill-switch parity gate: NOMAD_TPU_PACK_CACHE=0
    must produce identical placements. Returns a dict or None."""
    import numpy as np

    from nomad_tpu import mock
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.reconcile import AllocPlaceResult
    from nomad_tpu.solver.service import TpuPlacementService, dispatch_lane
    from nomad_tpu.structs import Plan
    from nomad_tpu.tensor import pack as tpack

    snap = h.state.snapshot()

    def one_pack(tag):
        job = mock.job(id=f"packbench-{tag}")
        job.task_groups[0].count = n_placements
        tg = job.task_groups[0]
        plan = Plan(eval_id=f"packbench-eval-{tag}", priority=50, job=job)
        ctx = EvalContext(snap, plan)
        places = [AllocPlaceResult(name=f"{job.id}.{tg.name}[{k}]",
                                   task_group=tg)
                  for k in range(n_placements)]
        svc = TpuPlacementService(ctx, job, batch_mode=False,
                                  spread_alg=False)
        t0 = time.perf_counter()
        lane = svc.pack(tg, places, nodes)
        return time.perf_counter() - t0, lane

    tpack.invalidate_pack_caches("bench cold measurement")
    cold_dt, lane = one_pack("cold")
    if lane is None:
        return None
    warm_dt = None
    for r in range(repeats):
        dt, lane = one_pack("warm")     # same eval id: identical work
        warm_dt = dt if warm_dt is None else min(warm_dt, dt)

    # parity: the cached lane vs a NOMAD_TPU_PACK_CACHE=0 repack of the
    # SAME eval must place identically
    prev = os.environ.get("NOMAD_TPU_PACK_CACHE")
    os.environ["NOMAD_TPU_PACK_CACHE"] = "0"
    try:
        _, lane_off = one_pack("warm")
    finally:
        if prev is None:
            os.environ.pop("NOMAD_TPU_PACK_CACHE", None)
        else:
            os.environ["NOMAD_TPU_PACK_CACHE"] = prev
    on = dispatch_lane(lane)
    off = dispatch_lane(lane_off)
    mismatch = int((np.asarray(on[0]) != np.asarray(off[0])).sum())
    return {
        "cold_ms": cold_dt * 1e3,
        "warm_ms": warm_dt * 1e3,
        "cut": (cold_dt / warm_dt) if warm_dt else 0.0,
        "mismatch": mismatch,
    }


def time_scale_northstar(mismatch):
    """BENCH_SCALE_ALLOCS (default ~2.05M) live allocations through the
    full batched pipeline via benchkit.run_scale_northstar; skipped on
    BENCH_SKIP_SCALE=1 or an earlier parity failure (a scale number on
    top of a broken round would be noise). Returns the result dict or
    None."""
    if mismatch or os.environ.get("BENCH_SKIP_SCALE", "") == "1":
        return None
    from nomad_tpu.benchkit import run_scale_northstar

    target = int(os.environ.get("BENCH_SCALE_ALLOCS", "2048000"))
    e_evals = int(os.environ.get("BENCH_FUSED_EVALS", "32"))
    try:
        out = run_scale_northstar(
            target, n_nodes=N_NODES, e_evals=e_evals,
            per_eval=N_PLACEMENTS, log=log)
    except Exception as e:  # noqa: BLE001 -- report the rest anyway
        log(f"bench: north-star scale run failed: {e!r}")
        return None
    log(f"bench: north-star scale {out['allocs']} live allocs in "
        f"{out['wall_s']:.1f}s ({out['placements_per_sec']:.0f} "
        f"placements/s, rss {out['rss_mb']:.0f}MB"
        f"{', TRUNCATED' if out['truncated'] else ''})")
    return out


def time_scale_churn(mismatch):
    """Sustained-churn north star (ISSUE 6): hold BENCH_CHURN_LIVE live
    allocations (default ~2.05M) while absorbing arrivals, completions
    and node flaps at steady state via benchkit.run_scale_churn --
    p50/p99 submit->commit latency, per-round RSS (bounded, not
    monotonic), and the incremental-memo fold parity gate. Skipped on
    BENCH_SKIP_CHURN=1 or an earlier parity failure. Returns the result
    dict or None."""
    if mismatch or os.environ.get("BENCH_SKIP_CHURN", "") == "1":
        return None
    from nomad_tpu.benchkit import run_scale_churn

    target = int(os.environ.get("BENCH_CHURN_LIVE", "2048000"))
    rounds = int(os.environ.get("BENCH_CHURN_ROUNDS", "6"))
    e_evals = int(os.environ.get("BENCH_FUSED_EVALS", "32"))
    try:
        out = run_scale_churn(
            target, n_nodes=N_NODES, e_evals=e_evals,
            per_eval=N_PLACEMENTS, rounds=rounds, log=log)
    except Exception as e:  # noqa: BLE001 -- report the rest anyway
        log(f"bench: sustained-churn run failed: {e!r}")
        return None
    log(f"bench: sustained churn held {out['live_allocs']} live over "
        f"{out['rounds']} rounds ({out['arrivals']} arrivals, "
        f"{out['completions']} completions, {out['flaps']} flaps); "
        f"submit->commit p50 {out['submit_commit_p50_ms']:.0f}ms / "
        f"p99 {out['submit_commit_p99_ms']:.0f}ms, rss growth "
        f"{out['rss_growth_mb']:+.0f}MB, "
        f"parity_mismatch={out['parity_mismatch']}"
        f"{', TRUNCATED' if out['truncated'] else ''}")
    log(f"bench: churn delta stream "
        f"{'ON' if out['delta_stream_enabled'] else 'OFF'}: "
        f"{out['delta_promotions']} promotions / "
        f"{out['delta_reuses']} reuses / "
        f"{out['delta_fallbacks']} fallbacks, "
        f"{out['delta_bytes_per_dispatch']:.0f}B delta + "
        f"{out['shipped_bytes_per_dispatch']:.0f}B shipped per "
        f"dispatch, ledger_parity={out['xfer_ledger_parity']}")
    return out


def time_worker_scaling(mismatch):
    """Crash-safe N-worker control plane scaling (ISSUE 16): e2e
    placements/s through the supervised PLAIN worker pool for each
    size in BENCH_WSCALE_POOLS (default 1,2,4,8) at fold parity 0 via
    benchkit.run_worker_scaling -- the proof number for ROADMAP 2a's
    multi-worker scheduling. Skipped on BENCH_SKIP_WORKER_SCALING=1 or
    an earlier parity failure. Returns the result dict or None."""
    if mismatch or os.environ.get("BENCH_SKIP_WORKER_SCALING",
                                  "") == "1":
        return None
    from nomad_tpu.benchkit import run_worker_scaling

    pools = tuple(
        int(s) for s in os.environ.get(
            "BENCH_WSCALE_POOLS", "1,2,4,8").split(",") if s.strip())
    n_nodes = int(os.environ.get("BENCH_WSCALE_NODES", "2000"))
    jobs = int(os.environ.get("BENCH_WSCALE_JOBS", "16"))
    per_eval = int(os.environ.get("BENCH_WSCALE_PER_EVAL", "250"))
    try:
        out = run_worker_scaling(
            pool_sizes=pools, n_nodes=n_nodes, jobs=jobs,
            per_eval=per_eval, log=log)
    except Exception as e:  # noqa: BLE001 -- report the rest anyway
        log(f"bench: worker-scaling run failed: {e!r}")
        return None
    summary = ", ".join(
        f"N={n}: {v:.0f}/s"
        for n, v in sorted(out["placements_per_sec"].items()))
    log(f"bench: worker scaling ({out['placed_per_size']} placements "
        f"per size) {summary}; best vs 1 worker "
        f"{out['speedup_best_vs_1']:.2f}x, "
        f"parity_mismatch={out['parity_mismatch']}"
        f"{', TRUNCATED' if out['truncated'] else ''}")
    return out


def time_worker_scaling_ab(mismatch):
    """NOMAD_TPU_NATIVE_CP=0 leg of the worker-scaling readout
    (ISSUE 17): the same e2e pool harness with the native control
    plane killed, at reduced pool sizes (BENCH_WSCALE_AB_POOLS,
    default "1,4") -- the A/B showing what the native hot paths buy
    the N-worker pool. Skipped on BENCH_SKIP_WORKER_SCALING=1 /
    BENCH_SKIP_WSCALE_AB=1 or an earlier parity failure."""
    if mismatch or os.environ.get("BENCH_SKIP_WORKER_SCALING",
                                  "") == "1" \
            or os.environ.get("BENCH_SKIP_WSCALE_AB", "") == "1":
        return None
    from nomad_tpu.benchkit import run_worker_scaling

    pools = tuple(
        int(s) for s in os.environ.get(
            "BENCH_WSCALE_AB_POOLS", "1,4").split(",") if s.strip())
    n_nodes = int(os.environ.get("BENCH_WSCALE_NODES", "2000"))
    jobs = int(os.environ.get("BENCH_WSCALE_JOBS", "16"))
    per_eval = int(os.environ.get("BENCH_WSCALE_PER_EVAL", "250"))
    prev = os.environ.get("NOMAD_TPU_NATIVE_CP")
    os.environ["NOMAD_TPU_NATIVE_CP"] = "0"
    try:
        out = run_worker_scaling(
            pool_sizes=pools, n_nodes=n_nodes, jobs=jobs,
            per_eval=per_eval, log=log)
    except Exception as e:  # noqa: BLE001 -- report the rest anyway
        log(f"bench: worker-scaling A/B (native CP off) failed: {e!r}")
        return None
    finally:
        if prev is None:
            os.environ.pop("NOMAD_TPU_NATIVE_CP", None)
        else:
            os.environ["NOMAD_TPU_NATIVE_CP"] = prev
    summary = ", ".join(
        f"N={n}: {v:.0f}/s"
        for n, v in sorted(out["placements_per_sec"].items()))
    log(f"bench: worker scaling A/B (NOMAD_TPU_NATIVE_CP=0) {summary}, "
        f"parity_mismatch={out['parity_mismatch']}"
        f"{', TRUNCATED' if out['truncated'] else ''}")
    return out


def time_eval_fixed(h, job, nodes, repeats=40):
    """Per-eval FIXED-cost microbench (ISSUE 17): the control-plane
    work an eval pays no matter how fast the solver is -- advance and
    build a state snapshot, verify a plan's asks against the columnar
    fold state, commit and materialize the result -- with the solver
    entirely out of the loop (the plan's allocs are prebuilt). The
    table is seeded to BENCH_EVAL_FIXED_SEED live allocs first: the
    wholesale snapshot copy this microbench exists to expose is
    O(live allocs), invisible on a near-empty table. Both arms run in
    the SAME process/world -- ``eval_fixed_ms`` with the native control
    plane, ``eval_fixed_nocp_ms`` with NOMAD_TPU_NATIVE_CP=0 -- so the
    step is read within-round, immune to cross-round box noise. Each
    iteration's commit advances the alloc journal, so the NEXT
    iteration's snapshot exercises the real delta-advance path.
    Returns the result dict or None; BENCH_SKIP_EVAL_FIXED=1 skips."""
    if os.environ.get("BENCH_SKIP_EVAL_FIXED", "") == "1":
        return None
    from nomad_tpu import mock
    from nomad_tpu.server.plan_apply import Planner

    from nomad_tpu.structs import Plan

    per_plan = int(os.environ.get("BENCH_EVAL_FIXED_ALLOCS", "50"))
    seed = int(os.environ.get("BENCH_EVAL_FIXED_SEED", "50000"))
    live = len(h.state.snapshot()._allocs)
    if live < seed:
        batch = []
        for i in range(seed - live):
            a = mock.alloc_for(job, nodes[i % len(nodes)], 0)
            tr = a.allocated_resources.tasks["web"]
            tr.cpu_shares = 1
            tr.memory_mb = 1
            batch.append(a)
            if len(batch) >= 5000:
                h.state.upsert_allocs(batch)
                batch = []
        if batch:
            h.state.upsert_allocs(batch)

    def one_arm(arm, native_cp):
        prev = os.environ.get("NOMAD_TPU_NATIVE_CP")
        if native_cp:
            os.environ.pop("NOMAD_TPU_NATIVE_CP", None)
        else:
            os.environ["NOMAD_TPU_NATIVE_CP"] = "0"
        planner = Planner(h.state)
        times = []
        rejected = 0
        try:
            for r in range(repeats):
                # prebuild outside the timed window: alloc CONSTRUCTION
                # is the scheduler's cost, not the control plane's
                allocs = []
                for i in range(per_plan):
                    a = mock.alloc_for(
                        job, nodes[(r * per_plan + i) % len(nodes)], 0)
                    tr = a.allocated_resources.tasks["web"]
                    tr.cpu_shares = 1
                    tr.memory_mb = 1
                    allocs.append(a)
                t0 = time.perf_counter()
                plan = Plan(eval_id=f"bench-fixed-{arm}{r:026d}",
                            priority=50, job=job)
                for a in allocs:
                    plan.append_alloc(a)
                result = planner.apply(plan)
                times.append(time.perf_counter() - t0)
                rejected += len(result.rejected_nodes)
        finally:
            planner.shutdown()
            if prev is None:
                os.environ.pop("NOMAD_TPU_NATIVE_CP", None)
            else:
                os.environ["NOMAD_TPU_NATIVE_CP"] = prev
        return statistics.median(times), rejected

    p50, rejected = one_arm("a", True)
    p50_nocp, rejected_nocp = one_arm("b", False)
    cut = p50_nocp / p50 if p50 else 0.0
    log(f"bench: eval fixed cost {p50 * 1e3:.2f}ms p50 native vs "
        f"{p50_nocp * 1e3:.2f}ms NOMAD_TPU_NATIVE_CP=0 ({cut:.2f}x) "
        f"over {repeats} evals x {per_plan} asks on a "
        f"{max(live, seed)}-alloc table "
        f"(rejected_nodes={rejected + rejected_nocp})")
    return {"eval_fixed_ms": round(p50 * 1e3, 3),
            "eval_fixed_nocp_ms": round(p50_nocp * 1e3, 3),
            "per_plan": per_plan, "seed": max(live, seed),
            "rejected": rejected + rejected_nocp}


def solve_once(h, job, nodes, n_placements):
    """One full TPU-path eval: host-side packing + one dense solver dispatch
    + the single device->host result fetch -- the complete per-eval latency
    path a production worker pays."""
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.reconcile import AllocPlaceResult
    from nomad_tpu.solver.service import TpuPlacementService
    from nomad_tpu.structs import Plan

    plan = Plan(eval_id="bench-eval-0000000000000001", priority=50, job=job)
    snap = h.state.snapshot()
    ctx = EvalContext(snap, plan)
    tg = job.task_groups[0]
    places = [AllocPlaceResult(name=f"{job.id}.{tg.name}[{i}]", task_group=tg)
              for i in range(n_placements)]
    service = TpuPlacementService(ctx, job, batch_mode=False,
                                  spread_alg=False)
    t0 = time.perf_counter()
    solved = service.solve(tg, places, nodes)
    dt = time.perf_counter() - t0
    placed = {sp.place.name: (sp.node.id if sp.node is not None else None)
              for sp in solved}
    return dt, placed


def main_tier(platform: str, tier: int):
    """BENCH_TIER mode: run the BASELINE tier shape end-to-end (full
    scheduler pipeline via the harness) host vs tpu with gating parity --
    the same nomad_tpu/benchkit generators tests/test_parity_scale.py
    gates at CI scale."""
    from nomad_tpu.benchkit import run_tier_placements

    n_nodes = N_NODES
    count = N_PLACEMENTS
    if tier == 1:
        # BASELINE tier 1 is a fixed dev-cluster shape: 3-TG service
        # job on 5 nodes (the TG counts come from the job itself)
        n_nodes, count = 5, 3
    t0 = time.time()
    host, host_ev = run_tier_placements(tier, n_nodes, count, seed=1,
                                        alg="binpack", with_evictions=True)
    host_dt = time.time() - t0
    log(f"bench[tier{tier}]: host {len(host)} placements in {host_dt:.2f}s")
    run_tier_placements(tier, n_nodes, count, seed=1, alg="tpu-binpack")
    t0 = time.time()
    tpu, tpu_ev = run_tier_placements(tier, n_nodes, count, seed=1,
                                      alg="tpu-binpack",
                                      with_evictions=True)
    tpu_dt = time.time() - t0
    log(f"bench[tier{tier}]: tpu {len(tpu)} placements in {tpu_dt:.2f}s")
    # bidirectional placement parity + eviction-set parity (tier 5 exists
    # to exercise preemption)
    keys = set(host) | set(tpu)
    mismatch = sum(1 for k in keys if host.get(k) != tpu.get(k))
    mismatch += sum(1 for k in keys if host_ev.get(k) != tpu_ev.get(k))
    if tier == 2:
        # BASELINE tier 2 is "binpack vs spread": gate the worst-fit
        # scheduler-algorithm pair too
        host_s, host_s_ev = run_tier_placements(
            tier, n_nodes, count, seed=2, alg="spread",
            with_evictions=True)
        tpu_s, tpu_s_ev = run_tier_placements(
            tier, n_nodes, count, seed=2, alg="tpu-spread",
            with_evictions=True)
        keys_s = set(host_s) | set(tpu_s)
        sp_mism = sum(1 for k in keys_s
                      if host_s.get(k) != tpu_s.get(k))
        sp_mism += sum(1 for k in keys_s
                       if host_s_ev.get(k) != tpu_s_ev.get(k))
        log(f"bench[tier2]: spread-algorithm variant "
            f"{len(tpu_s)} placements, parity_mismatch={sp_mism}")
        mismatch += sp_mism
    placements_per_sec = len(tpu) / tpu_dt if tpu_dt else 0.0
    out = {
        "metric": f"tier{tier}_eval_placements_per_sec",
        "value": round(placements_per_sec, 2),
        "unit": (f"placements/s ({n_nodes} nodes end-to-end eval, "
                 f"platform={platform}, parity_mismatch={mismatch})"),
        "vs_baseline": round(host_dt / tpu_dt, 2) if tpu_dt else 0.0,
        "platform": platform,
        "parity_mismatch": mismatch,
    }
    # explicit degraded verdict + breaker/dispatch state: a wedged
    # tunnel or tripped breaker must never read as a chip result
    from nomad_tpu.benchkit import (
        artifact_stamp, delta_stream_stamp, dispatch_health_stamp,
        jitcheck_stamp, shardcheck_stamp, statecheck_stamp,
        xferobs_stamp)
    out.update(dispatch_health_stamp(platform))
    out.update(jitcheck_stamp())
    out.update(statecheck_stamp())
    out.update(shardcheck_stamp())
    # transfer ledger + tunnel-model fields (ISSUE 13): byte parity and
    # per-dispatch payload are gated per round like the sanitizers
    out.update(xferobs_stamp())
    # delta streaming (ISSUE 20): chain promotions vs wholesale
    # fallbacks + cumulative delta payload, regress-gated
    out.update(delta_stream_stamp())
    # ISSUE 19: mesh-route fields ride the tier tails too (self-guarded
    # on device count + the NOMAD_TPU_MESH knob; parity is gating)
    if os.environ.get("BENCH_SKIP_MESH", "") != "1":
        try:
            mesh_leg = time_mesh_leg()
        except Exception as e:  # noqa: BLE001 -- report the rest anyway
            log(f"bench[tier{tier}]: mesh leg failed: {e!r}")
            mesh_leg = None
        if mesh_leg is not None:
            mismatch += mesh_leg["mesh_parity_mismatch"]
            out["parity_mismatch"] = mismatch
            out.update(mesh_leg)
    out.update(artifact_stamp())
    out["trace_artifact"] = _export_trace_artifact(
        default=f"BENCH_trace_tier{tier}.json")
    print(json.dumps(out), flush=True)
    sys.exit(1 if mismatch else 0)


def _export_trace_artifact(default: str):
    """Ship the eval-span flight recorder next to the BENCH_*.json
    line (Perfetto/chrome://tracing JSON; BENCH_TRACE_OUT overrides
    the path, empty disables)."""
    path = os.environ.get("BENCH_TRACE_OUT", default)
    if not path:
        return None
    from nomad_tpu.benchkit import export_chrome_trace
    written = export_chrome_trace(path)
    if written:
        log(f"bench: eval trace artifact -> {written}")
    return written


def main():
    platform = pick_platform()
    tier = os.environ.get("BENCH_TIER", "").strip()
    if tier:
        main_tier(platform, int(tier))
        return
    t0 = time.time()
    h, job, nodes = build_world()
    log(f"bench: world built ({N_NODES} nodes) in {time.time() - t0:.1f}s")

    # --- host oracle: full workload, equal work to the solver path.
    # min over N_ORACLE_RUNS filters one-off GC/cold-cache noise from the
    # baseline side the same way median-of-repeats does for the solver.
    oracle_dt = None
    for _ in range(N_ORACLE_RUNS):
        run_dt, oracle_placed = time_host_inner_loop(
            h, job, nodes, N_PLACEMENTS)
        oracle_dt = run_dt if oracle_dt is None else min(oracle_dt, run_dt)
    n_oracle_ok = sum(1 for v in oracle_placed.values() if v is not None)
    log(f"bench: oracle placed {n_oracle_ok}/{N_PLACEMENTS} "
        f"in {oracle_dt:.3f}s ({oracle_dt / max(n_oracle_ok, 1) * 1e3:.3f} "
        f"ms/placement, min of {N_ORACLE_RUNS})")

    # --- compiled-host baseline (C++): parity-gated against the oracle
    native_dt, native_placed = time_native_oracle(
        h, job, nodes, N_PLACEMENTS)
    native_mismatch = 0
    if native_dt is not None:
        native_mismatch = sum(
            1 for k, v in oracle_placed.items()
            if native_placed.get(k) != v)
        log(f"bench: native C++ baseline {native_dt * 1e3:.3f} ms/eval "
            f"({native_dt / max(n_oracle_ok, 1) * 1e6:.2f} us/placement, "
            f"parity_mismatch={native_mismatch})")
    else:
        log("bench: native C++ baseline unavailable (build failed)")

    # --- TPU solver: warmup (compile) then repeated timed evals for p50
    warm_dt, tpu_placed = solve_once(h, job, nodes, N_PLACEMENTS)
    log(f"bench: solver warmup (incl. compile) {warm_dt:.3f}s")
    rtt = None
    try:
        rtt = _tunnel_rtt()
        log(f"bench: dispatch round-trip (trivial program) "
            f"{rtt * 1e3:.1f}ms -- every blocking per-call timing below "
            f"includes this as pure host<->device latency")
    except Exception as e:  # noqa: BLE001 -- diagnostic only
        log(f"bench: rtt probe failed: {e!r}")
    times = []
    for r in range(N_REPEATS):
        dt, rep_placed = solve_once(h, job, nodes, N_PLACEMENTS)
        times.append(dt)
        if rep_placed != tpu_placed:
            log("bench: FATAL: solver output unstable across repeats")
            _emit(platform, 0.0, -1, oracle_dt)
            sys.exit(1)
    p50 = statistics.median(times)
    n_tpu_ok = sum(1 for v in tpu_placed.values() if v is not None)
    log(f"bench: solver p50 {p50 * 1e3:.1f}ms over {N_REPEATS} evals "
        f"(placed {n_tpu_ok}/{N_PLACEMENTS})")

    # --- GATING parity over the FULL workload: same keys, same nodes
    mismatch = sum(
        1 for k, v in oracle_placed.items() if tpu_placed.get(k) != v)
    mismatch += sum(1 for k in tpu_placed if k not in oracle_placed)
    if mismatch:
        for k, v in list(oracle_placed.items()):
            tv = tpu_placed.get(k)
            if tv != v:
                log(f"bench: PARITY MISMATCH {k}: oracle={v} tpu={tv}")
                break
    mismatch += native_mismatch

    # --- host packing tax: cold vs warm service.pack at the headline
    #     shape (the snapshot-scoped pack caches' claim), parity-gated
    #     against the NOMAD_TPU_PACK_CACHE=0 kill switch
    pack_tax = None
    if os.environ.get("BENCH_SKIP_PACK", "") != "1":
        try:
            pack_tax = time_pack_tax(h, nodes, N_PLACEMENTS)
        except Exception as e:  # noqa: BLE001 -- report the rest anyway
            log(f"bench: pack tax probe failed: {e!r}")
        if pack_tax is not None:
            mismatch += pack_tax["mismatch"]
            log(f"bench: host pack cold {pack_tax['cold_ms']:.1f}ms -> "
                f"warm {pack_tax['warm_ms']:.1f}ms "
                f"({pack_tax['cut']:.1f}x cut, "
                f"killswitch_mismatch={pack_tax['mismatch']})")

    # --- fused solver throughput: E evals, one dispatch (the headline)
    fused = None
    if not mismatch and os.environ.get("BENCH_SKIP_FUSED", "") != "1":
        e_evals = int(os.environ.get("BENCH_FUSED_EVALS", "32"))
        try:
            fdt, fplaced, fmis, fcompute = time_fused_solver(
                h, nodes, e_evals, N_PLACEMENTS)
            if fdt is not None:
                mismatch += fmis
                fused = (fdt, e_evals, fplaced, fcompute)
                log(f"bench: fused solver {e_evals} evals x "
                    f"{N_PLACEMENTS} in {fdt:.3f}s ({fplaced} placed, "
                    f"{fplaced / fdt:.0f} placements/s, "
                    f"fused_mismatch={fmis})")
                if fcompute and fcompute.get("blocking"):
                    log(f"bench: fused compute-only "
                        f"{fcompute['blocking'] * 1e3:.1f}ms blocking "
                        f"({fplaced / fcompute['blocking']:.0f} "
                        f"placements/s incl. 1 dispatch RTT)")
                if fcompute and fcompute.get("marginal"):
                    log(f"bench: fused compute MARGINAL "
                        f"{fcompute['marginal'] * 1e3:.2f}ms/exec "
                        f"({fplaced / fcompute['marginal']:.0f} "
                        f"placements/s steady-state on-chip)")
                if fcompute and fcompute.get("pipelined"):
                    log(f"bench: fused PIPELINED dispatch "
                        f"{fcompute['pipelined'] * 1e3:.1f}ms/round "
                        f"({fplaced / fcompute['pipelined']:.0f} "
                        f"placements/s, depth-6 transfer+exec+fetch)")
        except Exception as e:  # noqa: BLE001 -- report the rest anyway
            log(f"bench: fused solver failed: {e!r}")

    # --- end-to-end batched pipeline through BatchWorker (control plane
    #     included: broker, schedulers, plan applier, state store), at
    #     two shapes: the historical 16-way split of N_PLACEMENTS, and
    #     the HEADLINE shape (E full-size evals -- the same total work as
    #     the fused measurement, so batched_full vs fused is an
    #     apples-to-apples control-plane-tax readout)
    def run_batched(tag, e_evals, per_eval):
        # opt-in best-of-N (BENCH_BATCHED_BEST_OF): the pipeline is
        # multi-threaded, so single draws on a contended/1-core box swing
        # 2-4x on scheduler luck (r07/r08 notes); max throughput over a
        # couple of complete rounds de-noises the readout. Default stays
        # 1 -- extra rounds also inflate the cumulative xfer ledger's
        # dispatch mix, so stamped rounds keep single-draw parity with
        # prior artifacts unless the operator opts in.
        best_of = max(1, int(os.environ.get("BENCH_BATCHED_BEST_OF",
                                            "1")))
        try:
            bdt, bevals, bplaced = time_batched_path(
                N_NODES, e_evals, per_eval)
            for _ in range(best_of - 1):
                dt2, ev2, pl2 = time_batched_path(
                    N_NODES, e_evals, per_eval)
                if dt2 > 0.0 and (bdt == 0.0 or pl2 / dt2 > bplaced / bdt):
                    bdt, bevals, bplaced = dt2, ev2, pl2
        except Exception as e:  # noqa: BLE001 -- report the rest anyway
            log(f"bench: e2e pipeline ({tag}) failed: {e!r}")
            return None
        if bdt == 0.0:
            # drain-failure sentinel: the measured round never ran
            log(f"bench: e2e pipeline ({tag}) DRAIN FAILED; "
                f"dropping metric")
            return None
        log(f"bench: e2e pipeline ({tag}) {bevals} evals x {per_eval} in "
            f"{bdt:.3f}s ({bplaced} placed, "
            f"{bplaced / bdt:.0f} placements/s)")
        if bplaced < e_evals * per_eval:
            # run_round's 600s deadline expired mid-round: a truncated
            # round must not be published as a complete measurement
            log(f"bench: e2e pipeline ({tag}) TRUNCATED "
                f"({bplaced}/{e_evals * per_eval} placed); dropping metric")
            return None
        return (bdt, bevals, bplaced)

    # --- streaming dispatch: sync vs depth-D pipelined, const cache warm
    streaming = None
    if not mismatch and os.environ.get("BENCH_SKIP_STREAMING", "") != "1":
        depth = int(os.environ.get(
            "BENCH_STREAM_DEPTH",
            os.environ.get("NOMAD_TPU_DISPATCH_DEPTH", "4")))
        depth = max(2, depth)
        e_evals = int(os.environ.get("BENCH_FUSED_EVALS", "32"))
        try:
            streaming = time_streaming_solver(h, nodes, e_evals,
                                              N_PLACEMENTS, depth)
        except Exception as e:  # noqa: BLE001 -- report the rest anyway
            log(f"bench: streaming solver failed: {e!r}")
        if streaming is not None:
            mismatch += streaming["mismatch"]
            log(f"bench: streaming sync {streaming['sync_dt'] * 1e3:.1f}"
                f"ms/round ({streaming['placed'] / streaming['sync_dt']:.0f}"
                f" placements/s), depth-{depth} pipelined "
                f"{streaming['pipe_dt'] * 1e3:.1f}ms/round "
                f"({streaming['placed'] / streaming['pipe_dt']:.0f} "
                f"placements/s); dispatch bytes cold "
                f"{streaming['cold_bytes']} -> warm "
                f"{streaming['warm_bytes']} "
                f"(hit rate {streaming['const_cache_hit_rate']})")

    batched = None
    if not mismatch and os.environ.get("BENCH_SKIP_BATCHED", "") != "1":
        e_evals = int(os.environ.get("BENCH_BATCH_EVALS", "16"))
        batched = run_batched("split", e_evals,
                              max(1, N_PLACEMENTS // e_evals))
    batched_full = None
    if not mismatch and os.environ.get("BENCH_SKIP_BATCHED_FULL", "") != "1":
        e_evals = int(os.environ.get("BENCH_FUSED_EVALS", "32"))
        batched_full = run_batched("headline shape", e_evals, N_PLACEMENTS)

    # --- whole-queue LP tier: the same e2e pipeline with tpu-lpq
    #     selected -- evals/solve amortization + quality delta vs the
    #     greedy replay of the same queue (ISSUE 8)
    lpq = None
    if not mismatch and os.environ.get("BENCH_SKIP_LPQ", "") != "1":
        lpq_evals = int(os.environ.get("BENCH_LPQ_EVALS", "128"))
        lpq_per = int(os.environ.get("BENCH_LPQ_PER_EVAL", "8"))
        try:
            lpq = time_lpq(N_NODES, lpq_evals, lpq_per)
        except Exception as e:  # noqa: BLE001 -- report the rest anyway
            log(f"bench: lpq tier failed: {e!r}")

    # --- north-star scale: ~2M LIVE allocs through the batched pipeline
    #     (accumulating, never drained) -- the ROADMAP number measured
    #     instead of extrapolated. AllocTable preallocated, per-placement
    #     metric stubs pruned, peak RSS recorded in the artifact.
    scale = time_scale_northstar(mismatch)

    # --- sustained churn: hold the north-star live count while the
    #     pipeline absorbs arrivals/completions/flaps at steady state
    #     (the regime production traffic actually is)
    churn = time_scale_churn(mismatch)

    # --- N-worker control plane scaling: e2e placements/s through the
    #     supervised plain worker pool for N in {1,2,4,8} (ISSUE 16)
    wscale = time_worker_scaling(mismatch)

    # --- same harness, native control plane KILLED (ISSUE 17 A/B):
    #     what the GIL-free verify/fold/materialize path buys the pool
    wscale_ab = time_worker_scaling_ab(mismatch)

    # --- multi-chip mesh solve: mesh vs single-device walls + per-shard
    #     ship bytes over a node-count sweep (ISSUE 19); self-guarded on
    #     device count and the NOMAD_TPU_MESH rollback knob
    mesh_leg = None
    if os.environ.get("BENCH_SKIP_MESH", "") != "1":
        try:
            mesh_leg = time_mesh_leg()
        except Exception as e:  # noqa: BLE001 -- report the rest anyway
            log(f"bench: mesh leg failed: {e!r}")
        if mesh_leg is not None:
            mismatch += mesh_leg["mesh_parity_mismatch"]
            log(f"bench: mesh leg grid={mesh_leg['mesh_grid']} "
                f"{mesh_leg['mesh_pps']:.0f} placements/s, "
                f"shard bytes {mesh_leg['mesh_shard_bytes']}, "
                f"collective overhead "
                f"{mesh_leg['mesh_collective_ms']:.1f}ms, "
                f"parity_mismatch={mesh_leg['mesh_parity_mismatch']}")

    # --- per-eval fixed cost: snapshot+verify+commit with the solver
    #     out of the loop (ISSUE 17 headline microbench); runs LAST
    #     because it accumulates allocs into the bench world
    eval_fixed = None
    try:
        eval_fixed = time_eval_fixed(h, job, nodes)
    except Exception as e:  # noqa: BLE001 -- report the rest anyway
        log(f"bench: eval fixed-cost probe failed: {e!r}")

    _emit(platform, p50, mismatch, oracle_dt, native_dt, batched,
          n_placed=n_tpu_ok, fused=fused, batched_full=batched_full,
          rtt=rtt, streaming=streaming, pack_tax=pack_tax, scale=scale,
          churn=churn, lpq=lpq, wscale=wscale, wscale_ab=wscale_ab,
          eval_fixed=eval_fixed, mesh=mesh_leg)
    if mismatch:
        log(f"bench: FAILED parity gate: {mismatch} mismatches")
        sys.exit(1)


def _emit(platform, p50, mismatch, oracle_total, native_total=None,
          batched=None, n_placed=0, fused=None, batched_full=None,
          rtt=None, streaming=None, pack_tax=None, scale=None,
          churn=None, lpq=None, wscale=None, wscale_ab=None,
          eval_fixed=None, mesh=None):
    placements_per_sec = (n_placed / p50) if p50 > 0 else 0.0
    per_place_tpu = p50 / n_placed if n_placed else 0.0
    per_place_host = oracle_total / max(n_placed, 1)
    speedup = (per_place_host / per_place_tpu) if per_place_tpu else 0.0
    per_place_native = (native_total / max(n_placed, 1)
                        if native_total is not None else None)
    out = {
        # headline (overwritten below when the fused measurement landed):
        # single-eval latency path
        "metric": "placements_per_sec_10k_nodes",
        "value": round(placements_per_sec, 2),
        "unit": (f"placements/s ({N_NODES} nodes, {n_placed} placed, "
                 f"platform={platform}, parity_mismatch={mismatch})"),
        "vs_baseline": round(speedup, 2),
        "p50_eval_ms": round(p50 * 1e3, 2),
        "host_oracle_eval_ms": round(oracle_total * 1e3, 2),
        "vs_python_host": round(speedup, 2),
        "platform": platform,
        "parity_mismatch": mismatch,
    }
    if rtt is not None:
        out["dispatch_rtt_ms"] = round(rtt * 1e3, 2)
    if native_total is not None:
        vs_native = (per_place_native / per_place_tpu) if per_place_tpu \
            else 0.0
        out["native_host_eval_ms"] = round(native_total * 1e3, 3)
        out["vs_native_host"] = round(vs_native, 4)
        out["vs_baseline"] = round(vs_native, 4)
    if fused is not None:
        # THE HEADLINE: solver throughput with E evals per dispatch (the
        # designed TPU win -- amortize dispatch over a coalesced batch),
        # vs the compiled C++ host baseline doing the same work
        # sequentially on one core. Parity is gated per-lane. The
        # compute-only variant excludes host<->device transfer (in this
        # environment a tunnel ~1000x slower than local PCIe; a real
        # deployment's end-to-end sits near the compute number).
        fdt, fevals, fplaced, fcompute = fused
        out["metric"] = "fused_placements_per_sec_10k_nodes"
        out["value"] = round(fplaced / fdt, 2)
        out["unit"] = (f"placements/s ({fevals} evals/dispatch, "
                       f"{N_NODES} nodes, platform={platform}, "
                       f"parity_mismatch={mismatch})")
        out["fused_evals_per_dispatch"] = fevals
        out["fused_placements_per_sec"] = round(fplaced / fdt, 2)
        if per_place_native is not None and fplaced:
            out["fused_vs_native_host"] = round(
                per_place_native / (fdt / fplaced), 4)
            out["vs_baseline"] = out["fused_vs_native_host"]
        blocking = fcompute.get("blocking") if fcompute else None
        marginal = fcompute.get("marginal") if fcompute else None
        if blocking:
            out["fused_compute_ms"] = round(blocking * 1e3, 3)
            out["fused_compute_placements_per_sec"] = round(
                fplaced / blocking, 2)
            if per_place_native is not None:
                out["fused_compute_vs_native_host"] = round(
                    per_place_native / (blocking / fplaced), 4)
        pipelined = fcompute.get("pipelined") if fcompute else None
        if pipelined:
            # streaming dispatch path: transfer + execute + fetch with
            # round trips overlapped across in-flight rounds -- the
            # per-dispatch cost a production server pays once the
            # tunnel/link latency is pipelined away
            out["fused_pipelined_ms"] = round(pipelined * 1e3, 3)
            out["fused_pipelined_placements_per_sec"] = round(
                fplaced / pipelined, 2)
            if per_place_native is not None:
                out["fused_pipelined_vs_native_host"] = round(
                    per_place_native / (pipelined / fplaced), 4)
        if marginal:
            # steady-state on-chip rate (chained in-dispatch repeats):
            # the dispatch round trip -- rtt_ms, ~70ms through this
            # environment's axon tunnel, ~0 locally attached --
            # amortizes away under pipelining, so THIS is the chip's
            # real throughput and the number a production deployment
            # (local PCIe/ICI attach) sees; the blocking metrics above
            # keep the tunnel cost visible rather than hiding it.
            out["fused_compute_marginal_ms"] = round(marginal * 1e3, 3)
            out["fused_compute_marginal_placements_per_sec"] = round(
                fplaced / marginal, 2)
            if per_place_native is not None:
                out["fused_compute_marginal_vs_native_host"] = round(
                    per_place_native / (marginal / fplaced), 4)
    if streaming is not None:
        # steady-state streaming: the SAME fused workload dispatched
        # round after round with the const cache warm -- blocking
        # baseline kept alongside the depth-D pipelined number for
        # honesty, plus the per-dispatch transfer cut (cold = full
        # upload, warm = deltas only)
        placed = streaming["placed"]
        out["streaming_sync_placements_per_sec"] = round(
            placed / streaming["sync_dt"], 2) if streaming["sync_dt"] \
            else 0.0
        out["streaming_pipelined_placements_per_sec"] = round(
            placed / streaming["pipe_dt"], 2) if streaming["pipe_dt"] \
            else 0.0
        out["streaming_depth"] = streaming["depth"]
        out["dispatch_bytes_cold"] = streaming["cold_bytes"]
        out["dispatch_bytes_warm"] = streaming["warm_bytes"]
        if streaming["warm_bytes"]:
            out["dispatch_bytes_cut"] = round(
                streaming["cold_bytes"] / streaming["warm_bytes"], 2)
        out["const_cache_hit_rate"] = streaming["const_cache_hit_rate"]
        if native_total is not None and placed:
            out["streaming_pipelined_vs_native_host"] = round(
                per_place_native / (streaming["pipe_dt"] / placed), 4)
    if pack_tax is not None:
        # host packing tax, next to the transfer cut: cold = every pack
        # cache dropped, warm = snapshot caches resident; the warm cut
        # is the amortization the pack layer buys each steady-state eval
        out["pack_ms_cold"] = round(pack_tax["cold_ms"], 2)
        out["pack_ms_warm"] = round(pack_tax["warm_ms"], 2)
        out["pack_warm_cut"] = round(pack_tax["cut"], 2)
        out["pack_killswitch_mismatch"] = pack_tax["mismatch"]
    if batched is not None:
        bdt, bevals, bplaced = batched
        out["batched_evals_per_sec"] = round(bevals / bdt, 2)
        out["batched_placements_per_sec"] = round(bplaced / bdt, 2)
        if native_total is not None and bplaced:
            per_place_batched = bdt / bplaced
            out["batched_vs_native_host"] = round(
                per_place_native / per_place_batched, 4)
    if batched_full is not None:
        bdt, bevals, bplaced = batched_full
        out["batched_full_placements_per_sec"] = round(bplaced / bdt, 2)
        if native_total is not None and bplaced:
            out["batched_full_vs_native_host"] = round(
                per_place_native / (bdt / bplaced), 4)
        stats = getattr(time_batched_path, "last_planner_stats", None)
        if stats is not None:
            # the acceptance contract: the speedup must not come from
            # the applier silently rejecting work -- rejected stays 0
            out["batched_full_planner_rejected"] = stats["rejected"]
            out["plan_group_commits"] = stats["group_commits"]
        if fused is not None and fused[0] and bplaced:
            # control-plane tax: fused throughput / e2e throughput at the
            # SAME workload shape (1.0 = no tax)
            out["control_plane_tax"] = round(
                (fused[2] / fused[0]) / (bplaced / bdt), 2)
    if lpq is not None:
        # whole-queue LP tier: dispatch amortization (evals per joint
        # solve), throughput, and quality vs a greedy replay of the
        # SAME queue -- repair_rate is the rounding-health signal
        # (docs/OPERATIONS.md "LP queue tier")
        out.update(lpq)
    if scale is not None:
        # north-star scale: live-alloc count actually placed, steady
        # throughput across the accumulating run, and the memory
        # ceiling -- a truncated run is flagged, never silently
        # published as complete
        out["scale_allocs"] = scale["allocs"]
        out["scale_placements_per_sec"] = scale["placements_per_sec"]
        out["scale_rss_mb"] = scale["rss_mb"]
        out["scale_truncated"] = scale["truncated"]
        out["scale_wall_s"] = scale["wall_s"]
    if churn is not None:
        # sustained churn: live count HELD (not accumulated), latency
        # percentiles under steady arrivals/completions/flaps, per-round
        # RSS (growth = leak signal), and the incremental-memo parity
        # gate -- parity_mismatch must be 0 for the run to count
        out["churn_live_allocs"] = churn["live_allocs"]
        out["churn_rounds"] = churn["rounds"]
        out["churn_p50_ms"] = churn["submit_commit_p50_ms"]
        out["churn_p99_ms"] = churn["submit_commit_p99_ms"]
        out["churn_rss_growth_mb"] = churn["rss_growth_mb"]
        out["churn_rss_mb_rounds"] = churn["rss_mb_rounds"]
        out["churn_flaps"] = churn["flaps"]
        out["churn_quarantine_deferrals"] = churn["quarantine_deferrals"]
        out["churn_parity_mismatch"] = churn["parity_mismatch"]
        out["churn_truncated"] = churn["truncated"]
        # delta streaming (ISSUE 20): warm steady-state payload per
        # dispatch (journal deltas scattered on device instead of
        # re-shipped tables) + fallback count; ledger parity must be 0
        out["churn_delta_stream_enabled"] = \
            churn["delta_stream_enabled"]
        out["churn_delta_promotions"] = churn["delta_promotions"]
        out["churn_delta_reuses"] = churn["delta_reuses"]
        out["churn_delta_fallbacks"] = churn["delta_fallbacks"]
        out["churn_delta_bytes_per_dispatch"] = \
            churn["delta_bytes_per_dispatch"]
        out["churn_shipped_bytes_per_dispatch"] = \
            churn["shipped_bytes_per_dispatch"]
        out["churn_xfer_ledger_parity"] = churn["xfer_ledger_parity"]
    if wscale is not None:
        # N-worker control plane scaling (ISSUE 16): e2e placements/s
        # through the supervised plain pool per size, at fold parity 0
        # -- flat per-size fields so the regress gate can trend each N
        out["worker_scaling_pools"] = wscale["pool_sizes"]
        for n, v in wscale["placements_per_sec"].items():
            out[f"worker_scaling_pps_n{n}"] = v
        out["worker_scaling_speedup"] = wscale["speedup_best_vs_1"]
        out["worker_scaling_parity_mismatch"] = \
            wscale["parity_mismatch"]
        out["worker_scaling_truncated"] = wscale["truncated"]
    if wscale_ab is not None:
        # ISSUE 17 A/B: the same pool harness with NOMAD_TPU_NATIVE_CP=0
        # -- the native-control-plane win read directly off the artifact
        for n, v in wscale_ab["placements_per_sec"].items():
            out[f"worker_scaling_pps_n{n}_nocp"] = v
        out["worker_scaling_ab_parity_mismatch"] = \
            wscale_ab["parity_mismatch"]
    if eval_fixed is not None:
        # ISSUE 17 headline: per-eval fixed cost (snapshot + plan verify
        # + commit, solver out of the loop), regress-gated lower-better
        out["eval_fixed_ms"] = eval_fixed["eval_fixed_ms"]
        out["eval_fixed_nocp_ms"] = eval_fixed["eval_fixed_nocp_ms"]
        out["eval_fixed_allocs_per_plan"] = eval_fixed["per_plan"]
        out["eval_fixed_table_allocs"] = eval_fixed["seed"]
    if mesh is not None:
        # ISSUE 19: mesh-route throughput, per-shard ship bytes and
        # collective overhead over the node-count sweep; the parity
        # field already rode into the gating mismatch upstream
        out.update(mesh)
    # a CPU-fallback / breaker-degraded artifact must never read as a
    # healthy TPU round (VERDICT r3 next-step 1, r5 weak #1): stamp the
    # explicit degraded verdict + dispatch-layer state
    from nomad_tpu.benchkit import (
        artifact_stamp, delta_stream_stamp, dispatch_health_stamp,
        jitcheck_stamp, shardcheck_stamp, statecheck_stamp,
        xferobs_stamp)
    out.update(dispatch_health_stamp(platform))
    # dispatch discipline (ISSUE 10): retraces/host syncs/x64 leaks
    # observed this run, gated by scripts/check_bench_regress.py
    out.update(jitcheck_stamp())
    out.update(statecheck_stamp())
    # sharding discipline (ISSUE 15): spec drift / implicit transfers /
    # collective excess observed this run, zero-tolerance gated
    out.update(shardcheck_stamp())
    # transfer ledger + tunnel-model fields (ISSUE 13): payload bytes
    # decomposed per dispatch, byte parity vs dispatch_bytes_total
    # (must be 0), and the live rtt/bandwidth fit -- the r05 manual
    # tunnel diagnosis as a standing, regress-gated readout
    out.update(xferobs_stamp())
    # delta streaming (ISSUE 20): version-chain promotions vs wholesale
    # fallbacks + cumulative delta payload, regress-gated
    out.update(delta_stream_stamp())
    # quality scoreboard + per-stage saturation from the headline e2e
    # server (ISSUE 7): quality_fragmentation / quality_drift /
    # stage_busy_pct_* so solver changes are judged on placement
    # QUALITY, not just throughput
    quality = getattr(time_batched_path, "last_quality", None)
    if quality is not None:
        out.update(quality)
    # provenance: round/run ids + git SHA so trend tooling (and
    # scripts/check_bench_regress.py) can line artifacts up
    out.update(artifact_stamp())
    out["trace_artifact"] = _export_trace_artifact(
        default="BENCH_trace.json")
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
