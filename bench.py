#!/usr/bin/env python
"""Benchmark: the scheduler's placement inner loop, TPU solver vs host oracle.

Measures the north-star hot loop (BASELINE.json): per-placement feasibility +
bin-pack scoring + selection over a 10K-node fleet (config tier 3/4 shape:
cpu+mem+disk+port constraints), comparing
  - host oracle: the faithful reimplementation of Nomad's iterator stack
    (scheduler/rank.go BinPackIterator + selection), one Stack.Select per
    placement -- the reference algorithm at reference semantics;
  - TPU solver: the same placements solved as one dense lax.scan dispatch
    (nomad_tpu/solver/binpack.py), verified to produce IDENTICAL placements.

Prints ONE JSON line {"metric","value","unit","vs_baseline"}. vs_baseline is
the solver's speedup over the host oracle's inner loop at equal, verified
work (the reference repo publishes no absolute numbers -- BASELINE.md).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_NODES = int(os.environ.get("BENCH_NODES", "10000"))
N_PLACEMENTS = int(os.environ.get("BENCH_PLACEMENTS", "2000"))
ORACLE_PLACEMENTS = int(os.environ.get("BENCH_ORACLE_PLACEMENTS", "300"))


def build_world():
    from nomad_tpu import mock
    from nomad_tpu.scheduler import Harness

    h = Harness()
    nodes = []
    for i in range(N_NODES):
        n = mock.node()
        n.id = f"bench-node-{i:06d}"
        n.node_resources.cpu.cpu_shares = (2000, 4000, 8000)[i % 3]
        n.node_resources.memory.memory_mb = (4096, 8192, 16384)[i % 3]
        n.compute_class()
        nodes.append(n)
        h.state.upsert_node(n)
    job = mock.job(id="bench-job")
    job.task_groups[0].count = N_PLACEMENTS
    h.state.upsert_job(job)
    return h, job, nodes


def time_host_inner_loop(h, job, nodes, n_placements):
    """One Stack.Select per placement, usage carried via the plan --
    exactly the reference's per-eval inner loop."""
    from nomad_tpu import mock
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.stack import GenericStack, SelectOptions
    from nomad_tpu.structs import (
        AllocatedResources, AllocatedSharedResources, Allocation, Plan,
        generate_uuid)

    plan = Plan(eval_id="bench-eval-0000000000000001", priority=50, job=job)
    snap = h.state.snapshot()
    ctx = EvalContext(snap, plan)
    stack = GenericStack(False, ctx)
    stack.set_job(job)
    stack.set_nodes(list(nodes))
    tg = job.task_groups[0]

    t0 = time.perf_counter()
    placed = {}
    for i in range(n_placements):
        name = f"{job.id}.{tg.name}[{i}]"
        option = stack.select(tg, SelectOptions(alloc_name=name))
        if option is None:
            continue
        alloc = Allocation(
            id=generate_uuid(), name=name, job_id=job.id, job=job,
            task_group=tg.name, node_id=option.node.id,
            allocated_resources=AllocatedResources(
                tasks=dict(option.task_resources),
                shared=AllocatedSharedResources(
                    disk_mb=tg.ephemeral_disk.size_mb)))
        plan.append_alloc(alloc)
        placed[name] = option.node.id
    dt = time.perf_counter() - t0
    return dt, placed


def time_tpu_inner_loop(h, job, nodes, n_placements):
    """All placements in one dense dispatch. The timed region is one full
    service.solve() call: host-side packing (O(nodes) numpy) + the solver
    dispatch + the single device->host result fetch -- i.e. the complete
    per-eval p50 latency path, conservatively including costs a production
    deployment amortizes with incremental usage tensors."""
    from nomad_tpu.scheduler.context import EvalContext
    from nomad_tpu.scheduler.reconcile import AllocPlaceResult
    from nomad_tpu.solver.service import TpuPlacementService
    from nomad_tpu.structs import Plan
    import jax

    plan = Plan(eval_id="bench-eval-0000000000000001", priority=50, job=job)
    snap = h.state.snapshot()
    ctx = EvalContext(snap, plan)
    tg = job.task_groups[0]
    places = [AllocPlaceResult(name=f"{job.id}.{tg.name}[{i}]", task_group=tg)
              for i in range(n_placements)]
    service = TpuPlacementService(ctx, job, batch_mode=False,
                                  spread_alg=False)

    # Warmup compiles the (n_pad, P) program.
    service.solve(tg, places, nodes)

    t0 = time.perf_counter()
    solved = service.solve(tg, places, nodes)
    dt = time.perf_counter() - t0
    placed = {sp.place.name: sp.node.id for sp in solved
              if sp.node is not None}
    return dt, placed


def main():
    h, job, nodes = build_world()

    oracle_dt, oracle_placed = time_host_inner_loop(
        h, job, nodes, ORACLE_PLACEMENTS)
    host_per_place = oracle_dt / max(len(oracle_placed), 1)

    tpu_dt, tpu_placed = time_tpu_inner_loop(h, job, nodes, N_PLACEMENTS)
    tpu_per_place = tpu_dt / max(len(tpu_placed), 1)

    # parity spot-check on the overlapping prefix
    mismatch = sum(
        1 for k in list(oracle_placed)[:ORACLE_PLACEMENTS]
        if k in tpu_placed and tpu_placed[k] != oracle_placed[k])

    placements_per_sec = len(tpu_placed) / tpu_dt if tpu_dt > 0 else 0.0
    speedup = host_per_place / tpu_per_place if tpu_per_place else 0.0

    print(json.dumps({
        "metric": "placements_per_sec_10k_nodes",
        "value": round(placements_per_sec, 2),
        "unit": (f"placements/s ({N_NODES} nodes, {len(tpu_placed)} placed, "
                 f"parity_mismatch={mismatch})"),
        "vs_baseline": round(speedup, 2),
    }))


if __name__ == "__main__":
    main()
