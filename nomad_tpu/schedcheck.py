"""Deterministic schedule explorer ("schedcheck") for the control plane.

ROADMAP item 2 (N scheduler workers over MVCC snapshots) multiplies the
thread interleavings in broker -> worker -> applier -> store, but the
bench host has ONE core: the OS scheduler will never exercise the racy
interleavings on its own, so lockcheck/statecheck (the runtime
sanitizers this module is the fourth sibling of) can only catch what
happens to occur.  Following the systematic-concurrency-testing
lineage in PAPERS.md (controlled-scheduler exploration a la
PCT/Coyote, and deterministic-replay debugging), schedcheck makes the
interleaving a *controlled input*:

  * while a controlled run is active, repo-created threads are
    serialized through a controller: exactly one managed thread holds
    the "floor" at a time, and at every interposition point the
    sanitizer family already owns -- lock acquire/release and
    Condition wait/notify (via lockcheck's ``threading.Lock/RLock/
    Condition`` factory seam), ``queue.Queue`` get/put, ``Event``
    wait/set, ``Thread`` start/join, ``time.sleep``, the broker
    delayed-heap pops, ``guard.run_dispatch`` entry, ``Planner.apply``
    submission, and ``StateStore._bump`` / ``apply_plan_results_batch``
    -- the floor returns to the controller, which picks the next
    runnable thread by seeded PRNG (random-walk), PCT
    priority-change-point schedules (``NOMAD_TPU_SCHEDCHECK_DEPTH``),
    or bounded round-robin.
  * timed waits (``Condition.wait(t)`` poll loops, ``Event.wait(t)``,
    ``queue.get(timeout=)``, ``time.sleep``) are VIRTUALIZED: the
    controller may schedule the waiter as a spurious timeout, so a
    controlled run burns no wall clock sleeping -- but only when no
    pure-runnable thread exists, so a poll loop can never livelock the
    schedule.  Real-time-meaningful deadlines (the dispatch watchdog)
    opt out with ``with schedcheck.real_time():``.
  * same seed => bit-identical decision trace (the run's schedule
    fingerprint) => deterministic placements even for multi-worker
    runs.  Every lockcheck/statecheck violation recorded during a
    controlled run gains a ``schedule`` witness (seed + policy +
    decision step), turning cycles/torn-reads/write-skews into
    *replayable counterexamples*: ``operator schedcheck --replay
    <seed>`` re-runs the exact interleaving.
  * ``explore(fn, seeds=N)`` runs a scenario under N schedules with
    lockcheck+statecheck armed and aggregates the violations; the
    seeded-bug gauntlet in tests/test_schedcheck.py proves it finds a
    planted write-skew and a planted torn read within <=64 schedules
    where hundreds of uncontrolled runs find nothing.

Liveness: a managed thread that blocks on something the controller
cannot see (a socket, a future, foreign compute) is handled by the
park watchdog -- parked threads that observe no schedule progress for
``NOMAD_TPU_SCHEDCHECK_PARK_S`` revoke the floor and the stuck thread
re-enters cooperatively at its next interposition point (counted as
``preemptions``; zero in a well-interposed scenario).

Kill switch semantics (mirrors lockcheck/jitcheck/statecheck): OFF by
default and ``NOMAD_TPU_SCHEDCHECK=0``/unset is a true no-op --
``Thread.start/join``, ``queue.Queue.get/put``, ``Event.wait/set`` and
``time.sleep`` are untouched and no controller is observable anywhere
(bitwise-parity-tested on a real dispatch + plan-commit cycle).
``NOMAD_TPU_SCHEDCHECK=1`` at process start installs the patches and
begins a controlled run rooted at the installing thread; ``enable()``
+ ``begin_run(seed)`` is how explore/replay and the conftest fixture
drive it.

State rides the usual surfaces: ``stats.schedcheck`` in
``/v1/agent/self``, ``operator schedcheck [--replay SEED]`` CLI,
``schedcheck.json`` in operator debug bundles, and the
``nomad.schedcheck.*`` counters.

Knobs: ``NOMAD_TPU_SCHEDCHECK`` (off; ``1`` installs at import),
``NOMAD_TPU_SCHEDCHECK_SEED`` (0: schedule seed),
``NOMAD_TPU_SCHEDCHECK_POLICY`` (random | pct | rr),
``NOMAD_TPU_SCHEDCHECK_DEPTH`` (3: PCT priority change points),
``NOMAD_TPU_SCHEDCHECK_PARK_S`` (0.2: park watchdog / floor
revocation threshold), ``NOMAD_TPU_SCHEDCHECK_TRACE`` (4096: retained
decision-trace entries), ``NOMAD_TPU_SCHEDCHECK_MAX`` (256: retained
reports).
"""
from __future__ import annotations

import hashlib
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional

import _thread

# raw primitives, captured before any patching (lockcheck's factory
# patches threading.Lock/Condition; schedcheck itself patches the
# Thread/Event/queue/sleep entry points below)
_REAL_LOCK = threading.Lock
_REAL_THREAD_START = threading.Thread.start
_REAL_THREAD_JOIN = threading.Thread.join
_REAL_EVENT_WAIT = threading.Event.wait
_REAL_EVENT_SET = threading.Event.set
_REAL_SLEEP = time.sleep
_REAL_QUEUE_GET = None           # queue.Queue.get, saved at enable
_REAL_QUEUE_PUT = None

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ACTIVE = False                  # module-global fast gate (one read)

_slock = _REAL_LOCK()            # leaf: guards module state, no user
                                 # code ever runs under it

_park_s = 0.2
_trace_cap = 4096
_max_reports = 256
# consecutive zero-progress park windows a BLOCKED thread observes
# before a deadlock is declared (~8 * park_s of total quiescence)
_DEADLOCK_WINDOWS = 8

# thread names the env/fixture (non-explore) mode manages: the
# control-plane actors whose interleavings ROADMAP-2 multiplies.
# Everything else (HTTP serving, telemetry flushers, dispatch runner
# threads, pool workers) free-runs and interacts through the
# interposed primitives.
MANAGED_PREFIXES = (
    "scheduler-worker-", "batch-worker-", "batch-eval-", "lpq-eval-",
    "eval-broker-delayed",
)

_counters = {"runs": 0, "decisions": 0, "parks": 0, "preemptions": 0,
             "timeout_wakes": 0, "deadlocks": 0, "divergences": 0,
             "reports_dropped": 0}
_reports: List[dict] = []        # deadlock/divergence counterexamples
_last_run: Optional[dict] = None

_tls = threading.local()


def _metrics():
    """Telemetry sink, or None mid-teardown -- the sanitizer must
    never take the process down with it."""
    try:
        from .server.telemetry import metrics
        return metrics
    except Exception:  # noqa: BLE001
        return None


def _report(payload: dict) -> None:
    with _slock:
        if len(_reports) >= _max_reports:
            _counters["reports_dropped"] += 1
            return
        _reports.append(payload)


# ----------------------------------------------------------------------
# thread states + controller

_ST_RUNNABLE = "runnable"        # wants the floor
_ST_RUNNING = "running"          # holds the floor
_ST_BLOCKED = "blocked"          # waits for an explicit wake
_ST_TIMED = "timed"              # waits, but schedulable as a timeout
_ST_DETACHED = "detached"        # free-running (real block / revoked)
_ST_DONE = "done"


class _TState:
    __slots__ = ("serial", "name", "gate", "status", "wait_kind",
                 "wait_key", "wake_reason", "priority", "ident",
                 "stall_windows")

    def __init__(self, serial: int, name: str):
        self.serial = serial
        self.name = name
        self.gate = threading.Event()
        self.status = _ST_RUNNABLE
        self.wait_kind = ""
        self.wait_key = None
        self.wake_reason = ""
        self.priority = 0.0
        self.ident = None
        self.stall_windows = 0


class _Controller:
    """One controlled run: a seed, a policy, the floor, and the
    decision trace.  All state mutations happen under ``_mx`` (a raw
    leaf lock); parking/granting uses the per-thread raw Event gates,
    touched ONLY through the captured ``_REAL_EVENT_*`` entry points so
    the controller can never recurse into its own interposition."""

    def __init__(self, seed: int, policy: str, depth: int,
                 manage_all: bool, prefixes=MANAGED_PREFIXES):
        self._mx = _REAL_LOCK()
        self.seed = int(seed)
        self.policy = policy
        self.depth = max(0, int(depth))
        self.manage_all = manage_all
        self.prefixes = tuple(prefixes)
        self._rng = random.Random(self.seed)
        self._serial = 0
        self._by_ident: Dict[int, _TState] = {}
        self._states: List[_TState] = []
        self._floor: Optional[_TState] = None
        self.step = 0
        self.trace: List[tuple] = []      # (step, serial, point)
        self._fp = hashlib.blake2b(digest_size=16)
        self._fp.update(f"{self.seed}:{self.policy}:{self.depth}"
                        .encode())
        # bounded round-robin: the seed rotates the start offset so a
        # seed sweep still yields distinct (if few) schedules
        self._rr_next = self.seed % 8
        # PCT: the first ``depth`` change points are drawn up front so
        # the schedule is a pure function of the seed
        self._pct_points = sorted(
            self._rng.randrange(1, 4096) for _ in range(self.depth))
        self.deadlocked = False
        self.finished = False
        # deadlock detection signals: a wake through ANY patched entry
        # point (event set, queue put, lock release, cond notify --
        # callable from unmanaged threads too) bumps the wake serial;
        # scheduling a RUNNABLE thread (as opposed to spinning a
        # virtual-timeout poller) bumps the fruitful counter.  A
        # BLOCKED thread that watches BOTH freeze for a full grace
        # (while nothing runnable/detached exists) is deadlocked.
        self._wake_serial = 0
        self._fruitful = 0

    # -- registration --------------------------------------------------
    def adopt_current(self) -> _TState:
        """Register the calling thread (the run root, or a managed
        thread at begin-of-run) as RUNNING with the floor if vacant."""
        with self._mx:
            st = self._by_ident.get(_thread.get_ident())
            if st is not None:
                return st
            st = self._new_state_locked(threading.current_thread().name)
            st.ident = _thread.get_ident()
            self._by_ident[st.ident] = st
            if self._floor is None:
                st.status = _ST_RUNNING
                self._floor = st
            return st

    def _new_state_locked(self, name: str) -> _TState:
        self._serial += 1
        st = _TState(self._serial, name)
        st.priority = self._rng.random()
        self._states.append(st)
        return st

    def adopt_thread(self, thread: threading.Thread) -> _TState:
        """Register a thread at ``start()`` time (before it runs) so
        serial assignment follows creation order deterministically."""
        with self._mx:
            st = self._new_state_locked(thread.name)
            return st

    def bind_current(self, st: _TState) -> None:
        with self._mx:
            st.ident = _thread.get_ident()
            self._by_ident[st.ident] = st

    def current(self) -> Optional[_TState]:
        return self._by_ident.get(_thread.get_ident())

    def wants_thread(self, thread: threading.Thread, creator) -> bool:
        if self.manage_all:
            return creator is not None
        name = thread.name or ""
        return any(name.startswith(p) for p in self.prefixes)

    # -- scheduling core ----------------------------------------------
    def _record_locked(self, st: _TState, point: str) -> None:
        self.step += 1
        _counters["decisions"] += 1
        self._fp.update(f"{self.step}:{st.serial}:{point};".encode())
        if len(self.trace) < _trace_cap:
            self.trace.append((self.step, st.serial, point))

    def _pick_locked(self) -> Optional[_TState]:
        """The policy decision.  Pure-runnable threads always win over
        virtual-timeout wakes (a poll loop must never starve the thread
        that would make its predicate true); within a class the pick is
        a deterministic function of the seed."""
        runnable = [s for s in self._states if s.status == _ST_RUNNABLE]
        timed = ([] if runnable else
                 [s for s in self._states if s.status == _ST_TIMED])
        cands = runnable or timed
        if not cands:
            return None
        cands.sort(key=lambda s: s.serial)
        if self.policy == "rr":
            nxt = next((s for s in cands
                        if s.serial >= self._rr_next), cands[0])
            self._rr_next = nxt.serial + 1
        elif self.policy == "pct":
            if self._pct_points and self.step >= self._pct_points[0]:
                self._pct_points.pop(0)
                top = max(cands, key=lambda s: (s.priority, s.serial))
                top.priority = min(s.priority
                                   for s in self._states) - 1.0
            nxt = max(cands, key=lambda s: (s.priority, s.serial))
        else:
            nxt = cands[self._rng.randrange(len(cands))]
        if nxt.status == _ST_TIMED:
            nxt.wake_reason = "timeout"
            _counters["timeout_wakes"] += 1
        else:
            self._fruitful += 1
        return nxt

    def _grant_locked(self, st: _TState) -> None:
        st.status = _ST_RUNNING
        self._floor = st
        _REAL_EVENT_SET(st.gate)

    def _pass_floor_locked(self) -> None:
        """The floor is being given up; hand it to the next pick (or
        leave it vacant when only blocked/detached threads remain --
        an external wake through a patched entry point, or the park
        watchdog's stall detection, moves things along)."""
        nxt = self._pick_locked()
        if nxt is not None:
            self._grant_locked(nxt)
            return
        self._floor = None

    def _panic_locked(self) -> None:
        """Every managed thread waits on a wake that can never come: a
        MANIFESTED deadlock.  Record the counterexample (seed + trace)
        and release everyone to free-run (blocked cond/event waiters
        wake spuriously; predicate loops tolerate that) so the process
        survives to report it."""
        if self.deadlocked:
            return
        self.deadlocked = True
        _counters["deadlocks"] += 1
        _report({
            "kind": "deadlock",
            "schedule_seed": self.seed, "policy": self.policy,
            "step": self.step,
            "waiting": [{"thread": s.name, "serial": s.serial,
                         "on": f"{s.wait_kind}:{s.wait_key}"}
                        for s in self._states
                        if s.status == _ST_BLOCKED],
            "trace_tail": [list(t) for t in self.trace[-64:]],
        })
        for s in self._states:
            if s.status in (_ST_BLOCKED, _ST_TIMED, _ST_RUNNABLE):
                s.status = _ST_DETACHED
                s.wake_reason = "panic"
                _REAL_EVENT_SET(s.gate)
        # NOTE: no metrics emit here -- _mx is held and the telemetry
        # sink takes instrumented locks that would re-enter the
        # controller; the caller emits after releasing _mx

    # -- the thread-facing protocol -----------------------------------
    def yield_point(self, st: _TState, point: str) -> None:
        """The floor-holder offers a scheduling decision.  A detached
        thread re-enters the cooperative schedule here."""
        with self._mx:
            if self.finished:
                return
            self._record_locked(st, point)
            st.gate.clear()
            st.status = _ST_RUNNABLE
            if self._floor is st:
                self._pass_floor_locked()
            elif self._floor is None:
                self._pass_floor_locked()
        if st.status != _ST_RUNNING:
            self._park(st)

    def block(self, st: _TState, kind: str, key, timed: bool) -> str:
        """Park until an explicit ``wake`` (or, for ``timed`` waits, a
        policy-chosen virtual timeout).  Returns the wake reason."""
        with self._mx:
            if self.finished:
                return "finished"
            self._record_locked(st, f"block:{kind}")
            st.gate.clear()
            st.status = _ST_TIMED if timed else _ST_BLOCKED
            st.wait_kind, st.wait_key = kind, key
            st.wake_reason = ""
            if self._floor is st or self._floor is None:
                self._pass_floor_locked()
        self._park(st)
        if st.wake_reason == "timeout":
            # pace virtual-timeout polls: determinism is unaffected
            # (the decision already happened), but an unbounded poll
            # spin must not burn the whole core
            _REAL_SLEEP(0.001)
        return st.wake_reason or "granted"

    def wake(self, kind: str, key, n: Optional[int] = None) -> int:
        """Make threads blocked on (kind, key) runnable.  Callable from
        ANY thread (including unmanaged ones: a free-running HTTP
        handler notifying a managed worker's condvar) -- it only flips
        states; the floor moves at the next decision, or immediately
        when it is vacant."""
        woken = 0
        if n is not None and n <= 0:
            return 0
        with self._mx:
            if self.finished:
                return 0
            for s in sorted(self._states, key=lambda s: s.serial):
                if s.status in (_ST_BLOCKED, _ST_TIMED) and \
                        s.wait_kind == kind and s.wait_key == key:
                    s.status = _ST_RUNNABLE
                    s.wake_reason = "notified"
                    woken += 1
                    if n is not None and woken >= n:
                        break
            if woken:
                # only wakes that woke SOMEONE count as progress for
                # the deadlock accrual: background releases/sets with
                # no virtual waiters (leaked test threads, telemetry
                # flushers) must not mask a real circular wait forever
                self._wake_serial += 1
                if self._floor is None:
                    self._pass_floor_locked()
        return woken

    def _park(self, st: _TState) -> None:
        """Wait for the floor.  The park watchdog: if the schedule
        makes NO progress for a full park window while we sit parked,
        the floor-holder is stuck in something the controller cannot
        see -- revoke the floor (the stuck thread detaches and
        re-enters at its next interposition point) so the run keeps
        moving."""
        _counters["parks"] += 1
        last_step = -1
        last_progress = (-1, -1)      # (fruitful, wake_serial)
        while True:
            if _REAL_EVENT_WAIT(st.gate, _park_s):
                st.gate.clear()
                st.stall_windows = 0
                return
            with self._mx:
                if self.finished or st.status == _ST_DETACHED:
                    if st.status != _ST_DONE:
                        st.status = _ST_DETACHED
                    return
                if st.status == _ST_RUNNING:
                    continue          # granted between wait and lock
                if self.step == last_step:
                    self._stalled_locked(st)
                elif self._floor is None:
                    self._pass_floor_locked()
                # deadlock accrual: I am parked on an explicit wake,
                # and for this whole window nothing fruitful ran and
                # nothing woke anyone -- the system is only spinning
                # virtual-timeout pollers (or fully idle)
                declared = False
                progress = (self._fruitful, self._wake_serial)
                if st.status == _ST_BLOCKED and \
                        progress == last_progress and \
                        not any(s.status in (_ST_RUNNABLE,
                                             _ST_DETACHED)
                                for s in self._states):
                    st.stall_windows += 1
                    if st.stall_windows >= _DEADLOCK_WINDOWS:
                        already = self.deadlocked
                        self._panic_locked()
                        declared = not already
                else:
                    st.stall_windows = 0
                last_step = self.step
                last_progress = progress
            if declared:
                # emit OUTSIDE _mx with interposition suppressed (the
                # telemetry sink takes instrumented locks)
                _tls.in_ctl = True
                try:
                    m = _metrics()
                    if m is not None:
                        m.incr("nomad.schedcheck.deadlock")
                finally:
                    _tls.in_ctl = False

    def _stalled_locked(self, st: _TState) -> None:
        """A full park window passed with zero decisions: the
        floor-holder is wedged outside the interposition set -> revoke
        the floor (it re-enters at its next yield point) so the run
        keeps moving.  (Deadlock among BLOCKED threads is the separate
        accrual in _park -- a vacant floor with only blocked threads
        is normal while an unmanaged thread works toward a wake.)"""
        holder = self._floor
        if holder is not None:
            _counters["preemptions"] += 1
            holder.status = _ST_DETACHED
            self._floor = None
        self._pass_floor_locked()

    def thread_begin(self, st: _TState) -> None:
        self.bind_current(st)
        with self._mx:
            if self.finished:
                st.status = _ST_DETACHED
                return
            st.status = _ST_RUNNABLE
            if self._floor is None:
                self._pass_floor_locked()
        if st.status != _ST_RUNNING:
            self._park(st)

    def thread_end(self, st: _TState) -> None:
        with self._mx:
            held = self._floor is st
            st.status = _ST_DONE
            self._wake_serial += 1
            for s in self._states:
                if s.status in (_ST_BLOCKED, _ST_TIMED) and \
                        s.wait_kind == "join" and s.wait_key == st:
                    s.status = _ST_RUNNABLE
                    s.wake_reason = "notified"
            if held or self._floor is None:
                self._floor = None
                self._pass_floor_locked()

    def detach(self, st: _TState) -> None:
        """Enter a real-blocking region: give up the floor and
        free-run until the next interposition point."""
        with self._mx:
            if self.finished:
                return
            self._record_locked(st, "detach")
            st.status = _ST_DETACHED
            if self._floor is st:
                self._floor = None
                self._pass_floor_locked()

    def finish(self) -> dict:
        """End the run: release every parked thread to free-run and
        freeze the summary."""
        with self._mx:
            self.finished = True
            summary = {
                "seed": self.seed, "policy": self.policy,
                "depth": self.depth, "decisions": self.step,
                "fingerprint": self._fp.hexdigest(),
                "threads": len(self._states),
                "deadlocked": self.deadlocked,
                "trace_tail": [list(t) for t in self.trace[-64:]],
            }
            for s in self._states:
                if s.status not in (_ST_DONE,):
                    s.status = _ST_DETACHED
                _REAL_EVENT_SET(s.gate)
            self._floor = None
        return summary


_ctl: Optional[_Controller] = None


def _cur() -> Optional[_TState]:
    """The calling thread's managed state, or None (fast path: one
    module-global read when the checker is off)."""
    ctl = _ctl
    if ctl is None or ctl.finished:
        return None
    if getattr(_tls, "in_ctl", False):
        return None
    return ctl.current()


# ----------------------------------------------------------------------
# interposition API (called from lockcheck wrappers and the repo's
# marker sites; every entry is gated on _ACTIVE by the caller or here)


def yield_point(point: str) -> None:
    """A scheduling decision: the floor-holder pauses and the policy
    picks the next runnable thread (possibly the same one)."""
    if not _ACTIVE:
        return
    ctl, st = _ctl, _cur()
    if ctl is None or st is None:
        return
    ctl.yield_point(st, point)


def lock_gate(inner, point: str = "lock.acquire") -> None:
    """Deterministic lock handoff: yield, then wait (virtually) while
    the inner primitive is held elsewhere.  The caller performs the
    real acquire after we return -- uncontended by construction, since
    only one managed thread runs at a time and the release hook wakes
    us."""
    if not _ACTIVE:
        return
    ctl, st = _ctl, _cur()
    if ctl is None or st is None:
        return
    ctl.yield_point(st, point)
    stalls = 0
    while not _probe_free(inner):
        # timed: a release by an unmanaged thread may not wake us, so
        # stay schedulable and re-probe
        reason = ctl.block(st, "lock", id(inner), timed=True)
        if reason in ("panic", "finished"):
            return            # the caller's real acquire blocks for real
        if reason == "timeout":
            # the holder is outside the schedule (detached/unmanaged):
            # pace the re-probe so the spin does not burn a core
            stalls += 1
            if stalls > 2:
                _REAL_SLEEP(0.001)


def lock_released(inner) -> None:
    if not _ACTIVE:
        return
    ctl = _ctl
    if ctl is None or ctl.finished:
        return
    ctl.wake("lock", id(inner))
    st = _cur()
    if st is not None:
        ctl.yield_point(st, "lock.release")


def _probe_free(inner) -> bool:
    """Whether the raw Lock/RLock could be acquired without blocking
    (includes RLock re-entry by the probing thread)."""
    if inner.acquire(False):
        inner.release()
        return True
    return False


def cond_wait_gate(cond_id: int, timed: bool) -> bool:
    """Virtual Condition.wait: park until notify (or a virtual timeout
    for timed waits).  Returns True when notified."""
    ctl, st = _ctl, _cur()
    if ctl is None or st is None:
        return True
    reason = ctl.block(st, "cond", cond_id, timed=timed)
    return reason == "notified"


def cond_notify(cond_id: int, n: Optional[int]) -> None:
    ctl = _ctl
    if ctl is None or ctl.finished:
        return
    ctl.wake("cond", cond_id, n=n)


def managed_active() -> bool:
    """Whether the calling thread is under the controller right now
    (the lockcheck wrappers route their wait/acquire through the
    virtual protocol only when this holds)."""
    return _ACTIVE and _cur() is not None


class _RealBlock:
    """``with schedcheck.real_block():`` -- the body performs real
    blocking the controller cannot interpose (socket, future, foreign
    compute): detach for the duration, re-enter at exit."""

    def __enter__(self):
        ctl, st = _ctl, _cur()
        self._st = st if ctl is not None else None
        if self._st is not None:
            ctl.detach(self._st)
        return self

    def __exit__(self, *exc):
        st = self._st
        ctl = _ctl
        if st is not None and ctl is not None and not ctl.finished:
            ctl.yield_point(st, "real_block.exit")
        return False


def real_block() -> _RealBlock:
    return _RealBlock()


class _RealTime:
    """``with schedcheck.real_time():`` -- timed waits in the body keep
    REAL timeout semantics (the dispatch watchdog deadline must not
    fire virtually early); the thread detaches for the duration."""

    def __enter__(self):
        self._prev = getattr(_tls, "real_time", 0)
        _tls.real_time = self._prev + 1
        self._rb = _RealBlock().__enter__()
        return self

    def __exit__(self, *exc):
        _tls.real_time = self._prev
        self._rb.__exit__(*exc)
        return False


def real_time() -> _RealTime:
    return _RealTime()


def _in_real_time() -> bool:
    return bool(getattr(_tls, "real_time", 0))


def witness() -> Optional[dict]:
    """The schedule witness attached to every lockcheck/statecheck
    report recorded during a controlled run: replaying the seed
    reproduces the interleaving that manifested the violation."""
    ctl = _ctl
    if not _ACTIVE or ctl is None or ctl.finished:
        return None
    return {"schedule_seed": ctl.seed, "policy": ctl.policy,
            "step": ctl.step}


# ----------------------------------------------------------------------
# patched stdlib entry points (installed by enable(); every wrapper
# falls through to the real call unless the CURRENT thread is managed)


def _patched_thread_start(self):
    ctl = _ctl
    if _ACTIVE and ctl is not None and not ctl.finished and \
            not getattr(self, "_sc_state", None):
        creator = _cur()
        if ctl.wants_thread(self, creator):
            st = ctl.adopt_thread(self)
            self._sc_state = st
            run = self.run

            def _managed_run():
                ctl.thread_begin(st)
                try:
                    run()
                finally:
                    ctl.thread_end(st)

            self.run = _managed_run
    return _REAL_THREAD_START(self)


def _patched_thread_join(self, timeout=None):
    ctl, st = _ctl, _cur()
    if st is None or ctl is None:
        return _REAL_THREAD_JOIN(self, timeout)
    target = getattr(self, "_sc_state", None)
    if target is not None:
        # virtual join on a managed target: wait for its thread_end
        while self.is_alive() and target.status != _ST_DONE:
            reason = ctl.block(st, "join", target,
                               timed=timeout is not None)
            if reason == "timeout" and timeout is not None:
                return            # virtual expiry; caller re-checks
            if reason in ("panic", "finished"):
                with real_block():
                    return _REAL_THREAD_JOIN(self, timeout)
        return _REAL_THREAD_JOIN(self, 0.05)
    with real_block():
        return _REAL_THREAD_JOIN(self, timeout)


def _patched_event_wait(self, timeout=None):
    ctl, st = _ctl, _cur()
    if st is None or ctl is None or _in_real_time():
        if _in_real_time() and _cur() is not None:
            with real_block():
                return _REAL_EVENT_WAIT(self, timeout)
        return _REAL_EVENT_WAIT(self, timeout)
    while not self.is_set():
        reason = ctl.block(st, "event", id(self),
                           timed=timeout is not None)
        if reason == "timeout":
            break                 # a legit (virtual) timeout expiry
        if reason == "panic":
            break                 # manifested deadlock: wake spuriously
                                  # so the wedge surfaces instead of
                                  # parking on a set() that never comes
        if reason == "finished":
            return _REAL_EVENT_WAIT(self, timeout)
    return self.is_set()


def _patched_event_set(self):
    _REAL_EVENT_SET(self)
    ctl = _ctl
    if _ACTIVE and ctl is not None and not ctl.finished:
        ctl.wake("event", id(self))


def _patched_sleep(secs):
    ctl, st = _ctl, _cur()
    if st is None or ctl is None or _in_real_time() or secs <= 0:
        return _REAL_SLEEP(secs)
    # virtual sleep: one schedulable timeout event, no wall clock
    ctl.block(st, "sleep", None, timed=True)


def _patched_queue_get(self, block=True, timeout=None):
    ctl, st = _ctl, _cur()
    if st is None or ctl is None or not block:
        return _REAL_QUEUE_GET(self, block, timeout)
    import queue as _queue
    while True:
        ctl.yield_point(st, "queue.get")
        try:
            return _REAL_QUEUE_GET(self, False)
        except _queue.Empty:
            reason = ctl.block(st, "queue", id(self),
                               timed=timeout is not None)
            if reason == "timeout" and timeout is not None:
                raise
            if reason == "panic":
                raise             # deadlock: surface as Empty rather
                                  # than park on a put() never coming
            if reason == "finished":
                return _REAL_QUEUE_GET(self, block, timeout)


def _patched_queue_put(self, item, block=True, timeout=None):
    ctl, st = _ctl, _cur()
    if st is not None and ctl is not None:
        ctl.yield_point(st, "queue.put")
    out = _REAL_QUEUE_PUT(self, item, block, timeout)
    if _ACTIVE and ctl is not None and not ctl.finished:
        ctl.wake("queue", id(self))
    return out


# ----------------------------------------------------------------------
# lifecycle


def enabled() -> bool:
    return _ACTIVE


def enable() -> None:
    """Install the interposition patches.  They are inert (one
    module-global read, then a thread-registry miss) for every thread
    outside a controlled run."""
    global _ACTIVE, _REAL_QUEUE_GET, _REAL_QUEUE_PUT
    global _park_s, _trace_cap, _max_reports
    with _slock:
        if _ACTIVE:
            return
        _park_s = float(os.environ.get(
            "NOMAD_TPU_SCHEDCHECK_PARK_S", "0.2"))
        _trace_cap = int(os.environ.get(
            "NOMAD_TPU_SCHEDCHECK_TRACE", "4096"))
        _max_reports = int(os.environ.get(
            "NOMAD_TPU_SCHEDCHECK_MAX", "256"))
    import queue
    if _REAL_QUEUE_GET is None:
        _REAL_QUEUE_GET = queue.Queue.get
        _REAL_QUEUE_PUT = queue.Queue.put
    threading.Thread.start = _patched_thread_start
    threading.Thread.join = _patched_thread_join
    threading.Event.wait = _patched_event_wait
    threading.Event.set = _patched_event_set
    time.sleep = _patched_sleep
    queue.Queue.get = _patched_queue_get
    queue.Queue.put = _patched_queue_put
    _ACTIVE = True


def disable() -> None:
    """Restore the real entry points.  A run still active is finished
    first so no thread stays parked."""
    global _ACTIVE
    if not _ACTIVE:
        return
    end_run()
    _ACTIVE = False
    import queue
    threading.Thread.start = _REAL_THREAD_START
    threading.Thread.join = _REAL_THREAD_JOIN
    threading.Event.wait = _REAL_EVENT_WAIT
    threading.Event.set = _REAL_EVENT_SET
    time.sleep = _REAL_SLEEP
    if _REAL_QUEUE_GET is not None:
        queue.Queue.get = _REAL_QUEUE_GET
        queue.Queue.put = _REAL_QUEUE_PUT


def begin_run(seed: int = 0, policy: Optional[str] = None,
              depth: Optional[int] = None,
              manage_all: bool = False) -> None:
    """Start a controlled run rooted at the calling thread.  Threads
    the root (transitively) starts are managed when ``manage_all``
    (explore/replay scenarios), else by the MANAGED_PREFIXES allowlist
    (env/fixture mode over live suites)."""
    global _ctl
    if not _ACTIVE:
        enable()
    end_run()
    policy = policy or os.environ.get(
        "NOMAD_TPU_SCHEDCHECK_POLICY", "random")
    if depth is None:
        depth = int(os.environ.get("NOMAD_TPU_SCHEDCHECK_DEPTH", "3"))
    ctl = _Controller(seed, policy, depth, manage_all)
    ctl.adopt_current()
    with _slock:
        _counters["runs"] += 1
    _ctl = ctl
    m = _metrics()
    if m is not None:
        m.incr("nomad.schedcheck.run")


def end_run() -> Optional[dict]:
    """Finish the active run (if any) and return its summary."""
    global _ctl, _last_run
    ctl = _ctl
    if ctl is None:
        return None
    _ctl = None
    summary = ctl.finish()
    with _slock:
        _last_run = summary
    return summary


def maybe_install_from_env() -> None:
    if os.environ.get("NOMAD_TPU_SCHEDCHECK", "0") == "1":
        enable()
        begin_run(seed=int(os.environ.get(
            "NOMAD_TPU_SCHEDCHECK_SEED", "0")))


# ----------------------------------------------------------------------
# exploration driver + replay


class RunResult:
    __slots__ = ("seed", "policy", "fingerprint", "decisions",
                 "violations", "summary", "error")

    def __init__(self, seed, policy, fingerprint, decisions,
                 violations, summary, error=None):
        self.seed = seed
        self.policy = policy
        self.fingerprint = fingerprint
        self.decisions = decisions
        self.violations = violations
        self.summary = summary
        self.error = error

    def to_dict(self) -> dict:
        return {"seed": self.seed, "policy": self.policy,
                "fingerprint": self.fingerprint,
                "decisions": self.decisions,
                "violations": self.violations,
                "error": repr(self.error) if self.error else None}


class ExploreResult:
    __slots__ = ("runs", "violations")

    def __init__(self, runs: List[RunResult]):
        self.runs = runs
        self.violations = [v for r in runs for v in r.violations]

    @property
    def seeds_with_violations(self) -> List[int]:
        return sorted({r.seed for r in self.runs if r.violations})

    def to_dict(self) -> dict:
        return {"runs": [r.to_dict() for r in self.runs],
                "violation_count": len(self.violations),
                "seeds_with_violations": self.seeds_with_violations}


def _collect_violations() -> List[dict]:
    """Harvest the hard findings the armed sanitizers recorded during
    one controlled run, normalized to (checker, kind, witness...)."""
    out: List[dict] = []
    from . import lockcheck, statecheck
    lc = lockcheck.state()
    for c in lc.get("cycles") or []:
        out.append({"checker": "lockcheck", "kind": "cycle",
                    "locks": c.get("locks"),
                    "schedule": c.get("schedule")})
    sc = statecheck.state()
    for key, kind in (("torn_reads", "torn_read"),
                      ("aliasing_writes", "aliasing_write"),
                      ("write_skews", "write_skew"),
                      ("journal_gaps", "journal_gap"),
                      ("stale_memos", "stale_memo")):
        for r in sc.get(key) or []:
            v = {"checker": "statecheck", "kind": kind,
                 "schedule": r.get("schedule")}
            for f in ("op", "site", "versions", "node", "plans",
                      "detail"):
                if r.get(f) is not None:
                    v[f] = r[f]
            out.append(v)
    return out


def run_schedule(fn: Callable[[], None], seed: int,
                 policy: Optional[str] = None,
                 depth: Optional[int] = None) -> RunResult:
    """One controlled run of ``fn`` under (seed, policy) with
    lockcheck + statecheck armed; returns the violations each carrying
    the schedule witness."""
    from . import lockcheck, statecheck
    lc_was, sc_was = lockcheck.enabled(), statecheck.enabled()
    if not lc_was:
        lockcheck.enable()
    if not sc_was:
        statecheck.enable()
    enable()
    begin_run(seed, policy=policy, depth=depth, manage_all=True)
    error = None
    try:
        fn()
    except Exception as e:  # noqa: BLE001 -- the run result carries it
        error = e
    summary = end_run()
    violations = _collect_violations()
    if summary.get("deadlocked"):
        violations.append({
            "checker": "schedcheck", "kind": "deadlock",
            "schedule": {"schedule_seed": seed,
                         "policy": summary["policy"],
                         "step": summary["decisions"]}})
    lockcheck._reset_for_tests()
    statecheck._reset_for_tests()
    if not lc_was:
        lockcheck.disable()
    if not sc_was:
        statecheck.disable()
    return RunResult(seed, summary["policy"], summary["fingerprint"],
                     summary["decisions"], violations, summary, error)


def explore(fn: Callable[[], None], seeds=16,
            policy: Optional[str] = None,
            depth: Optional[int] = None) -> ExploreResult:
    """Run ``fn`` under N seeded schedules (``seeds`` is a count or an
    iterable of seeds) and aggregate the violations."""
    seed_list = (list(range(seeds)) if isinstance(seeds, int)
                 else list(seeds))
    runs = [run_schedule(fn, s, policy=policy, depth=depth)
            for s in seed_list]
    return ExploreResult(runs)


def replay(fn: Callable[[], None], seed: int,
           policy: Optional[str] = None,
           depth: Optional[int] = None,
           expect_fingerprint: Optional[str] = None) -> RunResult:
    """Re-run the exact interleaving a violation reported.  When the
    caller pins the expected schedule fingerprint, a divergence (the
    scenario itself changed between record and replay) is counted and
    reported."""
    result = run_schedule(fn, seed, policy=policy, depth=depth)
    if expect_fingerprint is not None and \
            result.fingerprint != expect_fingerprint:
        with _slock:
            _counters["divergences"] += 1
        _report({"kind": "divergence", "schedule_seed": seed,
                 "expected": expect_fingerprint,
                 "got": result.fingerprint})
        m = _metrics()
        if m is not None:
            m.incr("nomad.schedcheck.divergence")
    return result


# ----------------------------------------------------------------------
# built-in scenarios (the CLI replay surface and the gauntlet's
# targets; the planted-* ones SEED the bug they are named for)


def _world():
    from . import mock
    from .state.store import StateStore

    store = StateStore()
    node = mock.node()
    node.id = "sched-node-0000"
    node.compute_class()
    store.upsert_node(node)
    job = mock.job(id="sched-job")
    return store, node, job


def scenario_broker_smoke() -> None:
    """Clean scenario: two workers race dequeues off one broker and
    commit disjoint single-plan batches.  Zero violations expected
    under every schedule."""
    from . import mock
    from .server.broker import EvalBroker
    from .structs import PlanResult

    store, node, job = _world()
    broker = EvalBroker()
    broker.set_enabled(True)
    evs = []
    for k in range(4):
        ev = mock.evaluation(job_id=f"smoke-job-{k}")
        ev.id = f"smoke-eval-{k}-" + "0" * 18
        evs.append(ev)
    broker.enqueue_all(evs)

    def worker(k):
        for _ in range(2):
            ev, token = broker.dequeue(["service"], timeout=0.2)
            if ev is None:
                continue
            a = mock.alloc_for(job, node, index=hash(ev.id) % 97)
            a.eval_id = ev.id
            store.apply_plan_results_batch(
                [(PlanResult(node_allocation={node.id: [a]}), None)])
            broker.ack(ev.id, token)

    threads = [threading.Thread(target=worker, args=(k,),
                                daemon=True, name=f"smoke-worker-{k}")
               for k in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        while t.is_alive():
            t.join(timeout=5.0)
    broker.shutdown()


def scenario_planted_write_skew() -> None:
    """PLANTED BUG: two workers claim a node through a check-then-act
    whose check runs OUTSIDE the claim lock (the disjointness check is
    bypassed).  Under the racy interleaving both claims land in ONE
    ``apply_plan_results_batch`` transaction touching the same node --
    statecheck's write-skew witness.  Uncontrolled, the racy window is
    a few bytecodes wide and the OS never splits it."""
    from . import mock
    from .structs import PlanResult

    store, node, job = _world()
    claimed: set = set()
    batch: list = []
    claim_lock = threading.Lock()

    def worker(k):
        a = mock.alloc_for(job, node, index=k)
        a.eval_id = f"skew-eval-{k}-" + "0" * 16
        if node.id not in claimed:          # racy read (the bug)
            with claim_lock:
                claimed.add(node.id)
                batch.append(
                    (PlanResult(node_allocation={node.id: [a]}), None))

    threads = [threading.Thread(target=worker, args=(k,),
                                daemon=True, name=f"skew-worker-{k}")
               for k in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        while t.is_alive():
            t.join(timeout=5.0)
    if batch:
        store.apply_plan_results_batch(batch)


def scenario_planted_torn_read() -> None:
    """PLANTED BUG: a verifier opens a strict scope but drops the store
    lock between its two fold reads; a committer that lands in the gap
    makes the verifier observe two table versions inside one strict
    scope -- statecheck's torn read.  The committer thread is only
    SPAWNED once the first read completed, so an uncontrolled run can
    never collide (thread spawn latency dwarfs the microsecond gap);
    under a controlled schedule the spawn is itself a decision point
    and the commit can land squarely in the gap."""
    from . import mock, statecheck

    store, node, job = _world()
    store.upsert_allocs([mock.alloc_for(job, node)])
    r1_done = threading.Event()

    def verifier():
        with statecheck.strict_scope("schedcheck.gauntlet"):
            with store._lock:
                store.alloc_table.fold_verify([node.id])
            r1_done.set()
            # the planted bug: the lock is dropped mid-verify
            with store._lock:
                store.alloc_table.fold_verify([node.id])

    def committer():
        store.upsert_allocs([mock.alloc_for(job, node, index=1)])

    vt = threading.Thread(target=verifier, daemon=True,
                          name="torn-verifier")
    vt.start()
    r1_done.wait(5.0)
    ct = threading.Thread(target=committer, daemon=True,
                          name="torn-committer")
    ct.start()
    for t in (vt, ct):
        while t.is_alive():
            t.join(timeout=5.0)


SCENARIOS: Dict[str, Callable[[], None]] = {
    "broker-smoke": scenario_broker_smoke,
    "planted-write-skew": scenario_planted_write_skew,
    "planted-torn-read": scenario_planted_torn_read,
}


# ----------------------------------------------------------------------
# reporting


def state() -> dict:
    """Full checker state (capped); rides /v1/agent/self, the operator
    CLI and debug bundles."""
    ctl = _ctl
    with _slock:
        return {
            "enabled": _ACTIVE,
            "run_active": bool(ctl is not None and not ctl.finished),
            "seed": ctl.seed if ctl is not None else None,
            "policy": ctl.policy if ctl is not None else None,
            "depth": ctl.depth if ctl is not None else None,
            "park_s": _park_s,
            "runs": _counters["runs"],
            "decisions": _counters["decisions"],
            "parks": _counters["parks"],
            "preemptions": _counters["preemptions"],
            "timeout_wakes": _counters["timeout_wakes"],
            "deadlock_count": _counters["deadlocks"],
            "divergence_count": _counters["divergences"],
            "reports_dropped": _counters["reports_dropped"],
            "threads_managed": (len(ctl._states)
                                if ctl is not None else 0),
            "last_run": dict(_last_run) if _last_run else None,
            "reports": [dict(r) for r in _reports],
        }


def _reset_for_tests() -> None:
    global _last_run
    end_run()
    with _slock:
        _reports.clear()
        _last_run = None
        for k in _counters:
            _counters[k] = 0
