"""Jobspec mapping: HCL tree -> Job structs.

Semantic parity with /root/reference/jobspec2/parse.go (Parse -> *api.Job;
block mapping mirrors jobspec/parse_job.go, parse_group.go, parse_task.go,
parse_network.go of the HCL1 package, which enumerate the exact block and
attribute names: group/task/resources/network/port/constraint/affinity/
spread/update/restart/reschedule/migrate/periodic/parameterized/meta/env/
service/volume/ephemeral_disk/lifecycle/artifact/template/logs/device).
Durations accept go-style strings ("30s", "5m", "1h30m").
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from ..structs import (
    Affinity, Constraint, DeviceRequest, EphemeralDisk, Job, LogConfig,
    MigrateStrategy, NetworkResource, ParameterizedJobConfig,
    PeriodicConfig, Port, ReschedulePolicy, Resources, RestartPolicy,
    Service, Spread, SpreadTarget, Task, TaskGroup, UpdateStrategy,
    VolumeRequest,
)
from .hcl import Block, HclError, parse_hcl

_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h|d)")
_DUR_MULT = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def duration(val: Any, default: float = 0.0) -> float:
    """go-style duration -> seconds."""
    if val is None:
        return default
    if isinstance(val, (int, float)):
        return float(val)
    s = str(val).strip()
    if not s:
        return default
    total, matched = 0.0, False
    for m in _DUR_RE.finditer(s):
        total += float(m.group(1)) * _DUR_MULT[m.group(2)]
        matched = True
    if not matched:
        try:
            return float(s)
        except ValueError:
            raise HclError(f"bad duration {val!r}")
    return total


def parse(src: str, variables: Optional[Dict[str, Any]] = None) -> Job:
    """(reference: jobspec2/parse.go:21 Parse)"""
    root = parse_hcl(src, variables)
    job_block = root.first("job")
    if job_block is None:
        raise HclError("no job block found")
    return parse_job(job_block)


def parse_file(path: str,
               variables: Optional[Dict[str, Any]] = None) -> Job:
    with open(path, encoding="utf-8") as fh:
        return parse(fh.read(), variables)


# ---------------------------------------------------------------------------
def parse_job(b: Block) -> Job:
    a = b.attrs()
    job = Job(
        id=b.label(0) or str(a.get("id", "")),
        name=str(a.get("name", b.label(0))),
        namespace=str(a.get("namespace", "default")),
        region=str(a.get("region", "global")),
        type=str(a.get("type", "service")),
        priority=int(a.get("priority", 50)),
        all_at_once=bool(a.get("all_at_once", False)),
        datacenters=[str(d) for d in a.get("datacenters", ["*"])],
        node_pool=str(a.get("node_pool", "default")),
        vault_namespace=str(a.get("vault_namespace", "")),
    )
    job.meta = {str(k): str(v) for k, v in _meta(b).items()}
    job.constraints = [_constraint(c) for c in b.blocks("constraint")]
    job.affinities = [_affinity(c) for c in b.blocks("affinity")]
    job.spreads = [_spread(s) for s in b.blocks("spread")]
    upd = b.first("update")
    if upd is not None:
        job.update = _update(upd)
    per = b.first("periodic")
    if per is not None:
        pa = per.attrs()
        job.periodic = PeriodicConfig(
            enabled=bool(pa.get("enabled", True)),
            spec=str(pa.get("cron", pa.get("spec", ""))),
            prohibit_overlap=bool(pa.get("prohibit_overlap", False)),
            timezone=str(pa.get("time_zone", "UTC")))
    param = b.first("parameterized")
    if param is not None:
        pa = param.attrs()
        job.parameterized = ParameterizedJobConfig(
            payload=str(pa.get("payload", "optional")),
            meta_required=[str(x) for x in pa.get("meta_required", [])],
            meta_optional=[str(x) for x in pa.get("meta_optional", [])])
    for g in b.blocks("group"):
        job.task_groups.append(parse_group(g, job))
    if not job.task_groups:
        # single top-level task sugar (reference: jobspec allows task at
        # job level wrapped into an implicit group)
        tasks = b.blocks("task")
        if tasks:
            tg = TaskGroup(name=job.id, count=1,
                           tasks=[parse_task(t) for t in tasks])
            job.task_groups.append(tg)
    return job


def parse_group(b: Block, job: Job) -> TaskGroup:
    a = b.attrs()
    tg = TaskGroup(
        name=b.label(0),
        count=int(a.get("count", 1)),
        meta={str(k): str(v) for k, v in _meta(b).items()},
    )
    if "max_client_disconnect" in a:
        tg.max_client_disconnect_s = duration(a["max_client_disconnect"])
    if "stop_after_client_disconnect" in a:
        tg.stop_after_client_disconnect_s = duration(
            a["stop_after_client_disconnect"])
    tg.prevent_reschedule_on_lost = bool(
        a.get("prevent_reschedule_on_lost", False))
    tg.constraints = [_constraint(c) for c in b.blocks("constraint")]
    tg.affinities = [_affinity(c) for c in b.blocks("affinity")]
    tg.spreads = [_spread(s) for s in b.blocks("spread")]
    sc = b.first("scaling")
    if sc is not None:
        sa = sc.attrs()
        tg.scaling = {
            "min": int(sa.get("min", 0)),
            "max": int(sa.get("max", tg.count)),
            "enabled": bool(sa.get("enabled", True)),
            "policy": {blk.label(0) or "policy": blk.attrs()
                       for blk in sc.blocks("policy")},
        }
    tg.networks = [_network(n) for n in b.blocks("network")]
    tg.services = [_service(s) for s in b.blocks("service")]
    upd = b.first("update")
    if upd is not None:
        tg.update = _update(upd)
    res = b.first("restart")
    if res is not None:
        ra = res.attrs()
        tg.restart_policy = RestartPolicy(
            attempts=int(ra.get("attempts", 2)),
            interval_s=duration(ra.get("interval"), 1800.0),
            delay_s=duration(ra.get("delay"), 15.0),
            mode=str(ra.get("mode", "fail")))
    rs = b.first("reschedule")
    if rs is not None:
        ra = rs.attrs()
        tg.reschedule_policy = ReschedulePolicy(
            attempts=int(ra.get("attempts", 0)),
            interval_s=duration(ra.get("interval"), 0.0),
            delay_s=duration(ra.get("delay"), 30.0),
            delay_function=str(ra.get("delay_function", "exponential")),
            max_delay_s=duration(ra.get("max_delay"), 3600.0),
            unlimited=bool(ra.get("unlimited", True)))
    mig = b.first("migrate")
    if mig is not None:
        ma = mig.attrs()
        tg.migrate = MigrateStrategy(
            max_parallel=int(ma.get("max_parallel", 1)),
            health_check=str(ma.get("health_check", "checks")),
            min_healthy_time_s=duration(ma.get("min_healthy_time"), 10.0),
            healthy_deadline_s=duration(ma.get("healthy_deadline"), 300.0))
    eph = b.first("ephemeral_disk")
    if eph is not None:
        ea = eph.attrs()
        tg.ephemeral_disk = EphemeralDisk(
            sticky=bool(ea.get("sticky", False)),
            size_mb=int(ea.get("size", 300)),
            migrate=bool(ea.get("migrate", False)))
    for v in b.blocks("volume"):
        va = v.attrs()
        tg.volumes[v.label(0)] = VolumeRequest(
            name=v.label(0), type=str(va.get("type", "host")),
            source=str(va.get("source", "")),
            read_only=bool(va.get("read_only", False)),
            access_mode=str(va.get("access_mode", "")),
            attachment_mode=str(va.get("attachment_mode", "")),
            per_alloc=bool(va.get("per_alloc", False)))
    for t in b.blocks("task"):
        tg.tasks.append(parse_task(t))
    return tg


def parse_task(b: Block) -> Task:
    a = b.attrs()
    task = Task(
        name=b.label(0),
        driver=str(a.get("driver", "mock")),
        user=str(a.get("user", "")),
        leader=bool(a.get("leader", False)),
        kind=str(a.get("kind", "")),
        kill_timeout_s=duration(a.get("kill_timeout"), 5.0),
        meta={str(k): str(v) for k, v in _meta(b).items()},
    )
    cfg = b.first("config")
    if cfg is not None:
        task.config = _config_tree(cfg)
    envb = b.first("env")
    if envb is not None:
        task.env = {str(k): str(v) for k, v in envb.attrs().items()}
    task.constraints = [_constraint(c) for c in b.blocks("constraint")]
    task.affinities = [_affinity(c) for c in b.blocks("affinity")]
    task.services = [_service(s) for s in b.blocks("service")]
    res = b.first("resources")
    if res is not None:
        task.resources = _resources(res)
    lc = b.first("lifecycle")
    if lc is not None:
        la = lc.attrs()
        task.lifecycle = {"hook": str(la.get("hook", "")),
                          "sidecar": bool(la.get("sidecar", False))}
    logs = b.first("logs")
    if logs is not None:
        la = logs.attrs()
        task.log_config = LogConfig(
            max_files=int(la.get("max_files", 10)),
            max_file_size_mb=int(la.get("max_file_size", 10)))
    for art in b.blocks("artifact"):
        aa = art.attrs()
        task.artifacts.append({
            "source": str(aa.get("source", "")),
            "destination": str(aa.get("destination", "")),
            "mode": str(aa.get("mode", "any"))})
    for tpl in b.blocks("template"):
        ta = tpl.attrs()
        task.templates.append({
            "data": str(ta.get("data", "")),
            "source": str(ta.get("source", "")),
            "destination": str(ta.get("destination", "")),
            "change_mode": str(ta.get("change_mode", "restart"))})
    vault = b.first("vault")
    if vault is not None:
        task.vault = vault.attrs()
    return task


# ---------------------------------------------------------------------------
def _meta(b: Block) -> Dict[str, Any]:
    m = b.first("meta")
    return m.attrs() if m is not None else {}


def _config_tree(b: Block) -> Dict[str, Any]:
    """config blocks may nest sub-blocks (e.g. docker mounts)."""
    out: Dict[str, Any] = dict(b.attrs())
    for sub in b.blocks():
        out.setdefault(sub.type, []).append(_config_tree(sub))
    return out


def _constraint(b: Block) -> Constraint:
    a = b.attrs()
    operand = str(a.get("operator", a.get("operand", "=")))
    # sugar forms (reference: parse_job.go constraint shorthands)
    for sugar in ("distinct_hosts", "distinct_property", "regexp",
                  "version", "semver", "set_contains", "is_set",
                  "is_not_set"):
        if sugar in a:
            operand = sugar
            if sugar not in ("distinct_hosts", "is_set", "is_not_set"):
                a.setdefault("value", a[sugar])
            break
    return Constraint(
        l_target=str(a.get("attribute", "")),
        r_target=str(a.get("value", "")),
        operand=operand)


def _affinity(b: Block) -> Affinity:
    a = b.attrs()
    return Affinity(
        l_target=str(a.get("attribute", "")),
        r_target=str(a.get("value", "")),
        operand=str(a.get("operator", a.get("operand", "="))),
        weight=int(a.get("weight", 50)))


def _spread(b: Block) -> Spread:
    a = b.attrs()
    targets = []
    for t in b.blocks("target"):
        ta = t.attrs()
        targets.append(SpreadTarget(
            value=t.label(0) or str(ta.get("value", "")),
            percent=int(ta.get("percent", 0))))
    return Spread(attribute=str(a.get("attribute", "")),
                  weight=int(a.get("weight", 50)),
                  spread_target=targets)


def _update(b: Block) -> UpdateStrategy:
    a = b.attrs()
    return UpdateStrategy(
        stagger_s=duration(a.get("stagger"), 30.0),
        max_parallel=int(a.get("max_parallel", 1)),
        health_check=str(a.get("health_check", "checks")),
        min_healthy_time_s=duration(a.get("min_healthy_time"), 10.0),
        healthy_deadline_s=duration(a.get("healthy_deadline"), 300.0),
        progress_deadline_s=duration(a.get("progress_deadline"), 600.0),
        auto_revert=bool(a.get("auto_revert", False)),
        auto_promote=bool(a.get("auto_promote", False)),
        canary=int(a.get("canary", 0)))


def _network(b: Block) -> NetworkResource:
    a = b.attrs()
    net = NetworkResource(mode=str(a.get("mode", "host")),
                          mbits=int(a.get("mbits", 0)))
    for p in b.blocks("port"):
        pa = p.attrs()
        port = Port(label=p.label(0),
                    value=int(pa.get("static", 0)),
                    to=int(pa.get("to", 0)),
                    host_network=str(pa.get("host_network", "default")))
        if port.value:
            net.reserved_ports.append(port)
        else:
            net.dynamic_ports.append(port)
    return net


def _service(b: Block) -> Service:
    a = b.attrs()
    connect = None
    cb = b.first("connect")
    if cb is not None:
        sb = cb.first("sidecar_service")
        if sb is not None:
            proxy = {}
            pb = sb.first("proxy")
            if pb is not None:
                proxy["upstreams"] = [{
                    "destination_name":
                        str(u.attrs().get("destination_name", "")),
                    "local_bind_port":
                        int(u.attrs().get("local_bind_port", 0)),
                } for u in pb.blocks("upstreams")]
            connect = {"sidecar_service": {"proxy": proxy} if proxy
                       else {}}
    return Service(
        name=str(a.get("name", b.label(0))),
        port_label=str(a.get("port", "")),
        provider=str(a.get("provider", "consul")),
        tags=[str(t) for t in a.get("tags", [])],
        checks=[c.attrs() for c in b.blocks("check")],
        connect=connect)


def _resources(b: Block) -> Resources:
    a = b.attrs()
    res = Resources(
        cpu=int(a.get("cpu", 100)),
        cores=int(a.get("cores", 0)),
        memory_mb=int(a.get("memory", 300)),
        memory_max_mb=int(a.get("memory_max", 0)),
        disk_mb=int(a.get("disk", 0)))
    for n in b.blocks("network"):
        res.networks.append(_network(n))
    for d in b.blocks("device"):
        da = d.attrs()
        res.devices.append(DeviceRequest(
            name=d.label(0), count=int(da.get("count", 1)),
            constraints=[_constraint(c) for c in d.blocks("constraint")],
            affinities=[_affinity(c) for c in d.blocks("affinity")]))
    return res
