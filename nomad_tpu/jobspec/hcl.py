"""HCL tokenizer + parser (the generic half of the jobspec language).

The reference parses job files with HCL2 (reference: jobspec2/parse.go:21
using hashicorp/hcl/v2; legacy HCL1 in jobspec/). This is a from-scratch
parser for the HCL subset job files actually use: blocks with string
labels, attributes, strings with escape + ${...} interpolation (kept
verbatim for runtime interpolation unless it's a resolvable var/local
reference), numbers, bools, null, lists, objects, heredocs, and the three
comment forms. Output is a generic tree (Body of Attribute|Block) that
parse.py maps onto Job structs.
"""
from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union


class HclError(Exception):
    def __init__(self, msg: str, line: int = 0):
        super().__init__(f"line {line}: {msg}" if line else msg)
        self.line = line


@dataclass
class Attribute:
    name: str
    value: Any
    line: int = 0


@dataclass
class Block:
    type: str
    labels: List[str] = field(default_factory=list)
    body: List[Union["Block", Attribute]] = field(default_factory=list)
    line: int = 0

    # -- conveniences used by the mapper -------------------------------
    def attrs(self) -> Dict[str, Any]:
        return {i.name: i.value for i in self.body
                if isinstance(i, Attribute)}

    def blocks(self, btype: Optional[str] = None) -> List["Block"]:
        out = [i for i in self.body if isinstance(i, Block)]
        if btype is not None:
            out = [b for b in out if b.type == btype]
        return out

    def first(self, btype: str) -> Optional["Block"]:
        bs = self.blocks(btype)
        return bs[0] if bs else None

    def label(self, k: int = 0, default: str = "") -> str:
        return self.labels[k] if k < len(self.labels) else default


# ---------------------------------------------------------------------------
# tokenizer

_TOKEN_RE = re.compile(r"""
    (?P<ws>[ \t\r]+)
  | (?P<comment>\#[^\n]*|//[^\n]*|/\*.*?\*/)
  | (?P<heredoc><<-?(?P<hd_tag>[A-Za-z_][A-Za-z0-9_]*)\n)
  | (?P<string>"(?:\\.|\$\{[^}]*\}|[^"\\])*")
  | (?P<number>-?\d+(?:\.\d+)?(?![A-Za-z_]))
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.\-]*)
  | (?P<punct>[={}\[\],:\n()])
""", re.VERBOSE | re.DOTALL)


@dataclass
class Token:
    kind: str
    value: str
    line: int


def tokenize(src: str) -> List[Token]:
    tokens: List[Token] = []
    pos, line = 0, 1
    n = len(src)
    while pos < n:
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise HclError(f"unexpected character {src[pos]!r}", line)
        kind = m.lastgroup or ""
        text = m.group(0)
        if kind == "heredoc":
            tag = m.group("hd_tag")
            line += 1
            end_re = re.compile(rf"^[ \t]*{re.escape(tag)}[ \t]*$",
                                re.MULTILINE)
            em = end_re.search(src, m.end())
            if em is None:
                raise HclError(f"heredoc {tag} unterminated", line)
            content = src[m.end():em.start()]
            tokens.append(Token("string", content, line))
            line += content.count("\n") + 1
            pos = em.end()
            continue
        if kind == "ws":
            pass
        elif kind == "comment":
            line += text.count("\n")
        elif kind == "punct" and text == "\n":
            tokens.append(Token("newline", text, line))
            line += 1
        elif kind == "string":
            tokens.append(Token("string", _unquote(text, line), line))
        else:
            tokens.append(Token(kind, text, line))
        pos = m.end()
    tokens.append(Token("eof", "", line))
    return tokens


def _unquote(text: str, line: int) -> str:
    body = text[1:-1]
    out = []
    i = 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            esc = body[i + 1]
            out.append({"n": "\n", "t": "\t", '"': '"', "\\": "\\",
                        "r": "\r"}.get(esc, esc))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


# ---------------------------------------------------------------------------
# parser

def _fn_format(fmt, *args):
    """HCL2 format(): %s/%d/%v/%q/%.Nf via Python's printf."""
    out = str(fmt).replace("%v", "%s").replace("%q", '"%s"')
    return out % tuple(args)


# the HCL2 stdlib subset jobspecs actually use
# (reference: jobspec2/types.variables.go + hcl2 ext stdlib funcs)
FUNCTIONS: Dict[str, Any] = {
    "upper": lambda s: str(s).upper(),
    "lower": lambda s: str(s).lower(),
    "title": lambda s: str(s).title(),
    "trimspace": lambda s: str(s).strip(),
    "format": _fn_format,
    "join": lambda sep, xs: str(sep).join(str(x) for x in xs),
    "split": lambda sep, s: str(s).split(str(sep)),
    "replace": lambda s, a, b: str(s).replace(str(a), str(b)),
    "substr": lambda s, off, n: str(s)[int(off):int(off) + int(n)],
    "length": lambda x: len(x),
    "concat": lambda *ls: [x for sub in ls for x in sub],
    "contains": lambda xs, v: v in xs,
    "min": lambda *xs: min(xs),
    "max": lambda *xs: max(xs),
    "abs": lambda x: abs(x),
    "ceil": lambda x: math.ceil(float(x)),
    "floor": lambda x: math.floor(float(x)),
    "coalesce": lambda *xs: next((x for x in xs
                                  if x is not None and x != ""), None),
    "tostring": lambda x: str(x),
    "tonumber": lambda x: float(x) if "." in str(x) else int(x),
    "keys": lambda m: sorted(m.keys()),
    "values": lambda m: [m[k] for k in sorted(m.keys())],
    "merge": lambda *ms: {k: v for m in ms for k, v in m.items()},
    "range": lambda *a: list(range(*(int(x) for x in a))),
}

# type-constructor expressions, valid ONLY inside variable blocks
# (variable { type = list(string) }); evaluating them in the general
# expression language would silently turn list()/map() calls elsewhere
# into literal strings instead of a clear unknown-function error
TYPE_FUNCTIONS: Dict[str, Any] = {
    "list": lambda t="": f"list({t})",
    "set": lambda t="": f"set({t})",
    "map": lambda t="": f"map({t})",
}


class Parser:
    def __init__(self, tokens: List[Token],
                 variables: Optional[Dict[str, Any]] = None):
        self.tokens = tokens
        self.i = 0
        self.variables = variables if variables is not None else {}
        # enclosing-block stack: type constructors (list/set/map) only
        # evaluate inside `variable` blocks
        self._block_stack: List[str] = []

    def peek(self) -> Token:
        return self.tokens[self.i]

    def next(self) -> Token:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def skip_newlines(self) -> None:
        while self.peek().kind == "newline":
            self.next()

    def parse_body(self, root: bool = False) -> List[Union[Block, Attribute]]:
        items: List[Union[Block, Attribute]] = []
        while True:
            self.skip_newlines()
            t = self.peek()
            if t.kind == "eof":
                if not root:
                    raise HclError("unexpected EOF in block", t.line)
                return items
            if t.kind == "punct" and t.value == "}":
                if root:
                    raise HclError("unexpected '}'", t.line)
                return items
            if t.kind != "ident":
                raise HclError(f"expected identifier, got {t.value!r}",
                               t.line)
            items.append(self.parse_item())

    def parse_item(self) -> Union[Block, Attribute]:
        name = self.next()
        t = self.peek()
        if t.kind == "punct" and t.value == "=":
            self.next()
            value = self.parse_expr()
            return Attribute(name=name.value, value=value, line=name.line)
        # block: labels then {
        labels: List[str] = []
        while self.peek().kind in ("string", "ident"):
            labels.append(self.next().value)
        t = self.peek()
        if not (t.kind == "punct" and t.value == "{"):
            raise HclError(f"expected '{{' after {name.value}", t.line)
        self.next()
        self._block_stack.append(name.value)
        try:
            body = self.parse_body()
        finally:
            self._block_stack.pop()
        close = self.next()
        if not (close.kind == "punct" and close.value == "}"):
            raise HclError("expected '}'", close.line)
        return Block(type=name.value, labels=labels, body=body,
                     line=name.line)

    def parse_expr(self) -> Any:
        self.skip_newlines()
        t = self.next()
        if t.kind == "string":
            return self._interp(t.value, t.line)
        if t.kind == "number":
            return float(t.value) if "." in t.value else int(t.value)
        if t.kind == "ident":
            if t.value == "true":
                return True
            if t.value == "false":
                return False
            if t.value == "null":
                return None
            nxt = self.peek()
            if nxt.kind == "punct" and nxt.value == "(":
                return self._parse_call(t.value, t.line)
            return self._resolve_ref(t.value, t.line)
        if t.kind == "punct" and t.value == "[":
            return self._parse_list()
        if t.kind == "punct" and t.value == "{":
            return self._parse_object()
        raise HclError(f"unexpected token {t.value!r} in expression",
                       t.line)

    def _parse_list(self) -> List[Any]:
        out = []
        while True:
            self.skip_newlines()
            t = self.peek()
            if t.kind == "punct" and t.value == "]":
                self.next()
                return out
            out.append(self.parse_expr())
            self.skip_newlines()
            if self.peek().kind == "punct" and self.peek().value == ",":
                self.next()

    def _parse_object(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        while True:
            self.skip_newlines()
            t = self.peek()
            if t.kind == "punct" and t.value == "}":
                self.next()
                return out
            key = self.next()
            if key.kind not in ("ident", "string"):
                raise HclError(f"bad object key {key.value!r}", key.line)
            sep = self.next()
            if not (sep.kind == "punct" and sep.value in ("=", ":")):
                raise HclError("expected '=' or ':' in object", sep.line)
            out[key.value] = self.parse_expr()
            self.skip_newlines()
            if self.peek().kind == "punct" and self.peek().value == ",":
                self.next()

    def _parse_call(self, name: str, line: int) -> Any:
        """HCL2 function call (reference: jobspec2's hcl2 stdlib)."""
        self.next()                                 # consume '('
        args: List[Any] = []
        while True:
            self.skip_newlines()
            t = self.peek()
            if t.kind == "punct" and t.value == ")":
                self.next()
                break
            args.append(self.parse_expr())
            self.skip_newlines()
            if self.peek().kind == "punct" and self.peek().value == ",":
                self.next()
        fn = FUNCTIONS.get(name)
        if fn is None and name in TYPE_FUNCTIONS \
                and "variable" in self._block_stack:
            fn = TYPE_FUNCTIONS[name]
        if fn is None:
            raise HclError(f"unknown function {name!r}", line)
        try:
            return fn(*args)
        except HclError:
            raise
        except Exception as e:  # noqa: BLE001 -- user input
            raise HclError(f"{name}(): {e}", line)

    # -- references & interpolation ------------------------------------
    def _resolve_ref(self, path: str, line: int) -> Any:
        if path.startswith("var."):
            name = path[len("var."):]
            if name in self.variables:
                return self.variables[name]
            raise HclError(f"undefined variable {name!r}", line)
        if path.startswith("local."):
            name = path[len("local."):]
            if name in self.variables:
                return self.variables[name]
            raise HclError(f"undefined local {name!r}", line)
        # bare identifier (e.g. unquoted enum-ish value): keep as string
        return path

    _INTERP_RE = re.compile(r"\$\{(var|local)\.([A-Za-z0-9_\-]+)\}")
    _INTERP_EXPR_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*\([^{}]*\))\}")

    def _interp(self, s: str, line: int) -> str:
        """Substitute ${var.x}/${local.x} and parse-time function calls
        like ${upper(var.x)}; other ${...} (NOMAD_*, node.*, attr.*) are
        runtime interpolations and pass through verbatim."""

        def repl(m: re.Match) -> str:
            name = m.group(2)
            if name in self.variables:
                return str(self.variables[name])
            raise HclError(f"undefined variable {name!r}", line)

        s = self._INTERP_RE.sub(repl, s)

        def repl_fn(m: re.Match) -> str:
            inner = m.group(1)
            fname = inner.split("(", 1)[0]
            if fname not in FUNCTIONS:
                return m.group(0)     # not ours: runtime interpolation
            # every identifier argument must be a parse-time value
            # (var./local./literal); runtime refs like NOMAD_* or node.*
            # must pass through VERBATIM, not evaluate to their own name
            toks = tokenize(inner)
            for k, tok in enumerate(toks):
                if tok.kind != "ident":
                    continue
                nxt = toks[k + 1] if k + 1 < len(toks) else None
                is_call = (nxt is not None and nxt.kind == "punct"
                           and nxt.value == "(")
                if is_call or tok.value in ("true", "false", "null") \
                        or tok.value.startswith(("var.", "local.")):
                    continue
                return m.group(0)     # runtime reference: untouched
            sub = Parser(toks, variables=self.variables)
            return str(sub.parse_expr())

        return self._INTERP_EXPR_RE.sub(repl_fn, s)


def parse_hcl(src: str, variables: Optional[Dict[str, Any]] = None
              ) -> Block:
    """Parse source into a synthetic root Block. `variable` blocks at the
    root supply defaults; caller `variables` override them
    (reference: jobspec2 ParseWithConfig VarContent/ArgVars)."""
    tokens = tokenize(src)
    # first pass without variables to harvest variable/locals defaults
    defaults: Dict[str, Any] = {}
    declared: Dict[str, Dict[str, Any]] = {}
    probe = Parser(tokens, variables=_Everything())
    try:
        items = probe.parse_body(root=True)
    except HclError:
        items = None
    if items is not None:
        for it in items:
            if isinstance(it, Block) and it.type == "variable" and it.labels:
                attrs = it.attrs()
                declared[it.labels[0]] = attrs
                if "default" in attrs:
                    defaults[it.labels[0]] = attrs["default"]
    merged = dict(defaults)
    merged.update(variables or {})
    # declared-variable contract (reference: jobspec2 ParseWithConfig --
    # unset required variables fail UPFRONT with their names, and
    # provided values coerce to the declared type or error)
    missing = [n for n in declared
               if n not in merged]
    if missing:
        raise HclError(
            "missing required variable(s): " + ", ".join(sorted(missing)),
            0)
    for n, attrs in declared.items():
        want = str(attrs.get("type", "") or "")
        if n in merged and want:
            merged[n] = _coerce_var(n, merged[n], want)
    if items is not None and any(
            isinstance(it, Block) and it.type == "locals" for it in items):
        # locals may reference variables: re-evaluate them with the real
        # variable values. Unknown refs (e.g. a local used elsewhere in
        # the file) resolve to placeholders in THIS pass only.
        lp = Parser(tokens, variables=_Fallback(merged))
        for it in lp.parse_body(root=True):
            if isinstance(it, Block) and it.type == "locals":
                merged.update(it.attrs())
    parser = Parser(tokens, variables=merged)
    root = Block(type="root", body=parser.parse_body(root=True))
    return root


def _coerce_var(name: str, value: Any, want: str) -> Any:
    """Coerce a provided variable value to its declared type (CLI/-var
    values arrive as strings; reference: hcl2 convert.Convert against
    the declared cty type)."""
    try:
        if want == "number":
            if isinstance(value, (int, float)):
                return value
            s = str(value)
            return float(s) if "." in s else int(s)
        if want == "bool":
            if isinstance(value, bool):
                return value
            s = str(value).lower()
            if s in ("true", "1"):
                return True
            if s in ("false", "0"):
                return False
            raise ValueError(s)
        if want == "string":
            return value if isinstance(value, str) else str(value)
        if want.startswith("list"):
            if isinstance(value, list):
                return value
            return [p.strip() for p in str(value).split(",") if p.strip()]
    except (ValueError, TypeError):
        raise HclError(
            f"variable {name!r}: value {value!r} does not match "
            f"declared type {want}", 0) from None
    return value        # unknown/complex type expressions: pass through


class _Fallback(dict):
    """Resolves known names to their real values, everything else to ''."""

    def __contains__(self, key) -> bool:
        return True

    def __getitem__(self, key):
        return self.get(key, "")


class _Everything(dict):
    """Probe-pass variable context: resolves anything to a placeholder so
    the first parse succeeds before defaults are known."""

    def __contains__(self, key) -> bool:
        return True

    def __getitem__(self, key):
        return ""
