"""Job specification language (reference: /root/reference/jobspec2/)."""
from .hcl import Block, HclError, parse_hcl  # noqa: F401
from .parse import duration, parse, parse_file  # noqa: F401
