"""Multi-chip sharding of the solver over a jax.sharding.Mesh."""
from .mesh import make_mesh, pick_mesh, shard_solver_inputs  # noqa: F401
