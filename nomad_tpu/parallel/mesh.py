"""Device-mesh sharding of the solver: the multi-chip scale path.

The reference scales scheduling by running NumCPU workers per server x M
servers against snapshots (SURVEY.md section 2.6); the TPU-native analog
shards two axes over a jax.sharding.Mesh:
  - ``evals``  (data-parallel): independent evaluations, one snapshot each;
  - ``nodes``  (model-parallel): the fleet axis inside every eval -- fit and
    scoring are elementwise over nodes, and the select/argmax reductions
    become cross-shard collectives that XLA inserts automatically (psum/
    all-gather over ICI), per the standard pick-mesh -> annotate ->
    let-XLA-insert-collectives recipe.

No NCCL/MPI analog is needed: collectives ride ICI within a slice and DCN
across slices, and the host-side control plane (raft-analog, plan applier)
stays on CPU exactly as nomad/plan_apply.go stays authoritative.

This module is also the repo's ONE home for sharding intent (ISSUE 15):
``SPEC_GROUPS`` declares the intended ``PartitionSpec`` per dispatch tree
group, every ``Mesh`` is built by a factory here, and every
``jax.device_put`` carrying a ``NamedSharding`` lives here -- enforced
statically by nomadlint's spec-declared / mesh-factory / no-implicit-put
rules and at runtime by the sharding-discipline sanitizer
(nomad_tpu/shardcheck.py), which compares what XLA actually did against
what this registry declares.
"""
from __future__ import annotations

import functools
import threading
from typing import Optional

import numpy as np


def _single_flight(fn):
    """Serialize program-factory invocations: lru_cache does not
    single-flight, so two pipelined generations racing one cold
    (mesh, statics) bucket would both trace/compile the program --
    wasted seconds of XLA work and jitcheck's fresh-identical-closure
    retrace pattern (same guard as the solver/binpack.py factories)."""
    lock = threading.Lock()

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with lock:
            return fn(*args, **kwargs)
    # the lru wrapper's cache management stays reachable (tests and
    # the jitcheck gauntlet rebuild buckets via cache_clear); not a
    # store-derived memo, so version-keyed-memo has nothing to key
    for attr in ("cache_clear", "cache_info"):
        setattr(wrapped, attr, getattr(fn, attr))
    return wrapped


def make_mesh(n_devices: Optional[int] = None,
              eval_parallel: Optional[int] = None):
    """Build a 2D (evals, nodes) mesh over the available devices."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if eval_parallel is None:
        # favor eval-parallelism (perfectly parallel) over node sharding:
        # give the evals axis the LARGER factor of the balanced split
        eval_parallel = n
        for cand in range(int(np.floor(np.sqrt(n))), 0, -1):
            if n % cand == 0:
                eval_parallel = n // cand
                break
    node_parallel = n // eval_parallel
    dev_grid = np.asarray(devices).reshape(eval_parallel, node_parallel)
    return Mesh(dev_grid, ("evals", "nodes"))


def pick_mesh(e: int, n: int, n_devices: Optional[int] = None):
    """Choose an (evals, nodes) grid that divides THIS batch's shapes:
    e_par = largest divisor of the eval axis that fits the device count,
    n_par = largest divisor of the (padded) node axis using the remaining
    devices. Falls back to pure node-sharding for E=1, so a single big
    eval still spreads over all chips. Returns None when fewer than 2
    devices can be used."""
    import jax

    d = n_devices if n_devices is not None else jax.device_count()
    if d <= 1 or e < 1 or n < 1:
        return None

    def largest_divisor(x: int, cap: int) -> int:
        return next(c for c in range(min(x, cap), 0, -1) if x % c == 0)

    # choose the split that uses the MOST devices (a greedy eval-first
    # pick can strand chips, e.g. E=3 on 8 devices -> 3x2 when 1x8 uses
    # all); prefer eval-parallelism among equals (perfectly parallel)
    best = (1, 1)
    for e_par in range(min(e, d), 0, -1):
        if e % e_par:
            continue
        n_par = largest_divisor(n, d // e_par)
        if e_par * n_par > best[0] * best[1]:
            best = (e_par, n_par)
    e_par, n_par = best
    if e_par * n_par < 2:
        return None
    return make_mesh(e_par * n_par, eval_parallel=e_par)


@functools.lru_cache(maxsize=None)
def eval_axis_mesh(n_devices: int):
    """1D ('evals',) mesh over the first ``n_devices`` devices -- the
    wave/wave-preempt compact transports shard only their fused eval
    axis (per-step work is O(B); nothing N-heavy to split)."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n_devices]), ("evals",))


# ----------------------------------------------------------------------
# sharding-spec registry (ISSUE 15): the declared PartitionSpec per
# dispatch tree group.  ``shard_solver_inputs`` puts by these specs, the
# shardcheck sanitizer compares every mesh callable's actual shardings
# against them, and ``shardcheck --compile-audit`` prints the per-group
# per-shard byte budgets they imply.  A spec change here IS the reviewed
# sharding-contract change; constructing PartitionSpec/NamedSharding
# anywhere outside nomad_tpu/parallel/ is a lint violation
# (spec-declared).


def const_partition_specs(c):
    """NodeConst: per-node columns shard (evals, nodes); per-eval
    scalars/tables without a node axis shard (evals) only."""
    from jax.sharding import PartitionSpec as P

    return type(c)(
        cpu_cap=P("evals", "nodes"), mem_cap=P("evals", "nodes"),
        disk_cap=P("evals", "nodes"), feasible=P("evals", "nodes"),
        affinity=P("evals", "nodes"), has_affinity=P("evals"),
        distinct_hosts=P("evals"), distinct_job_level=P("evals"),
        spread_vidx=P("evals", None, "nodes"),
        spread_desired=P("evals"), spread_has_targets=P("evals"),
        spread_weights=P("evals"), spread_sum_weights=P("evals"),
        n_spreads=P("evals"),
        dp_vidx=P("evals", None, "nodes"), dp_limit=P("evals"),
        dp_tg_scope=P("evals"),
        dev_aff=P("evals", None, None, "nodes"),
        dev_count=P("evals"), dev_sum_weight=P("evals"),
        mhz_per_core=P("evals", "nodes"))


def state_partition_specs(s):
    """NodeState: usage columns shard (evals, nodes); spread/distinct
    counters are per-eval tables."""
    from jax.sharding import PartitionSpec as P

    return type(s)(
        used_cpu=P("evals", "nodes"), used_mem=P("evals", "nodes"),
        used_disk=P("evals", "nodes"), placed=P("evals", "nodes"),
        placed_job=P("evals", "nodes"),
        static_free=P("evals", "nodes"), dyn_avail=P("evals", "nodes"),
        spread_counts=P("evals"),
        dp_counts=P("evals"),
        dev_free=P("evals", None, None, "nodes"),
        cores_free=P("evals", "nodes"))


def batch_partition_specs(b):
    """PlacementBatch: every per-placement column is (E, P) --
    data-parallel on the eval axis, replicated over node shards."""
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(lambda _leaf: P("evals"), b)


def output_partition_specs(out):
    """Mesh solve outputs gather fully replicated: the select/argmax
    collectives ARE the program's sanctioned cross-shard traffic, and
    the single bulk fetch reads identical buffers from any device."""
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(lambda _leaf: P(), out)


def eval_axis_partition_specs(tree):
    """Wave/wave-preempt compact tables: leading fused-eval axis only."""
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(lambda _leaf: P("evals"), tree)


# group tag -> spec-tree builder; the tags line up with the transfer
# ledger's tree groups (solver/xferobs.py) so the shardcheck per-shard
# byte rows land next to the bytes they decompose
SPEC_GROUPS = {
    "mesh_const": const_partition_specs,
    "mesh_init": state_partition_specs,
    "mesh_batch": batch_partition_specs,
    "mesh_out": output_partition_specs,
    "compact": eval_axis_partition_specs,
    "compact_preempt": eval_axis_partition_specs,
}


def declared_specs(group: str, tree):
    """The registry's intended PartitionSpec tree for ``tree`` under
    ``group`` (KeyError on an unregistered group: a new dispatch tree
    group must declare its sharding here first)."""
    return SPEC_GROUPS[group](tree)


@_single_flight
@functools.lru_cache(maxsize=None)
def mesh_solve_fn(mesh, spread_alg: bool, dtype_name: str):
    """One jitted mesh-sharded dense-solve program per (mesh, static
    args). jax.sharding.Mesh hashes by device grid + axis names, so
    the fresh-but-equal Mesh each pick_mesh() builds hits this cache
    -- the dispatch path used to construct a new ``jax.jit`` closure
    per fused dispatch, which re-traced the whole program every
    generation (the exact steady-state-retrace class jitcheck.py
    exists to catch; nomadlint's no-callsite-jit pins the fix)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..solver.binpack import solve_eval_batch

    return jax.jit(
        lambda c, i, b: solve_eval_batch(
            c, i, b, spread_alg=spread_alg, dtype_name=dtype_name),
        out_shardings=NamedSharding(mesh, P()))


def shard_solver_inputs(mesh, const, init, batch):
    """NamedShardings for solve_eval_batch inputs, by the registry's
    declared specs: leading axis (E) on 'evals'; node-axis (last dim of
    per-node arrays) on 'nodes'.

    Sharded puts bypass the device-resident const cache (it pins
    unsharded single-device buffers), but they still report their
    payload so ``nomad.solver.dispatch_bytes`` covers every transport
    path."""
    import jax
    from jax.sharding import NamedSharding

    from ..solver import xferobs
    from ..solver.constcache import note_dispatch_bytes
    # per-tree ledger attribution rides the same walk the byte counter
    # uses, so mesh-sharded puts decompose like the fused transport's
    # (gated so the kill switch skips the extra tree walks entirely)
    if xferobs.enabled():
        for name, tree in (("const", const), ("init", init),
                           ("batch", batch)):
            xferobs.note_payload("mesh_" + name, sum(
                np.asarray(leaf).nbytes
                for leaf in jax.tree_util.tree_leaves(tree)))
    note_dispatch_bytes(sum(
        np.asarray(leaf).nbytes
        for tree in (const, init, batch)
        for leaf in jax.tree_util.tree_leaves(tree)))

    def put(group, tree):
        specs = declared_specs(group, tree)
        return jax.tree.map(
            lambda leaf, s: jax.device_put(leaf, NamedSharding(mesh, s)),
            tree, specs)

    return (put("mesh_const", const), put("mesh_init", init),
            put("mesh_batch", batch))


def shard_eval_axis(trees, tag: str = "compact"):
    """Device-put a tuple of (possibly nested) arrays, sharding the
    leading eval axis across ALL attached devices. The fused eval axis
    is embarrassingly data-parallel: each chip runs its lanes' scans
    independently (no collectives; outputs gather on fetch). Callers
    (solver/binpack.py ``_put_eval_sharded``) gate on divisibility;
    ``tag`` is the transfer ledger's tree-group attribution."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..solver import xferobs
    from ..solver.constcache import note_dispatch_bytes

    mesh = eval_axis_mesh(jax.device_count())
    total = sum(
        np.asarray(leaf).nbytes
        for leaf in jax.tree_util.tree_leaves(trees))
    note_dispatch_bytes(total)
    xferobs.note_payload(tag, total)
    sharding = NamedSharding(mesh, P("evals"))
    return tuple(
        jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), t)
        for t in trees)
