"""Device-mesh sharding of the solver: the multi-chip scale path.

The reference scales scheduling by running NumCPU workers per server x M
servers against snapshots (SURVEY.md section 2.6); the TPU-native analog
shards two axes over a jax.sharding.Mesh:
  - ``evals``  (data-parallel): independent evaluations, one snapshot each;
  - ``nodes``  (model-parallel): the fleet axis inside every eval -- fit and
    scoring are elementwise over nodes, and the select/argmax reductions
    become cross-shard collectives that XLA inserts automatically (psum/
    all-gather over ICI), per the standard pick-mesh -> annotate ->
    let-XLA-insert-collectives recipe.

No NCCL/MPI analog is needed: collectives ride ICI within a slice and DCN
across slices, and the host-side control plane (raft-analog, plan applier)
stays on CPU exactly as nomad/plan_apply.go stays authoritative.

This module is also the repo's ONE home for sharding intent (ISSUE 15):
``SPEC_GROUPS`` declares the intended ``PartitionSpec`` per dispatch tree
group, every ``Mesh`` is built by a factory here, and every
``jax.device_put`` carrying a ``NamedSharding`` lives here -- enforced
statically by nomadlint's spec-declared / mesh-factory / no-implicit-put
rules and at runtime by the sharding-discipline sanitizer
(nomad_tpu/shardcheck.py), which compares what XLA actually did against
what this registry declares.
"""
from __future__ import annotations

import functools
import os
import threading
from typing import Optional

import numpy as np


def mesh_enabled() -> bool:
    """The mesh-execution master switch (ISSUE 19). On (default) the
    dispatch stack shards over the device mesh whenever >1 device is
    attached and the shapes divide a grid; ``NOMAD_TPU_MESH=0`` makes
    every factory below refuse a mesh, so every solve runs the
    single-device program path bit-for-bit -- the rollback lever the
    OPERATIONS.md mesh runbook documents."""
    return os.environ.get("NOMAD_TPU_MESH", "1") != "0"


def _single_flight(fn):
    """Serialize program-factory invocations: lru_cache does not
    single-flight, so two pipelined generations racing one cold
    (mesh, statics) bucket would both trace/compile the program --
    wasted seconds of XLA work and jitcheck's fresh-identical-closure
    retrace pattern (same guard as the solver/binpack.py factories)."""
    lock = threading.Lock()

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with lock:
            return fn(*args, **kwargs)
    # the lru wrapper's cache management stays reachable (tests and
    # the jitcheck gauntlet rebuild buckets via cache_clear); not a
    # store-derived memo, so version-keyed-memo has nothing to key
    for attr in ("cache_clear", "cache_info"):
        setattr(wrapped, attr, getattr(fn, attr))
    return wrapped


def make_mesh(n_devices: Optional[int] = None,
              eval_parallel: Optional[int] = None):
    """Build a 2D (evals, nodes) mesh over the available devices."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if eval_parallel is None:
        # favor eval-parallelism (perfectly parallel) over node sharding:
        # give the evals axis the LARGER factor of the balanced split
        eval_parallel = n
        for cand in range(int(np.floor(np.sqrt(n))), 0, -1):
            if n % cand == 0:
                eval_parallel = n // cand
                break
    node_parallel = n // eval_parallel
    dev_grid = np.asarray(devices).reshape(eval_parallel, node_parallel)
    return Mesh(dev_grid, ("evals", "nodes"))


def pick_mesh(e: int, n: int, n_devices: Optional[int] = None):
    """Choose an (evals, nodes) grid that divides THIS batch's shapes:
    e_par = largest divisor of the eval axis that fits the device count,
    n_par = largest divisor of the (padded) node axis using the remaining
    devices. Falls back to pure node-sharding for E=1, so a single big
    eval still spreads over all chips. Returns None when fewer than 2
    devices can be used. ``NOMAD_TPU_MESH=0`` always returns None --
    the one chokepoint every production mesh route picks through."""
    import jax

    if not mesh_enabled():
        return None
    d = n_devices if n_devices is not None else jax.device_count()
    if d <= 1 or e < 1 or n < 1:
        return None

    def largest_divisor(x: int, cap: int) -> int:
        return next(c for c in range(min(x, cap), 0, -1) if x % c == 0)

    # choose the split that uses the MOST devices (a greedy eval-first
    # pick can strand chips, e.g. E=3 on 8 devices -> 3x2 when 1x8 uses
    # all); prefer eval-parallelism among equals (perfectly parallel)
    best = (1, 1)
    for e_par in range(min(e, d), 0, -1):
        if e % e_par:
            continue
        n_par = largest_divisor(n, d // e_par)
        if e_par * n_par > best[0] * best[1]:
            best = (e_par, n_par)
    e_par, n_par = best
    if e_par * n_par < 2:
        return None
    return make_mesh(e_par * n_par, eval_parallel=e_par)


@functools.lru_cache(maxsize=None)
def eval_axis_mesh(n_devices: int):
    """1D ('evals',) mesh over the first ``n_devices`` devices -- the
    wave/wave-preempt compact transports shard only their fused eval
    axis (per-step work is O(B); nothing N-heavy to split)."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n_devices]), ("evals",))


# ----------------------------------------------------------------------
# sharding-spec registry (ISSUE 15): the declared PartitionSpec per
# dispatch tree group.  ``shard_solver_inputs`` puts by these specs, the
# shardcheck sanitizer compares every mesh callable's actual shardings
# against them, and ``shardcheck --compile-audit`` prints the per-group
# per-shard byte budgets they imply.  A spec change here IS the reviewed
# sharding-contract change; constructing PartitionSpec/NamedSharding
# anywhere outside nomad_tpu/parallel/ is a lint violation
# (spec-declared).


def const_partition_specs(c):
    """NodeConst: per-node columns shard (evals, nodes); per-eval
    scalars/tables without a node axis shard (evals) only."""
    from jax.sharding import PartitionSpec as P

    return type(c)(
        cpu_cap=P("evals", "nodes"), mem_cap=P("evals", "nodes"),
        disk_cap=P("evals", "nodes"), feasible=P("evals", "nodes"),
        affinity=P("evals", "nodes"), has_affinity=P("evals"),
        distinct_hosts=P("evals"), distinct_job_level=P("evals"),
        spread_vidx=P("evals", None, "nodes"),
        spread_desired=P("evals"), spread_has_targets=P("evals"),
        spread_weights=P("evals"), spread_sum_weights=P("evals"),
        n_spreads=P("evals"),
        dp_vidx=P("evals", None, "nodes"), dp_limit=P("evals"),
        dp_tg_scope=P("evals"),
        dev_aff=P("evals", None, None, "nodes"),
        dev_count=P("evals"), dev_sum_weight=P("evals"),
        mhz_per_core=P("evals", "nodes"))


def state_partition_specs(s):
    """NodeState: usage columns shard (evals, nodes); spread/distinct
    counters are per-eval tables."""
    from jax.sharding import PartitionSpec as P

    return type(s)(
        used_cpu=P("evals", "nodes"), used_mem=P("evals", "nodes"),
        used_disk=P("evals", "nodes"), placed=P("evals", "nodes"),
        placed_job=P("evals", "nodes"),
        static_free=P("evals", "nodes"), dyn_avail=P("evals", "nodes"),
        spread_counts=P("evals"),
        dp_counts=P("evals"),
        dev_free=P("evals", None, None, "nodes"),
        cores_free=P("evals", "nodes"))


def batch_partition_specs(b):
    """PlacementBatch: every per-placement column is (E, P) --
    data-parallel on the eval axis, replicated over node shards."""
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(lambda _leaf: P("evals"), b)


def output_partition_specs(out):
    """Mesh solve outputs gather fully replicated: the select/argmax
    collectives ARE the program's sanctioned cross-shard traffic, and
    the single bulk fetch reads identical buffers from any device."""
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(lambda _leaf: P(), out)


def eval_axis_partition_specs(tree):
    """Wave/wave-preempt compact tables: leading fused-eval axis only."""
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(lambda _leaf: P("evals"), tree)


def lpq_partition_specs(tree):
    """LPQ relaxation inputs ``(V, feas, ask, pcount, free, active)``:
    the (L, N) lane-major matrices shard lanes on 'evals'; the small
    per-lane ask/count vectors and the (N, 3) free-capacity table
    replicate.  The dual-price ascent's cross-shard combine is an
    all-gather of the lane shards, NOT a psum -- gathering moves bytes
    without re-associating the float reduction, which keeps the mesh
    program bit-for-bit the single-device one (see mesh_lpq_fn)."""
    from jax.sharding import PartitionSpec as P

    if len(tree) != 6:
        raise ValueError(
            f"lpq_in expects the 6-tuple (V, feas, ask, pcount, free, "
            f"active), got {len(tree)} leaves")
    return (P("evals", None), P("evals", None), P(), P(), P(), P())


# group tag -> spec-tree builder; the tags line up with the transfer
# ledger's tree groups (solver/xferobs.py) so the shardcheck per-shard
# byte rows land next to the bytes they decompose
SPEC_GROUPS = {
    "mesh_const": const_partition_specs,
    "mesh_init": state_partition_specs,
    "mesh_batch": batch_partition_specs,
    "mesh_out": output_partition_specs,
    "compact": eval_axis_partition_specs,
    "compact_preempt": eval_axis_partition_specs,
    "lpq_in": lpq_partition_specs,
    "lpq_out": output_partition_specs,
}


def declared_specs(group: str, tree):
    """The registry's intended PartitionSpec tree for ``tree`` under
    ``group`` (KeyError on an unregistered group: a new dispatch tree
    group must declare its sharding here first)."""
    return SPEC_GROUPS[group](tree)


@_single_flight
@functools.lru_cache(maxsize=None)
def mesh_solve_fn(mesh, spread_alg: bool, dtype_name: str):
    """One jitted mesh-sharded dense-solve program per (mesh, static
    args). jax.sharding.Mesh hashes by device grid + axis names, so
    the fresh-but-equal Mesh each pick_mesh() builds hits this cache
    -- the dispatch path used to construct a new ``jax.jit`` closure
    per fused dispatch, which re-traced the whole program every
    generation (the exact steady-state-retrace class jitcheck.py
    exists to catch; nomadlint's no-callsite-jit pins the fix).

    The program returns only (chosen, scores, n_yielded): the trailing
    NodeState the single-device kernel also yields is (E, N)-sized and
    was never read by the mesh route, yet replicated out_shardings
    forced a full cross-shard all-gather of it every dispatch --
    dropping it from the traced outputs lets XLA dead-code the gather
    (the dominant output bytes at fleet-scale N)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..solver.binpack import solve_eval_batch

    return jax.jit(
        lambda c, i, b: solve_eval_batch(
            c, i, b, spread_alg=spread_alg, dtype_name=dtype_name)[:3],
        out_shardings=NamedSharding(mesh, P()))


@_single_flight
@functools.lru_cache(maxsize=None)
def mesh_delta_scatter_fn(mesh, shape: tuple, dtype_str: str,
                          n_upd: int, spec):
    """One jitted mesh-sharded delta-scatter program per (mesh, table
    shape, dtype, update-count bucket, declared spec) -- the ISSUE-20
    device-side update under NOMAD_TPU_MESH. Coordinate formulation
    (the single-device program in solver/constcache.py scatters flat
    indices): a sharded operand must never reshape to 1D across
    shards, so the host unravels the flat diff indices into per-axis
    coordinates and the program scatters in the table's native rank.
    ``out_shardings`` pins the promoted buffer to the SAME declared
    PartitionSpec as the resident table (SPEC_GROUPS discipline): the
    replicated (coords, vals) payload reaches every device and each
    nodes-axis shard keeps exactly the updates that land in its slice
    -- whatever collective XLA inserts for that routing is recorded
    and budgeted by ``shardcheck --compile-audit`` beside the solve
    programs' argmax/all-gather baselines. No donation: the base
    buffer may still be referenced by in-flight dispatches."""
    import jax
    from jax.sharding import NamedSharding

    del dtype_str, n_upd   # dtypes/shapes ride the traced args; they
    #                        key the cache (one program per bucket)
    out = NamedSharding(mesh, spec)
    ndim = len(shape)

    def _apply(buf, coords, vals):
        return buf.at[tuple(coords[d] for d in range(ndim))].set(vals)

    return jax.jit(_apply, out_shardings=out)


def _note_shard_rows(mesh, group: str, tree, specs) -> None:
    """Fold this tree's per-shard declared/actual byte rows into the
    transfer ledger (xferobs ``per_shard``): declared = what the
    registry's spec budgets per device, actual = the shard bytes the
    NamedSharding put actually gives each device. The production-path
    twin of shardcheck's audit rows (same ``d<id>`` labels), so mesh
    dispatches decompose per shard even with the sanitizer off."""
    import jax
    from jax.sharding import NamedSharding

    from ..solver import xferobs

    leaves = jax.tree_util.tree_leaves(tree)
    spec_leaves = jax.tree_util.tree_leaves(specs)
    per_dev = 0
    for leaf, spec in zip(leaves, spec_leaves):
        arr = np.asarray(leaf)
        shard_shape = NamedSharding(mesh, spec).shard_shape(arr.shape)
        per_dev += int(np.prod(shard_shape, dtype=np.int64)
                       * arr.dtype.itemsize)
    for dev in mesh.devices.flat:
        xferobs.note_shard_bytes(group, f"d{dev.id}", per_dev, per_dev)


def shard_solver_inputs(mesh, const, init, batch, version=None,
                        delta_src=None):
    """NamedShardings for solve_eval_batch inputs, by the registry's
    declared specs: leading axis (E) on 'evals'; node-axis (last dim of
    per-node arrays) on 'nodes'.

    The const tree routes through the device-resident cache's
    per-shard path (solver/constcache.py device_put_sharded_cached):
    each shard slice is content-fingerprinted and pinned per device,
    so repeated fleet tables ship zero bytes and a node-table write
    re-uploads only the shards whose slice actually changed.
    ``version`` is the packing snapshot's node_table_index (hygiene
    eviction). The usage tree (mesh_init) routes through the ISSUE-20
    version chain when ``delta_src`` (the packing snapshot's
    (store, index)) is given: journal-covered generations ship only
    the changed elements, replicated, and the mesh-sharded scatter
    (mesh_delta_scatter_fn) applies them into the resident sharded
    buffer under the SAME declared spec -- each nodes-axis shard keeps
    the updates that land in its slice. batch ships fresh -- it
    changes every generation -- but still reports payload and
    per-shard rows so ``nomad.solver.dispatch_bytes`` and the ledger's
    ``per_shard`` decomposition cover every transport path."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..solver import constcache, xferobs
    from ..solver.constcache import note_dispatch_bytes

    def put_fresh(group, tree):
        specs = declared_specs(group, tree)
        total = sum(np.asarray(leaf).nbytes
                    for leaf in jax.tree_util.tree_leaves(tree))
        if xferobs.enabled():
            xferobs.note_payload(group, total)
            _note_shard_rows(mesh, group, tree, specs)
        note_dispatch_bytes(total)
        return jax.tree.map(
            lambda leaf, s: jax.device_put(leaf, NamedSharding(mesh, s)),
            tree, specs)

    def put_chain(group, tree):
        # ISSUE-20 delta route for the usage tree: per-leaf version
        # chain (solver/constcache.py chain_apply) with a mesh-sharded
        # scatter. The fuse arena reuses these host buffers across
        # generations, so chain_apply copies its shadow
        # (copy_shadow=True). Chain keys carry the Mesh itself: a grid
        # change re-installs rather than scattering into a buffer
        # sharded under the old grid.
        store = token = None
        if delta_src is not None and constcache.delta_stream_enabled():
            store, token = delta_src
            if token is None or not hasattr(store, "alloc_deltas_since"):
                store = token = None
        if store is None:
            return put_fresh(group, tree)
        specs = declared_specs(group, tree)
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        spec_leaves = treedef.flatten_up_to(specs)
        min_b = constcache._min_bytes()
        rep = NamedSharding(mesh, P())
        bufs = []
        shipped = 0
        small_total = 0
        for j, (leaf, spec) in enumerate(zip(leaves, spec_leaves)):
            arr = np.asarray(leaf)
            sh = NamedSharding(mesh, spec)
            if arr.nbytes < min_b:
                # small leaves ARE the delta traffic; ship by spec
                bufs.append(jax.device_put(arr, sh))
                shipped += arr.nbytes
                small_total += arr.nbytes
                continue

            def scatter(buf, shape, dtype_str, idx_p, vals_p,
                        _spec=spec):
                # unravel the flat diff indices into per-axis
                # coordinates (a sharded operand must never reshape to
                # 1D across shards); the replicated puts below ARE the
                # delta payload crossing the wire
                coords = np.ascontiguousarray(np.stack(
                    np.unravel_index(idx_p.astype(np.int64),
                                     shape)).astype(np.int32))
                pc = jax.device_put(coords, rep)
                pv = jax.device_put(vals_p, rep)
                prog = mesh_delta_scatter_fn(
                    mesh, shape, dtype_str, int(idx_p.size), _spec)
                return prog(buf, pc, pv)

            buf, ship_j, _outcome = constcache.chain_apply(
                (group, arr.dtype.str, arr.shape, j, mesh),
                arr, store, token, group,
                put_fn=lambda a, _sh=sh: jax.device_put(a, _sh),
                scatter=scatter,
                idx_width=4 * max(1, arr.ndim),
                copy_shadow=True)
            bufs.append(buf)
            shipped += ship_j
        if xferobs.enabled():
            if small_total:
                xferobs.note_payload(group, small_total)
            _note_shard_rows(mesh, group, tree, specs)
        note_dispatch_bytes(shipped)
        return jax.tree_util.tree_unflatten(treedef, bufs)

    specs = declared_specs("mesh_const", const)
    leaves, treedef = jax.tree_util.tree_flatten(const)
    shardings = [NamedSharding(mesh, s)
                 for s in treedef.flatten_up_to(specs)]
    buffers, _shipped = constcache.device_put_sharded_cached(
        leaves, shardings, group="mesh_const", version=version,
        fallback_put=lambda arr, sh: jax.device_put(arr, sh))
    s_const = jax.tree_util.tree_unflatten(treedef, buffers)
    return (s_const, put_chain("mesh_init", init),
            put_fresh("mesh_batch", batch))


@_single_flight
@functools.lru_cache(maxsize=16)
def mesh_lpq_fn(mesh, L_pad: int, N: int, steps: int):
    """One pjit LPQ-relaxation program per (mesh, shape bucket) --
    same lru + single-flight discipline as mesh_solve_fn.  Lanes (L)
    shard on 'evals' per the lpq_in registry specs; node tables
    replicate.  The per-step softmax/pricing math is shard-local
    (row-wise, bit-exact), and the dual-price load reduction is forced
    through an all-gather (with_sharding_constraint to replicated) so
    the einsum over lanes runs whole on every device: gathering moves
    bytes, not sums, so the mesh output is bit-for-bit the
    single-device program's.  A psum here would re-associate the f32
    reduction and the annealing loop amplifies that ulp noise into
    placement flips (measured on the virtual CPU mesh)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..solver.lpq import _lp_solve_body

    del L_pad  # shapes ride the traced args; L_pad keys the cache
    rep = NamedSharding(mesh, P())
    body = _lp_solve_body(
        N, steps,
        gather=lambda x: jax.lax.with_sharding_constraint(x, rep))
    return jax.jit(body, out_shardings=rep)


def shard_lpq_inputs(mesh, V, feas, ask, pcount, free, active):
    """NamedShardings for the LPQ relaxation inputs by the registry's
    ``lpq_in`` specs, with transfer-ledger attribution (one ``lpq``
    tree group + per-shard rows). No const-cache routing: V/feas are
    usage-dependent and change every solve."""
    import jax
    from jax.sharding import NamedSharding

    from ..solver import xferobs
    from ..solver.constcache import note_dispatch_bytes

    tree = (V, feas, ask, pcount, free, active)
    specs = declared_specs("lpq_in", tree)
    total = sum(np.asarray(a).nbytes for a in tree)
    if xferobs.enabled():
        xferobs.note_payload("lpq", total)
        _note_shard_rows(mesh, "lpq", tree, specs)
    note_dispatch_bytes(total)
    return tuple(jax.device_put(a, NamedSharding(mesh, s))
                 for a, s in zip(tree, specs))


def shard_eval_axis(trees, tag: str = "compact"):
    """Device-put a tuple of (possibly nested) arrays, sharding the
    leading eval axis across ALL attached devices. The fused eval axis
    is embarrassingly data-parallel: each chip runs its lanes' scans
    independently (no collectives; outputs gather on fetch). Callers
    (solver/binpack.py ``_put_eval_sharded``) gate on divisibility;
    ``tag`` is the transfer ledger's tree-group attribution."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..solver import xferobs
    from ..solver.constcache import note_dispatch_bytes

    mesh = eval_axis_mesh(jax.device_count())
    total = sum(
        np.asarray(leaf).nbytes
        for leaf in jax.tree_util.tree_leaves(trees))
    note_dispatch_bytes(total)
    xferobs.note_payload(tag, total)
    sharding = NamedSharding(mesh, P("evals"))
    return tuple(
        jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), t)
        for t in trees)
