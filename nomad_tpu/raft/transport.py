"""TCP transport: length-prefixed JSON request/response RPC.

The reference multiplexes msgpack-RPC over yamux on one TCP listener
(reference: nomad/rpc.go:24,409) and runs raft on its own stream layer
(server.go:1399). Equivalent here: one listener per server; each RPC is a
fresh connection carrying a 4-byte big-endian length + JSON request, and
the same framing back. Handlers are registered by message type; raft RPCs
and server->leader forwarding share the transport.
"""
from __future__ import annotations

import json
import socket
import ssl
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

Addr = Tuple[str, int]
_LEN = struct.Struct(">I")
MAX_MSG = 256 << 20


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, 4))
    if length > MAX_MSG:
        raise ConnectionError(f"frame too large: {length}")
    return json.loads(_recv_exact(sock, length))


class TcpTransport:
    """Listener + dispatcher. `register(msg_type, handler)` wires a
    callable(dict) -> dict; `send(addr, msg)` performs one blocking RPC."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 tls=None) -> None:
        # mutual TLS on every server<->server conn when configured
        # (reference: nomad/rpc.go:31 TLS wrapping of the RPC listener)
        self.tls = tls if tls is not None and tls.enable_rpc else None
        self._server_ctx = self._client_ctx = None
        if self.tls is not None:
            from ..tlsutil import client_context, server_context
            self._server_ctx = server_context(self.tls)
            self._client_ctx = client_context(self.tls)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.addr: Addr = self._listener.getsockname()
        self._handlers: Dict[str, Callable[[dict], dict]] = {}
        self._shutdown = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        # outbound connection pool: one persistent conn per peer addr
        # (reference: helper/pool ConnPool reuses yamux sessions)
        self._pool: Dict[Addr, Tuple[socket.socket, threading.Lock]] = {}
        self._pool_lock = threading.Lock()

    def register(self, msg_type: str, handler: Callable[[dict], dict]) -> None:
        self._handlers[msg_type] = handler

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"transport-{self.addr[1]}")
        self._accept_thread.start()

    def shutdown(self) -> None:
        self._shutdown.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._pool_lock:
            for sock, _ in self._pool.values():
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            self._pool.clear()

    # ------------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            # the TLS handshake happens in the per-connection thread
            # with a timeout: a stalled or plaintext peer must neither
            # kill nor block the accept loop
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            if self._server_ctx is not None:
                conn.settimeout(10.0)
                try:
                    conn = self._server_ctx.wrap_socket(conn,
                                                        server_side=True)
                except (ssl.SSLError, OSError):
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return          # reject this peer only
            with conn:
                conn.settimeout(30.0)
                while not self._shutdown.is_set():
                    try:
                        msg = _recv_frame(conn)
                    except (ConnectionError, socket.timeout, OSError,
                            json.JSONDecodeError):
                        return
                    handler = self._handlers.get(msg.get("type", ""))
                    if handler is None:
                        reply = {"error": f"no handler: {msg.get('type')}"}
                    else:
                        try:
                            reply = handler(msg)
                        except Exception as e:  # noqa: BLE001
                            reply = {"error": f"{type(e).__name__}: {e}"}
                    try:
                        _send_frame(conn, reply)
                    except OSError:
                        return
        except Exception:       # pragma: no cover - defensive
            pass

    # ------------------------------------------------------------------
    def _connect(self, addr: Addr, timeout: float):
        sock = socket.create_connection(addr, timeout=timeout)
        if self._client_ctx is not None:
            sock = self._client_ctx.wrap_socket(sock)
        return sock

    def send(self, addr: Addr, msg: dict, timeout: float = 5.0) -> dict:
        """One blocking request/response RPC to `addr`. Reuses a pooled
        connection per peer; a busy pooled conn falls back to an ephemeral
        one so concurrent RPCs don't serialize."""
        from ..faultinject import faults
        faults.fire("raft.rpc")     # chaos: delay or drop (raises a
        # ConnectionError, so callers see an ordinary network failure)
        addr = tuple(addr)
        with self._pool_lock:
            entry = self._pool.get(addr)
            if entry is None:
                entry = (None, threading.Lock())
                self._pool[addr] = entry
        sock, lock = entry
        if lock.acquire(blocking=False):
            try:
                if sock is None:
                    sock = self._connect(addr, timeout)
                    with self._pool_lock:
                        self._pool[addr] = (sock, lock)
                try:
                    sock.settimeout(timeout)
                    _send_frame(sock, msg)
                    return _recv_frame(sock)
                except (OSError, ConnectionError, json.JSONDecodeError):
                    # stale pooled conn: drop it and retry fresh once
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = self._connect(addr, timeout)
                    with self._pool_lock:
                        self._pool[addr] = (sock, lock)
                    sock.settimeout(timeout)
                    _send_frame(sock, msg)
                    return _recv_frame(sock)
            finally:
                lock.release()
        # pooled conn busy: ephemeral connection
        with self._connect(addr, timeout) as tmp:
            tmp.settimeout(timeout)
            _send_frame(tmp, msg)
            return _recv_frame(tmp)
