"""Consensus layer: replicated log, leader election, snapshots, membership.

The reference embeds hashicorp/raft (reference: nomad/server.go:1365
setupRaft -- BoltDB log store, TCP transport) and hashicorp/serf gossip
(server.go:1602 setupSerf). This package is a from-scratch equivalent:
`RaftNode` (election + log replication + snapshot install over a TCP
transport), `FileLogStore`/`InMemLogStore` (the WAL), `StateFSM` (applies
committed entries into the StateStore, mirroring nomad/fsm.go:211
nomadFSM.Apply), and `Membership` (serf-lite gossip for discovery and
failure detection).
"""
from .log import LogEntry, InMemLogStore, FileLogStore, SnapshotStore
from .transport import TcpTransport
from .node import RaftNode, NotLeaderError
from .fsm import StateFSM, dump_state, restore_state
from .membership import Membership

__all__ = [
    "LogEntry", "InMemLogStore", "FileLogStore", "SnapshotStore",
    "TcpTransport", "RaftNode", "NotLeaderError", "StateFSM",
    "dump_state", "restore_state", "Membership",
]
