"""Serf-lite cluster membership: join, gossip, failure detection.

The reference uses hashicorp/serf (SWIM gossip over UDP+TCP) for member
discovery, failure detection and leader-election events (reference:
nomad/server.go:1602 setupSerf; nomad/serf.go reacts to member joins).
Equivalent here, riding the same TCP transport as raft: each server keeps a
versioned member map; `join(addr)` merges maps both ways; a gossip loop
pushes the map to k random peers per round (epidemic dissemination); a
probe loop pings members and marks them failed/left. Raft remains the
authority for leadership -- membership only feeds discovery and health,
exactly as serf does for the reference.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .transport import TcpTransport

ALIVE, SUSPECT, FAILED, LEFT = "alive", "suspect", "failed", "left"


@dataclass
class Member:
    name: str
    addr: Tuple[str, int]
    status: str = ALIVE
    incarnation: int = 0       # per-member version; highest wins on merge
    tags: Dict[str, str] = field(default_factory=dict)

    def to_wire(self) -> dict:
        return {"name": self.name, "addr": list(self.addr),
                "status": self.status, "incarnation": self.incarnation,
                "tags": self.tags}

    @staticmethod
    def from_wire(d: dict) -> "Member":
        return Member(name=d["name"], addr=tuple(d["addr"]),
                      status=d["status"], incarnation=d["incarnation"],
                      tags=d.get("tags", {}))


class Membership:
    """(reference: serf cluster via nomad/serf.go)"""

    def __init__(self, name: str, transport: TcpTransport,
                 tags: Optional[Dict[str, str]] = None,
                 gossip_interval: float = 0.2,
                 probe_interval: float = 0.5,
                 suspicion_timeout: float = 2.0):
        self.name = name
        self.transport = transport
        self.gossip_interval = gossip_interval
        self.probe_interval = probe_interval
        self.suspicion_timeout = suspicion_timeout
        self._lock = threading.Lock()
        self._members: Dict[str, Member] = {
            name: Member(name=name, addr=transport.addr, tags=tags or {})}
        self._suspect_since: Dict[str, float] = {}
        self._shutdown = threading.Event()
        self._callbacks: List = []    # cb(event, member)
        transport.register("gossip", self._handle_gossip)
        transport.register("ping", lambda msg: {"ack": True,
                                                "from": self.name})

    # ------------------------------------------------------------------
    def start(self) -> None:
        for fn, label in ((self._gossip_loop, "gossip"),
                          (self._probe_loop, "probe")):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"serf-{label}-{self.name}")
            t.start()

    def shutdown(self) -> None:
        self._shutdown.set()

    def leave(self) -> None:
        """Graceful leave: bump incarnation, mark left, push once."""
        with self._lock:
            me = self._members[self.name]
            me.incarnation += 1
            me.status = LEFT
        self._gossip_round()
        self._shutdown.set()

    def on_event(self, cb) -> None:
        """cb(event: 'join'|'failed'|'left', member: Member)"""
        self._callbacks.append(cb)

    def members(self) -> List[Member]:
        with self._lock:
            return list(self._members.values())

    def alive_members(self) -> List[Member]:
        return [m for m in self.members() if m.status == ALIVE]

    # ------------------------------------------------------------------
    def join(self, addr: Tuple[str, int], timeout: float = 3.0) -> int:
        """Push-pull state sync with an existing member
        (reference: serf Join)."""
        reply = self.transport.send(tuple(addr), {
            "type": "gossip",
            "members": [m.to_wire() for m in self.members()],
        }, timeout=timeout)
        merged = reply.get("members", [])
        self._merge([Member.from_wire(d) for d in merged])
        return len(merged)

    def _handle_gossip(self, msg: dict) -> dict:
        self._merge([Member.from_wire(d) for d in msg.get("members", [])])
        return {"members": [m.to_wire() for m in self.members()]}

    def _merge(self, remote: List[Member]) -> None:
        events = []
        with self._lock:
            for rm in remote:
                cur = self._members.get(rm.name)
                if rm.name == self.name:
                    # refute rumors about ourselves (serf's alive-refutation)
                    if cur is not None and rm.incarnation >= cur.incarnation \
                            and rm.status != ALIVE:
                        cur.incarnation = rm.incarnation + 1
                        cur.status = ALIVE
                    continue
                if cur is None:
                    self._members[rm.name] = rm
                    if rm.status == ALIVE:
                        events.append(("join", rm))
                elif (rm.incarnation, _prio(rm.status)) > (
                        cur.incarnation, _prio(cur.status)):
                    old_status = cur.status
                    self._members[rm.name] = rm
                    if rm.status != old_status:
                        if rm.status == ALIVE:
                            events.append(("join", rm))
                        elif rm.status == FAILED:
                            events.append(("failed", rm))
                        elif rm.status == LEFT:
                            events.append(("left", rm))
        for ev, m in events:
            self._notify(ev, m)

    def _notify(self, event: str, member: Member) -> None:
        for cb in self._callbacks:
            try:
                cb(event, member)
            except Exception:   # noqa: BLE001
                pass

    # ------------------------------------------------------------------
    def _gossip_loop(self) -> None:
        while not self._shutdown.wait(self.gossip_interval):
            self._gossip_round()

    def _gossip_round(self, fanout: int = 3) -> None:
        peers = [m for m in self.members()
                 if m.name != self.name and m.status in (ALIVE, SUSPECT)]
        random.shuffle(peers)
        payload = {"type": "gossip",
                   "members": [m.to_wire() for m in self.members()]}
        for m in peers[:fanout]:
            try:
                reply = self.transport.send(m.addr, payload, timeout=1.0)
                self._merge([Member.from_wire(d)
                             for d in reply.get("members", [])])
            except (OSError, ConnectionError):
                pass

    def _probe_loop(self) -> None:
        while not self._shutdown.wait(self.probe_interval):
            targets = [m for m in self.members()
                       if m.name != self.name and m.status in (ALIVE, SUSPECT)]
            if not targets:
                continue
            m = random.choice(targets)
            ok = False
            try:
                reply = self.transport.send(m.addr, {"type": "ping"},
                                            timeout=0.5)
                ok = bool(reply.get("ack"))
            except (OSError, ConnectionError):
                ok = False
            now = time.monotonic()
            events = []
            with self._lock:
                cur = self._members.get(m.name)
                if cur is None:
                    continue
                if ok:
                    self._suspect_since.pop(m.name, None)
                    if cur.status in (SUSPECT, FAILED):
                        cur.status = ALIVE
                        cur.incarnation += 1
                        events.append(("join", cur))
                else:
                    since = self._suspect_since.setdefault(m.name, now)
                    if cur.status == ALIVE:
                        cur.status = SUSPECT
                        cur.incarnation += 1
                    elif cur.status == SUSPECT and \
                            now - since >= self.suspicion_timeout:
                        cur.status = FAILED
                        cur.incarnation += 1
                        events.append(("failed", cur))
            for ev, mem in events:
                self._notify(ev, mem)


def _prio(status: str) -> int:
    # at equal incarnation, stronger claims win (serf's precedence)
    return {ALIVE: 0, SUSPECT: 1, FAILED: 2, LEFT: 3}.get(status, 0)
