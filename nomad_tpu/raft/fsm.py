"""FSM: applies committed raft entries into the StateStore.

The reference's nomadFSM dispatches ~60 msgpack message types into state
(reference: nomad/fsm.go:211 Apply; snapshot Persist/Restore further down
fsm.go; state/state_store_restore.go rebuilds tables). Equivalent here:
each entry is {"m": <StateStore write method>, "a": [codec-encoded args]};
a typed registry drives decoding, so the full writable API of the store is
the replicated-message surface. Snapshots dump every table through the
generic struct codec.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..state.store import StateStore
from ..structs import (
    ACLPolicy, ACLRole, ACLToken, Allocation, CSIVolume, Deployment,
    DrainStrategy,
    Evaluation, Job, Namespace, Node, NodePool, PlanResult, RootKey,
    ScalingEvent, ScalingPolicy, SchedulerConfiguration,
    ServiceRegistration, VariableEncrypted,
)
from ..structs import codec

# method -> positional arg type hints (kwargs are normalized positionally
# by RaftBackedStateStore before proposing)
WRITE_METHODS: Dict[str, List[Any]] = {
    "upsert_node": [Node],
    "delete_node": [str],
    "update_node_status": [str, str, float],
    "update_node_eligibility": [str, str],
    "update_node_drain": [str, Optional[DrainStrategy], bool],
    "upsert_job": [Job],
    "update_job_status": [str, str, str],
    "update_job_stability": [str, str, int, bool],
    "delete_job": [str, str],
    "upsert_scaling_event": [str, str, ScalingEvent],
    "upsert_evals": [List[Evaluation]],
    "delete_evals": [List[str]],
    "upsert_allocs": [List[Allocation]],
    "update_allocs_from_client": [List[Allocation]],
    "update_alloc_desired_transition": [List[str], bool],
    "delete_allocs": [List[str]],
    "upsert_deployment": [Deployment],
    "upsert_deployment_cas": [Deployment, int],
    "delete_deployment": [str],
    "upsert_node_pool": [NodePool],
    "delete_node_pool": [str],
    "upsert_namespace": [Namespace],
    "delete_namespace": [str],
    "upsert_csi_volume": [CSIVolume],
    "delete_csi_volume": [str, str],
    "csi_volume_release": [str, str, str],
    "upsert_service_registrations": [List[ServiceRegistration]],
    "delete_service_registrations": [List[str]],
    "delete_services_by_alloc": [str],
    "delete_services_by_allocs": [List[str]],
    "delete_services_by_node": [str],
    "restore_from_snapshot": [Any],
    "set_scheduler_config": [SchedulerConfiguration],
    "upsert_plan_results": [PlanResult, Optional[List[Evaluation]]],
    "upsert_acl_policies": [List[ACLPolicy]],
    "delete_acl_policies": [List[str]],
    "upsert_acl_roles": [List[ACLRole]],
    "delete_acl_roles": [List[str]],
    "upsert_acl_tokens": [List[ACLToken]],
    "delete_acl_tokens": [List[str]],
    "bootstrap_acl_token": [ACLToken],
    "upsert_root_key": [RootKey],
    "delete_root_key": [str],
    "upsert_variable": [VariableEncrypted, Optional[int]],
    "delete_variable": [str, str, Optional[int]],
}


def encode_command(method: str, args: Tuple[Any, ...]) -> dict:
    specs = WRITE_METHODS[method]
    return {"m": method,
            "a": [codec.encode(a) for a in args[:len(specs)]]}


# ---------------------------------------------------------------------------
# Plan normalization (reference: nomad/worker.go:666-669 SubmitPlan's
# normalized requests + plan_normalization_test.go). Plans dominate the
# raft log under load, and a naive encoding ships FULL Allocation structs
# -- each embedding the entire Job -- for every stop, preemption and
# placement. The FSM only reads a diff's worth of fields from
# stops/preemptions (see StateStore.upsert_plan_results), and every
# placement in a plan shares its job, so the normalized form carries:
#   - stop/preemption STUBS (id + the status fields the apply reads),
#   - placements with the embedded job STRIPPED,
#   - each distinct job exactly once, reattached at apply time.

from ..structs.alloc import PLAN_STOP_STUB_FIELDS as _STOP_STUB_FIELDS


def _stub(alloc: Allocation) -> dict:
    return {f: getattr(alloc, f) for f in _STOP_STUB_FIELDS}


def encode_plan_results(result: PlanResult,
                        eval_updates: Optional[List[Evaluation]]) -> dict:
    """The normalized raft command for upsert_plan_results."""
    jobs: Dict[str, Any] = {}

    def strip(alloc: Allocation) -> dict:
        raw = codec.encode(alloc)
        job = alloc.job
        if job is not None:
            key = f"{alloc.namespace}\x00{alloc.job_id}\x00{job.version}"
            if key not in jobs:
                jobs[key] = codec.encode(job)
            raw["job"] = None
            raw["_jobkey"] = key
        return raw

    payload = {
        "node_update": {nid: [_stub(a) for a in allocs]
                        for nid, allocs in result.node_update.items()},
        "node_preemptions": {
            nid: [_stub(a) for a in allocs]
            for nid, allocs in result.node_preemptions.items()},
        "node_allocation": {
            nid: [strip(a) for a in allocs]
            for nid, allocs in result.node_allocation.items()},
        "deployment": codec.encode(result.deployment),
        "deployment_updates": [codec.encode(du)
                               for du in result.deployment_updates],
        "jobs": jobs,
        "evals": ([codec.encode(e) for e in eval_updates]
                  if eval_updates else None),
    }
    return {"m": "upsert_plan_results_norm", "a": [payload]}


def decode_plan_results(payload: dict
                        ) -> Tuple[PlanResult, Optional[List[Evaluation]]]:
    from ..structs import Deployment, DeploymentStatusUpdate

    jobs = {k: codec.decode(Job, v)
            for k, v in (payload.get("jobs") or {}).items()}

    def alloc_of(raw: dict) -> Allocation:
        key = raw.pop("_jobkey", None) if isinstance(raw, dict) else None
        a = codec.decode(Allocation, raw)
        if key is not None:
            a.job = jobs.get(key)
        return a

    result = PlanResult(
        node_update={nid: [alloc_of(r) for r in raws]
                     for nid, raws in payload["node_update"].items()},
        node_preemptions={nid: [alloc_of(r) for r in raws]
                          for nid, raws in
                          payload["node_preemptions"].items()},
        node_allocation={nid: [alloc_of(r) for r in raws]
                         for nid, raws in
                         payload["node_allocation"].items()},
        deployment=codec.decode(Optional[Deployment],
                                payload.get("deployment")),
        deployment_updates=[
            codec.decode(DeploymentStatusUpdate, du)
            for du in payload.get("deployment_updates") or []],
    )
    evals = ([codec.decode(Evaluation, e) for e in payload["evals"]]
             if payload.get("evals") else None)
    return result, evals


class StateFSM:
    """(reference: nomad/fsm.go nomadFSM)"""

    def __init__(self, store: StateStore):
        self.store = store

    def apply(self, data: dict) -> Any:
        method = data["m"]
        if method == "upsert_plan_results_norm":
            result, evals = decode_plan_results(data["a"][0])
            return self.store.upsert_plan_results(result, evals)
        specs = WRITE_METHODS.get(method)
        if specs is None:
            raise ValueError(f"unknown FSM command: {method}")
        args = [codec.decode(spec, raw)
                for spec, raw in zip(specs, data["a"])]
        return getattr(self.store, method)(*args)

    def snapshot(self) -> Any:
        return dump_state(self.store)

    def restore(self, blob: Any) -> None:
        restore_state(self.store, blob)


# ---------------------------------------------------------------------------
# whole-store dump/restore (reference: fsm.go Persist/Restore +
# state/state_store_restore.go)

def dump_state(store: StateStore) -> dict:
    with store._lock:
        return {
            "index": store._index,
            "table_index": dict(store._table_index),
            "nodes": [codec.encode(n) for n in store._nodes.values()],
            "jobs": [codec.encode(j) for j in store._jobs.values()],
            "job_versions": {
                codec._encode_key(k): codec.encode(v)
                for k, v in store._job_versions.items()},
            "evals": [codec.encode(e) for e in store._evals.values()],
            "allocs": [codec.encode(a) for a in store._allocs.values()],
            "deployments": [codec.encode(d)
                            for d in store._deployments.values()],
            "node_pools": [codec.encode(p)
                           for p in store._node_pools.values()],
            "scheduler_config": codec.encode(store._scheduler_config),
            "acl_policies": [codec.encode(p)
                             for p in store._acl_policies.values()],
            "acl_roles": [codec.encode(r)
                          for r in store._acl_roles.values()],
            "acl_tokens": [codec.encode(t)
                           for t in store._acl_tokens.values()],
            "acl_bootstrapped": store._acl_bootstrapped,
            "root_keys": [codec.encode(k)
                          for k in store._root_keys.values()],
            "variables": [codec.encode(v)
                          for v in store._variables.values()],
            "scaling_policies": [codec.encode(p)
                                 for p in store._scaling_policies.values()],
            "scaling_events": {
                codec._encode_key(k): [codec.encode(e) for e in evs]
                for k, evs in store._scaling_events.items()},
            "namespaces": [codec.encode(n)
                           for n in store._namespaces.values()],
            "csi_volumes": [codec.encode(v)
                            for v in store._csi_volumes.values()],
            "services": [codec.encode(s)
                         for s in store._services.values()],
        }


def restore_state(store: StateStore, blob: dict) -> None:
    nodes = [codec.decode(Node, n) for n in blob.get("nodes", [])]
    jobs = [codec.decode(Job, j) for j in blob.get("jobs", [])]
    evals = [codec.decode(Evaluation, e) for e in blob.get("evals", [])]
    allocs = [codec.decode(Allocation, a) for a in blob.get("allocs", [])]
    deployments = [codec.decode(Deployment, d)
                   for d in blob.get("deployments", [])]
    pools = [codec.decode(NodePool, p) for p in blob.get("node_pools", [])]
    sched_cfg = codec.decode(SchedulerConfiguration,
                             blob.get("scheduler_config") or {})
    acl_policies = [codec.decode(ACLPolicy, p)
                    for p in blob.get("acl_policies", [])]
    acl_tokens = [codec.decode(ACLToken, t)
                  for t in blob.get("acl_tokens", [])]
    acl_roles = [codec.decode(ACLRole, r)
                 for r in blob.get("acl_roles", [])]
    root_keys = [codec.decode(RootKey, k)
                 for k in blob.get("root_keys", [])]
    variables = [codec.decode(VariableEncrypted, v)
                 for v in blob.get("variables", [])]
    # decode EVERYTHING before touching the store, so a malformed blob
    # raises here and leaves state untouched (restore must be atomic)
    job_versions = {}
    for k, v in blob.get("job_versions", {}).items():
        ns, jid, ver = k.split("\x1f")
        job_versions[(ns, jid, int(ver))] = codec.decode(Job, v)
    scaling_policies = {
        pol.id: pol for pol in
        (codec.decode(ScalingPolicy, raw)
         for raw in blob.get("scaling_policies", []))}
    scaling_events = {}
    for k, evs in blob.get("scaling_events", {}).items():
        ns, jid = k.split("\x1f")
        scaling_events[(ns, jid)] = [
            codec.decode(ScalingEvent, e) for e in evs]
    restored_ns = [codec.decode(Namespace, n)
                   for n in blob.get("namespaces", [])]
    csi_volumes = {
        (v.namespace, v.id): v for v in
        (codec.decode(CSIVolume, raw)
         for raw in blob.get("csi_volumes", []))}
    services = {
        svc.id: svc for svc in
        (codec.decode(ServiceRegistration, raw)
         for raw in blob.get("services", []))}
    with store._lock:
        store._root_keys = {k.key_id: k for k in root_keys}
        store._variables = {(v.meta.namespace, v.meta.path): v
                            for v in variables}
        store._acl_policies = {p.name: p for p in acl_policies}
        store._acl_roles = {r.name: r for r in acl_roles}
        store._acl_tokens = {t.accessor_id: t for t in acl_tokens}
        store._acl_tokens_by_secret = {t.secret_id: t.accessor_id
                                       for t in acl_tokens}
        store._acl_bootstrapped = blob.get("acl_bootstrapped", False)
        store._nodes = {n.id: n for n in nodes}
        store._jobs = {(j.namespace, j.id): j for j in jobs}
        store._job_versions = job_versions
        store._evals = {e.id: e for e in evals}
        store._allocs = {a.id: a for a in allocs}
        store._deployments = {d.id: d for d in deployments}
        store._node_pools = {p.name: p for p in pools}
        if sched_cfg is not None:
            store._scheduler_config = sched_cfg
        # rebuild secondary indexes (and drop the snapshot cache + its
        # incremental-copy base: both refer to the replaced dicts)
        store._allocs_by_node = {}
        store._allocs_by_job = {}
        store._snap_cache = None
        store._snap_prev = None
        store._dirty_alloc_nodes.clear()
        store._dirty_alloc_jobs.clear()
        for a in allocs:
            store._allocs_by_node.setdefault(a.node_id, {})[a.id] = None
            store._allocs_by_job.setdefault(
                (a.namespace, a.job_id), {})[a.id] = None
        # re-link alloc.job to the stored job (codec duplicates the object)
        for a in allocs:
            stored = store._jobs.get((a.namespace, a.job_id))
            if stored is not None and a.job is not None and \
                    a.job.version == stored.version:
                a.job = stored
        store._scaling_policies = scaling_policies
        store._scaling_events = scaling_events
        if restored_ns:
            store._namespaces = {n.name: n for n in restored_ns}
        else:
            store._namespaces = {"default": Namespace(name="default")}
        store._namespaces.setdefault("default", Namespace(name="default"))
        store._csi_volumes = csi_volumes
        store._recompute_csi_plugins_locked()
        store._services = services
        store._index = blob.get("index", 1)
        ti = blob.get("table_index", {})
        for t in store._table_index:
            store._table_index[t] = ti.get(t, store._index)
        # rebuild the tensor-resident alloc table
        from ..state.alloc_table import AllocTable
        table = AllocTable()
        for n in nodes:
            table.register_node(n)
        # skip only CLIENT-terminal allocs (their rows would carry
        # live=0 AND live_strict=0 -- dead weight). Server-terminal
        # but client-running allocs must keep a row: they still
        # consume capacity in the scheduler's live filter until the
        # client acks, and dropping them made solver usage tensors
        # diverge across a snapshot restore
        # (tests/test_plan_normalization.py pins this).
        table.upsert_many(
            [a for a in allocs if not a.client_terminal_status()])
        store.alloc_table = table
        store._watch_cond.notify_all()
