"""FSM: applies committed raft entries into the StateStore.

The reference's nomadFSM dispatches ~60 msgpack message types into state
(reference: nomad/fsm.go:211 Apply; snapshot Persist/Restore further down
fsm.go; state/state_store_restore.go rebuilds tables). Equivalent here:
each entry is {"m": <StateStore write method>, "a": [codec-encoded args]};
a typed registry drives decoding, so the full writable API of the store is
the replicated-message surface. Snapshots dump every table through the
generic struct codec.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..state.store import StateStore
from ..structs import (
    ACLPolicy, ACLRole, ACLToken, Allocation, CSIVolume, Deployment,
    DrainStrategy,
    Evaluation, Job, Namespace, Node, NodePool, PlanResult, RootKey,
    ScalingEvent, ScalingPolicy, SchedulerConfiguration,
    ServiceRegistration, VariableEncrypted,
)
from ..structs import codec

# method -> positional arg type hints (kwargs are normalized positionally
# by RaftBackedStateStore before proposing)
WRITE_METHODS: Dict[str, List[Any]] = {
    "upsert_node": [Node],
    "delete_node": [str],
    "update_node_status": [str, str, float],
    "update_node_eligibility": [str, str],
    "update_node_drain": [str, Optional[DrainStrategy], bool],
    "upsert_job": [Job],
    "update_job_status": [str, str, str],
    "update_job_stability": [str, str, int, bool],
    "delete_job": [str, str],
    "upsert_scaling_event": [str, str, ScalingEvent],
    "upsert_evals": [List[Evaluation]],
    "delete_evals": [List[str]],
    "upsert_allocs": [List[Allocation]],
    "update_allocs_from_client": [List[Allocation]],
    "update_alloc_desired_transition": [List[str], bool],
    "delete_allocs": [List[str]],
    "upsert_deployment": [Deployment],
    "upsert_deployment_cas": [Deployment, int],
    "delete_deployment": [str],
    "upsert_node_pool": [NodePool],
    "delete_node_pool": [str],
    "upsert_namespace": [Namespace],
    "delete_namespace": [str],
    "upsert_csi_volume": [CSIVolume],
    "delete_csi_volume": [str, str],
    "csi_volume_release": [str, str, str],
    "upsert_service_registrations": [List[ServiceRegistration]],
    "delete_service_registrations": [List[str]],
    "delete_services_by_alloc": [str],
    "delete_services_by_allocs": [List[str]],
    "delete_services_by_node": [str],
    "restore_from_snapshot": [Any],
    "set_scheduler_config": [SchedulerConfiguration],
    "upsert_plan_results": [PlanResult, Optional[List[Evaluation]]],
    "upsert_acl_policies": [List[ACLPolicy]],
    "delete_acl_policies": [List[str]],
    "upsert_acl_roles": [List[ACLRole]],
    "delete_acl_roles": [List[str]],
    "upsert_acl_tokens": [List[ACLToken]],
    "delete_acl_tokens": [List[str]],
    "bootstrap_acl_token": [ACLToken],
    "upsert_root_key": [RootKey],
    "delete_root_key": [str],
    "upsert_variable": [VariableEncrypted, Optional[int]],
    "delete_variable": [str, str, Optional[int]],
}


def encode_command(method: str, args: Tuple[Any, ...]) -> dict:
    specs = WRITE_METHODS[method]
    return {"m": method,
            "a": [codec.encode(a) for a in args[:len(specs)]]}


# ---------------------------------------------------------------------------
# Plan normalization (reference: nomad/worker.go:666-669 SubmitPlan's
# normalized requests + plan_normalization_test.go). Plans dominate the
# raft log under load, and a naive encoding ships FULL Allocation structs
# -- each embedding the entire Job -- for every stop, preemption and
# placement. The FSM only reads a diff's worth of fields from
# stops/preemptions (see StateStore.upsert_plan_results), and every
# placement in a plan shares its job, so the normalized form carries:
#   - stop/preemption STUBS (id + the status fields the apply reads),
#   - placements with the embedded job STRIPPED,
#   - each distinct job exactly once, reattached at apply time.

from ..structs.alloc import PLAN_STOP_STUB_FIELDS as _STOP_STUB_FIELDS


def _stub(alloc: Allocation) -> dict:
    return {f: getattr(alloc, f) for f in _STOP_STUB_FIELDS}


def encode_plan_results(result: PlanResult,
                        eval_updates: Optional[List[Evaluation]]) -> dict:
    """The normalized raft command for upsert_plan_results."""
    jobs: Dict[str, Any] = {}

    def strip(alloc: Allocation) -> dict:
        raw = codec.encode(alloc)
        job = alloc.job
        if job is not None:
            key = f"{alloc.namespace}\x00{alloc.job_id}\x00{job.version}"
            if key not in jobs:
                jobs[key] = codec.encode(job)
            raw["job"] = None
            raw["_jobkey"] = key
        return raw

    payload = {
        "node_update": {nid: [_stub(a) for a in allocs]
                        for nid, allocs in result.node_update.items()},
        "node_preemptions": {
            nid: [_stub(a) for a in allocs]
            for nid, allocs in result.node_preemptions.items()},
        "node_allocation": {
            nid: [strip(a) for a in allocs]
            for nid, allocs in result.node_allocation.items()},
        "deployment": codec.encode(result.deployment),
        "deployment_updates": [codec.encode(du)
                               for du in result.deployment_updates],
        "jobs": jobs,
        "evals": ([codec.encode(e) for e in eval_updates]
                  if eval_updates else None),
    }
    return {"m": "upsert_plan_results_norm", "a": [payload]}


def decode_plan_results(payload: dict
                        ) -> Tuple[PlanResult, Optional[List[Evaluation]]]:
    from ..structs import Deployment, DeploymentStatusUpdate

    jobs = {k: codec.decode(Job, v)
            for k, v in (payload.get("jobs") or {}).items()}

    def alloc_of(raw: dict) -> Allocation:
        key = raw.pop("_jobkey", None) if isinstance(raw, dict) else None
        a = codec.decode(Allocation, raw)
        if key is not None:
            a.job = jobs.get(key)
        return a

    result = PlanResult(
        node_update={nid: [alloc_of(r) for r in raws]
                     for nid, raws in payload["node_update"].items()},
        node_preemptions={nid: [alloc_of(r) for r in raws]
                          for nid, raws in
                          payload["node_preemptions"].items()},
        node_allocation={nid: [alloc_of(r) for r in raws]
                         for nid, raws in
                         payload["node_allocation"].items()},
        deployment=codec.decode(Optional[Deployment],
                                payload.get("deployment")),
        deployment_updates=[
            codec.decode(DeploymentStatusUpdate, du)
            for du in payload.get("deployment_updates") or []],
    )
    evals = ([codec.decode(Evaluation, e) for e in payload["evals"]]
             if payload.get("evals") else None)
    return result, evals


class StateFSM:
    """(reference: nomad/fsm.go nomadFSM)"""

    def __init__(self, store: StateStore):
        self.store = store

    def apply(self, data: dict) -> Any:
        method = data["m"]
        if method == "upsert_plan_results_norm":
            result, evals = decode_plan_results(data["a"][0])
            return self.store.upsert_plan_results(result, evals)
        specs = WRITE_METHODS.get(method)
        if specs is None:
            raise ValueError(f"unknown FSM command: {method}")
        args = [codec.decode(spec, raw)
                for spec, raw in zip(specs, data["a"])]
        return getattr(self.store, method)(*args)

    def snapshot(self) -> Any:
        return dump_state(self.store)

    def restore(self, blob: Any) -> None:
        restore_state(self.store, blob)


# ---------------------------------------------------------------------------
# whole-store dump/restore (reference: fsm.go Persist/Restore +
# state/state_store_restore.go)

def dump_state(store: StateStore) -> dict:
    with store._lock:
        return {
            "index": store._index,
            "table_index": dict(store._table_index),
            "nodes": [codec.encode(n) for n in store._nodes.values()],
            "jobs": [codec.encode(j) for j in store._jobs.values()],
            "job_versions": {
                codec._encode_key(k): codec.encode(v)
                for k, v in store._job_versions.items()},
            "evals": [codec.encode(e) for e in store._evals.values()],
            "allocs": [codec.encode(a) for a in store._allocs.values()],
            "deployments": [codec.encode(d)
                            for d in store._deployments.values()],
            "node_pools": [codec.encode(p)
                           for p in store._node_pools.values()],
            "scheduler_config": codec.encode(store._scheduler_config),
            "acl_policies": [codec.encode(p)
                             for p in store._acl_policies.values()],
            "acl_roles": [codec.encode(r)
                          for r in store._acl_roles.values()],
            "acl_tokens": [codec.encode(t)
                           for t in store._acl_tokens.values()],
            "acl_bootstrapped": store._acl_bootstrapped,
            "root_keys": [codec.encode(k)
                          for k in store._root_keys.values()],
            "variables": [codec.encode(v)
                          for v in store._variables.values()],
            "scaling_policies": [codec.encode(p)
                                 for p in store._scaling_policies.values()],
            "scaling_events": {
                codec._encode_key(k): [codec.encode(e) for e in evs]
                for k, evs in store._scaling_events.items()},
            "namespaces": [codec.encode(n)
                           for n in store._namespaces.values()],
            "csi_volumes": [codec.encode(v)
                            for v in store._csi_volumes.values()],
            "services": [codec.encode(s)
                         for s in store._services.values()],
        }


# restore_state moved to nomad_tpu/state/restore.py (the one
# sanctioned writer of store internals lives with the store;
# see the no-direct-table-write lint rule). Re-exported here so
# the FSM surface is unchanged.
from ..state.restore import restore_state  # noqa: E402,F401
