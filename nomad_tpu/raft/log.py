"""Raft log + snapshot storage.

The reference persists its raft log in BoltDB (reference: nomad/server.go:30
raft-boltdb/v2, setupRaft server.go:1365) and snapshots as files through the
raft snapshot store (helper/snapshot/snapshot.go archives them). Equivalent
here: `FileLogStore` is an append-only JSONL WAL with an in-memory mirror
(every committed entry is one fsync-able line), `InMemLogStore` backs tests
and dev mode, `SnapshotStore` writes whole-FSM snapshots that allow the WAL
prefix to be compacted.
"""
from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass
class LogEntry:
    index: int = 0
    term: int = 0
    type: str = ""          # "noop" | "command" | "barrier"
    data: Any = None


class InMemLogStore:
    """Volatile log: a list offset by first_index (compaction trims the
    prefix once a snapshot covers it)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._entries: List[LogEntry] = []
        self._first = 1          # index of _entries[0] if non-empty

    # -- reads ---------------------------------------------------------
    def first_index(self) -> int:
        with self._lock:
            return self._first if self._entries else 0

    def last_index(self) -> int:
        with self._lock:
            return (self._first + len(self._entries) - 1
                    if self._entries else self._first - 1)

    def last_term(self) -> int:
        with self._lock:
            return self._entries[-1].term if self._entries else 0

    def get(self, index: int) -> Optional[LogEntry]:
        with self._lock:
            k = index - self._first
            if 0 <= k < len(self._entries):
                return self._entries[k]
            return None

    def entries_from(self, index: int, limit: int = 64) -> List[LogEntry]:
        with self._lock:
            k = max(0, index - self._first)
            return list(self._entries[k:k + limit])

    # -- writes --------------------------------------------------------
    def append(self, entry: LogEntry) -> None:
        with self._lock:
            self._entries.append(entry)
            self._persist(entry)

    def truncate_after(self, index: int) -> None:
        """Drop entries with index > `index` (conflict resolution on
        followers)."""
        with self._lock:
            keep = index - self._first + 1
            if keep < len(self._entries):
                self._entries = self._entries[:max(keep, 0)]
                self._persist_truncate(index)

    def compact_to(self, index: int) -> None:
        """Drop entries with index <= `index` (covered by a snapshot)."""
        with self._lock:
            drop = index - self._first + 1
            if drop > 0:
                self._entries = self._entries[drop:]
                self._first = index + 1
                self._persist_compact(index)

    def reset(self, first_index: int) -> None:
        """After installing a snapshot past our log."""
        with self._lock:
            self._entries = []
            self._first = first_index
            self._persist_reset(first_index)

    # -- persistence hooks (no-ops in memory) --------------------------
    def _persist(self, entry: LogEntry) -> None:
        pass

    def _persist_truncate(self, index: int) -> None:
        pass

    def _persist_compact(self, index: int) -> None:
        pass

    def _persist_reset(self, first_index: int) -> None:
        pass


class FileLogStore(InMemLogStore):
    """JSONL WAL. Each line is {"op": "append"|"truncate"|"compact"|"reset",
    ...}; recovery replays the ops. Rewritten compactly when the file grows
    past `rewrite_bytes`."""

    def __init__(self, path: str, rewrite_bytes: int = 8 << 20) -> None:
        super().__init__()
        self.path = path
        self.rewrite_bytes = rewrite_bytes
        self._fh = None
        if os.path.exists(path):
            self._recover()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")

    def _recover(self) -> None:
        with open(self.path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    break       # torn tail write: discard
                op = rec.get("op")
                if op == "append":
                    e = rec["entry"]
                    self._entries.append(LogEntry(
                        index=e["index"], term=e["term"], type=e["type"],
                        data=e.get("data")))
                    if len(self._entries) == 1:
                        self._first = e["index"]
                elif op == "truncate":
                    keep = rec["index"] - self._first + 1
                    self._entries = self._entries[:max(keep, 0)]
                elif op == "compact":
                    drop = rec["index"] - self._first + 1
                    if drop > 0:
                        self._entries = self._entries[drop:]
                        self._first = rec["index"] + 1
                elif op == "reset":
                    self._entries = []
                    self._first = rec["first"]

    def _write(self, rec: dict) -> None:
        if self._fh is None:
            return
        self._fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._fh.flush()

    def _persist(self, entry: LogEntry) -> None:
        self._write({"op": "append", "entry": {
            "index": entry.index, "term": entry.term, "type": entry.type,
            "data": entry.data}})

    def _persist_truncate(self, index: int) -> None:
        self._write({"op": "truncate", "index": index})

    def _persist_compact(self, index: int) -> None:
        self._write({"op": "compact", "index": index})
        self._maybe_rewrite()

    def _persist_reset(self, first_index: int) -> None:
        self._write({"op": "reset", "first": first_index})
        self._maybe_rewrite()

    def _maybe_rewrite(self) -> None:
        try:
            if os.path.getsize(self.path) < self.rewrite_bytes:
                return
        except OSError:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(json.dumps({"op": "reset", "first": self._first},
                                separators=(",", ":")) + "\n")
            for e in self._entries:
                fh.write(json.dumps(
                    {"op": "append", "entry": {
                        "index": e.index, "term": e.term, "type": e.type,
                        "data": e.data}}, separators=(",", ":")) + "\n")
        self._fh.close()
        os.replace(tmp, self.path)
        self._fh = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


@dataclass
class Snapshot:
    last_index: int = 0
    last_term: int = 0
    state: Any = None        # FSM-opaque JSON-able blob


class SnapshotStore:
    """Latest-wins snapshot storage; file-backed when given a directory
    (reference: raft snapshot store + FSM Persist/Restore, nomad/fsm.go)."""

    def __init__(self, dirpath: Optional[str] = None) -> None:
        self.dirpath = dirpath
        self._latest: Optional[Snapshot] = None
        self._lock = threading.Lock()
        if dirpath:
            os.makedirs(dirpath, exist_ok=True)
            path = os.path.join(dirpath, "snapshot.json")
            if os.path.exists(path):
                try:
                    with open(path, encoding="utf-8") as fh:
                        rec = json.load(fh)
                    self._latest = Snapshot(rec["last_index"],
                                            rec["last_term"], rec["state"])
                except (json.JSONDecodeError, KeyError, OSError):
                    pass

    def save(self, snap: Snapshot) -> None:
        with self._lock:
            self._latest = snap
            if self.dirpath:
                path = os.path.join(self.dirpath, "snapshot.json")
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump({"last_index": snap.last_index,
                               "last_term": snap.last_term,
                               "state": snap.state}, fh,
                              separators=(",", ":"))
                os.replace(tmp, path)

    def latest(self) -> Optional[Snapshot]:
        with self._lock:
            return self._latest
