"""Raft log + snapshot storage.

The reference persists its raft log in BoltDB (reference: nomad/server.go:30
raft-boltdb/v2, setupRaft server.go:1365) and snapshots as files through the
raft snapshot store (helper/snapshot/snapshot.go archives them). Equivalent
here: `FileLogStore` is an append-only JSONL WAL with an in-memory mirror
(every committed entry is one fsync-able line), `InMemLogStore` backs tests
and dev mode, `SnapshotStore` writes whole-FSM snapshots that allow the WAL
prefix to be compacted.
"""
from __future__ import annotations

import json
import os
import threading
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


class CorruptWalError(Exception):
    """Corruption detected in the MIDDLE of the WAL (valid frames follow
    the broken record). Unlike a torn tail, truncating here would silently
    drop entries raft already acked -- the node must refuse to start and
    let the operator restore from a snapshot/peer."""


@dataclass
class LogEntry:
    index: int = 0
    term: int = 0
    type: str = ""          # "noop" | "command" | "barrier"
    data: Any = None


class InMemLogStore:
    """Volatile log: a list offset by first_index (compaction trims the
    prefix once a snapshot covers it)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._entries: List[LogEntry] = []
        self._first = 1          # index of _entries[0] if non-empty

    # -- reads ---------------------------------------------------------
    def first_index(self) -> int:
        with self._lock:
            return self._first if self._entries else 0

    def last_index(self) -> int:
        with self._lock:
            return (self._first + len(self._entries) - 1
                    if self._entries else self._first - 1)

    def last_term(self) -> int:
        with self._lock:
            return self._entries[-1].term if self._entries else 0

    def get(self, index: int) -> Optional[LogEntry]:
        with self._lock:
            k = index - self._first
            if 0 <= k < len(self._entries):
                return self._entries[k]
            return None

    def entries_from(self, index: int, limit: int = 64) -> List[LogEntry]:
        with self._lock:
            k = max(0, index - self._first)
            return list(self._entries[k:k + limit])

    # -- writes --------------------------------------------------------
    def append(self, entry: LogEntry) -> None:
        with self._lock:
            self._entries.append(entry)
            self._persist(entry)

    def truncate_after(self, index: int) -> None:
        """Drop entries with index > `index` (conflict resolution on
        followers)."""
        with self._lock:
            keep = index - self._first + 1
            if keep < len(self._entries):
                self._entries = self._entries[:max(keep, 0)]
                self._persist_truncate(index)

    def compact_to(self, index: int) -> None:
        """Drop entries with index <= `index` (covered by a snapshot)."""
        with self._lock:
            drop = index - self._first + 1
            if drop > 0:
                self._entries = self._entries[drop:]
                self._first = index + 1
                self._persist_compact(index)

    def reset(self, first_index: int) -> None:
        """After installing a snapshot past our log."""
        with self._lock:
            self._entries = []
            self._first = first_index
            self._persist_reset(first_index)

    # -- persistence hooks (no-ops in memory) --------------------------
    def _persist(self, entry: LogEntry) -> None:
        pass

    def _persist_truncate(self, index: int) -> None:
        pass

    def _persist_compact(self, index: int) -> None:
        pass

    def _persist_reset(self, first_index: int) -> None:
        pass


class FileLogStore(InMemLogStore):
    """CRC-framed JSONL WAL. Each line is ``{payload}|<crc32 hex>``, the
    payload a JSON op record ("append"|"truncate"|"compact"|"reset");
    recovery replays ops up to the first missing/invalid CRC and
    TRUNCATES the file there, so a torn tail (kill -9 mid-append, torn
    sector) can never poison later appends. Appends fsync before
    returning -- raft must not ack an entry the disk might lose
    (reference durability contract: raft-boltdb at nomad/server.go:30).
    Rewritten compactly when the file grows past `rewrite_bytes`."""

    def __init__(self, path: str, rewrite_bytes: int = 8 << 20,
                 fsync: bool = True) -> None:
        super().__init__()
        self.path = path
        self.rewrite_bytes = rewrite_bytes
        self.fsync = fsync
        self._fh = None
        existed = os.path.exists(path)
        if existed:
            self._recover()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")
        if not existed:
            self._fsync_dir()       # the dirent must be durable too

    @staticmethod
    def _frame(payload: str) -> str:
        crc = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
        return f"{payload}|{crc:08x}\n"

    @staticmethod
    def _unframe(line: str) -> Optional[str]:
        """-> payload, or None when the frame is torn/corrupt."""
        line = line.rstrip("\n")
        cut = line.rfind("|")
        if cut < 0 or len(line) - cut != 9:
            return None
        payload, crc_hex = line[:cut], line[cut + 1:]
        try:
            want = int(crc_hex, 16)
        except ValueError:
            return None
        if zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF != want:
            return None
        return payload

    def _recover(self) -> None:
        """Streaming replay (O(1) in file size). Three outcomes per bad
        record: legacy (pre-CRC) lines replay and schedule a rewrite;
        a bad record with NO valid frame after it is a torn tail,
        truncated on disk; a bad record FOLLOWED by valid frames is
        mid-file corruption -> CorruptWalError (fail loudly rather than
        silently dropping acked entries)."""
        good_end = 0
        saw_framed = False
        needs_rewrite = False
        with open(self.path, "rb") as fh:
            while True:
                pos = fh.tell()
                line_b = fh.readline()
                if not line_b:
                    break
                if not line_b.endswith(b"\n"):
                    break                   # unterminated tail: torn
                line = line_b.decode("utf-8", "replace")
                payload = self._unframe(line)
                if payload is None and not saw_framed:
                    # legacy pre-CRC format: plain JSON lines are valid
                    # only in the un-framed PREFIX of an upgraded file
                    try:
                        rec = json.loads(line)
                        self._replay(rec)
                        needs_rewrite = True
                        good_end = fh.tell()
                        continue
                    except json.JSONDecodeError:
                        pass
                if payload is None:
                    if self._any_valid_frame_after(fh):
                        raise CorruptWalError(
                            f"{self.path}: corrupt record at byte {pos} "
                            "with valid records after it; refusing to "
                            "truncate acked entries")
                    break                   # torn tail
                try:
                    rec = json.loads(payload)
                except json.JSONDecodeError:
                    if self._any_valid_frame_after(fh):
                        raise CorruptWalError(
                            f"{self.path}: corrupt record at byte {pos}")
                    break
                saw_framed = True
                self._replay(rec)
                good_end = fh.tell()
        size = os.path.getsize(self.path)
        if good_end < size:
            # drop the torn tail ON DISK: appends after recovery must
            # follow the last valid record, not garbage a future replay
            # would stop at
            with open(self.path, "r+b") as fh:
                fh.truncate(good_end)
        if needs_rewrite:
            # migrate legacy content to the framed format in place
            self._rewrite_file()

    def _any_valid_frame_after(self, fh) -> bool:
        """Scan the remainder of the file for any intact framed record."""
        while True:
            line_b = fh.readline()
            if not line_b:
                return False
            if not line_b.endswith(b"\n"):
                return False
            if self._unframe(line_b.decode("utf-8", "replace")) is not None:
                return True

    def _replay(self, rec: dict) -> None:
        op = rec.get("op")
        if op == "append":
            e = rec["entry"]
            self._entries.append(LogEntry(
                index=e["index"], term=e["term"], type=e["type"],
                data=e.get("data")))
            if len(self._entries) == 1:
                self._first = e["index"]
        elif op == "truncate":
            keep = rec["index"] - self._first + 1
            self._entries = self._entries[:max(keep, 0)]
        elif op == "compact":
            drop = rec["index"] - self._first + 1
            if drop > 0:
                self._entries = self._entries[drop:]
                self._first = rec["index"] + 1
        elif op == "reset":
            self._entries = []
            self._first = rec["first"]

    def _write(self, rec: dict) -> None:
        if self._fh is None:
            return
        self._fh.write(self._frame(json.dumps(rec, separators=(",", ":"))))
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())

    def _persist(self, entry: LogEntry) -> None:
        self._write({"op": "append", "entry": {
            "index": entry.index, "term": entry.term, "type": entry.type,
            "data": entry.data}})

    def _persist_truncate(self, index: int) -> None:
        self._write({"op": "truncate", "index": index})

    def _persist_compact(self, index: int) -> None:
        self._write({"op": "compact", "index": index})
        self._maybe_rewrite()

    def _persist_reset(self, first_index: int) -> None:
        self._write({"op": "reset", "first": first_index})
        self._maybe_rewrite()

    def _maybe_rewrite(self) -> None:
        try:
            if os.path.getsize(self.path) < self.rewrite_bytes:
                return
        except OSError:
            return
        self._rewrite_file()
        self._fh = open(self.path, "a", encoding="utf-8")

    def _rewrite_file(self) -> None:
        """Atomically rewrite the WAL as compact framed records. Leaves
        self._fh closed; callers reopen."""
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(self._frame(json.dumps(
                {"op": "reset", "first": self._first},
                separators=(",", ":"))))
            for e in self._entries:
                fh.write(self._frame(json.dumps(
                    {"op": "append", "entry": {
                        "index": e.index, "term": e.term, "type": e.type,
                        "data": e.data}}, separators=(",", ":"))))
            fh.flush()
            os.fsync(fh.fileno())
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        os.replace(tmp, self.path)
        self._fsync_dir()

    def _fsync_dir(self) -> None:
        """Make the dirent durable (file create / rename): fsyncing file
        CONTENTS alone doesn't survive power loss of the directory."""
        try:
            fd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


@dataclass
class Snapshot:
    last_index: int = 0
    last_term: int = 0
    state: Any = None        # FSM-opaque JSON-able blob
    # cluster configuration as of last_index (single-server membership
    # changes; None on snapshots from before the feature)
    peers: Any = None        # {name: [host, port]} | None


class SnapshotStore:
    """Latest-wins snapshot storage; file-backed when given a directory
    (reference: raft snapshot store + FSM Persist/Restore, nomad/fsm.go)."""

    def __init__(self, dirpath: Optional[str] = None) -> None:
        self.dirpath = dirpath
        self._latest: Optional[Snapshot] = None
        self._lock = threading.Lock()
        if dirpath:
            os.makedirs(dirpath, exist_ok=True)
            path = os.path.join(dirpath, "snapshot.json")
            if os.path.exists(path):
                try:
                    with open(path, encoding="utf-8") as fh:
                        rec = json.load(fh)
                    self._latest = Snapshot(rec["last_index"],
                                            rec["last_term"], rec["state"],
                                            rec.get("peers"))
                except (json.JSONDecodeError, KeyError, OSError):
                    pass

    def save(self, snap: Snapshot) -> None:
        with self._lock:
            self._latest = snap
            if self.dirpath:
                path = os.path.join(self.dirpath, "snapshot.json")
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump({"last_index": snap.last_index,
                               "last_term": snap.last_term,
                               "state": snap.state,
                               "peers": snap.peers}, fh,
                              separators=(",", ":"))
                os.replace(tmp, path)

    def latest(self) -> Optional[Snapshot]:
        with self._lock:
            return self._latest
