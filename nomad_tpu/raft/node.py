"""Raft consensus node: leader election, log replication, snapshots.

A from-scratch Raft in the role hashicorp/raft plays for the reference
(reference: nomad/server.go:1365 setupRaft wires the log store, transport
and FSM; leader.go:90 monitorLeadership reacts to leadership changes).
Standard Raft: randomized election timeouts, per-peer replicator threads,
majority commit with current-term gate, InstallSnapshot for lagging
followers, and a `barrier()` (commit a noop) for linearizable reads.

`apply()` is the write path every state mutation rides -- the analog of the
reference's `raftApply` (nomad/rpc.go raftApplyFuture): append to the log,
replicate to a majority, apply to the FSM, return the FSM's result.
"""
from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .log import InMemLogStore, LogEntry, Snapshot, SnapshotStore
from .transport import TcpTransport

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class NotLeaderError(Exception):
    def __init__(self, leader_id: str = "", leader_addr=None):
        super().__init__(f"not the leader (leader={leader_id or '?'})")
        self.leader_id = leader_id
        self.leader_addr = leader_addr


class _Pending:
    __slots__ = ("event", "result", "error", "term")

    def __init__(self, term: int):
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[Exception] = None
        self.term = term


class RaftNode:
    """One consensus participant. `peers` maps server name -> (host, port)
    for every member INCLUDING this node (static bootstrap configuration,
    like the reference's bootstrap_expect dev clusters)."""

    def __init__(self, name: str, transport: TcpTransport,
                 peers: Dict[str, Tuple[str, int]], fsm,
                 log: Optional[InMemLogStore] = None,
                 data_dir: Optional[str] = None,
                 heartbeat_interval: float = 0.05,
                 election_timeout: float = 0.25,
                 snapshot_threshold: int = 8192,
                 joining: bool = False):
        self.name = name
        self.transport = transport
        self.peers = dict(peers)
        # a joining server must NOT campaign before it hears from the
        # cluster's leader: self-elections on a 1-node bootstrap inflate
        # its term, and that term would leak back through append replies
        # and depose the real leader the moment it starts replicating
        self._joining = joining
        self.fsm = fsm
        self.log = log if log is not None else InMemLogStore()
        self.data_dir = data_dir
        self.snapshots = SnapshotStore(
            os.path.join(data_dir, "snapshots") if data_dir else None)
        self.heartbeat_interval = heartbeat_interval
        self.election_timeout = election_timeout
        self.snapshot_threshold = snapshot_threshold

        self._lock = threading.RLock()
        self.state = FOLLOWER
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.leader_id: Optional[str] = None
        self.commit_index = 0
        self.last_applied = 0
        self._meta_path = (os.path.join(data_dir, "raft_meta.json")
                           if data_dir else None)
        self._load_meta()

        # membership baseline for config-entry replay (truncations and
        # restarts re-derive peers from baseline + log)
        self._base_peers: Dict[str, Tuple[str, int]] = dict(peers)
        snap = self.snapshots.latest()
        self._snap_last_index = snap.last_index if snap else 0
        self._snap_last_term = snap.last_term if snap else 0
        if snap is not None:
            self.fsm.restore(snap.state)
            self.commit_index = snap.last_index
            self.last_applied = snap.last_index
            if snap.peers:
                self._base_peers = {k: tuple(v)
                                    for k, v in snap.peers.items()}
                self.peers = dict(self._base_peers)
        # replay config entries the log holds past the snapshot point
        for idx in range(self.log.first_index() or 1,
                         self.log.last_index() + 1):
            e = self.log.get(idx)
            if e is not None and e.type == "config":
                self._apply_config_change(self.peers, e.data)

        self._next_index: Dict[str, int] = {}
        self._match_index: Dict[str, int] = {}
        self._pending: Dict[int, _Pending] = {}
        self._election_deadline = self._rand_deadline()
        self._apply_cond = threading.Condition(self._lock)
        self._fsm_lock = threading.Lock()
        self._repl_events: Dict[str, threading.Event] = {}
        self._repl_threads: List[threading.Thread] = []
        self._leadership_cbs: List[Callable[[bool], None]] = []
        self._leadership_q: List[bool] = []
        self._leadership_signal = threading.Event()
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []

        transport.register("request_vote", self._handle_request_vote)
        transport.register("append_entries", self._handle_append_entries)
        transport.register("install_snapshot", self._handle_install_snapshot)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        for fn, name in ((self._ticker, "raft-ticker"),
                         (self._apply_loop, "raft-apply"),
                         (self._leadership_dispatch_loop, "raft-leadership")):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"{name}-{self.name}")
            t.start()
            self._threads.append(t)

    def shutdown(self) -> None:
        self._shutdown.set()
        with self._apply_cond:
            self._apply_cond.notify_all()
        for ev in self._repl_events.values():
            ev.set()

    def on_leadership(self, cb: Callable[[bool], None]) -> None:
        self._leadership_cbs.append(cb)

    # -- public API ----------------------------------------------------
    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def leader(self) -> Tuple[str, Optional[Tuple[str, int]]]:
        with self._lock:
            lid = self.leader_id or ""
            return lid, self.peers.get(lid)

    def apply(self, data: Any, timeout: float = 10.0,
              entry_type: str = "command") -> Any:
        """Replicate one command and return the FSM's application result."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id or "",
                                     self.peers.get(self.leader_id or ""))
            entry = LogEntry(index=self.log.last_index() + 1,
                             term=self.current_term, type=entry_type,
                             data=data)
            self.log.append(entry)
            self._match_self()
            pend = _Pending(self.current_term)
            self._pending[entry.index] = pend
        self._wake_replicators()
        self._maybe_advance_commit()
        if not pend.event.wait(timeout):
            with self._lock:
                self._pending.pop(entry.index, None)
            raise TimeoutError(f"raft apply timed out at {entry.index}")
        if pend.error is not None:
            raise pend.error
        return pend.result

    # -- membership changes (single-server at a time) -------------------
    @staticmethod
    def _apply_config_change(peers: Dict[str, Tuple[str, int]],
                             change: dict) -> None:
        if change.get("op") == "add":
            peers[change["name"]] = tuple(change["addr"])
        elif change.get("op") == "remove":
            peers.pop(change["name"], None)

    def add_voter(self, name: str, addr: Tuple[str, int],
                  timeout: float = 10.0) -> None:
        """Grow the cluster by one voter (reference: raft AddVoter via
        `nomad server join` + autopilot). Single change at a time."""
        self._config_change({"op": "add", "name": name,
                             "addr": list(addr)}, timeout)

    def remove_server(self, name: str, timeout: float = 10.0) -> None:
        """Shrink the cluster by one server (reference: raft
        RemoveServer via `nomad operator raft remove-peer` / autopilot
        dead-server cleanup)."""
        if name == self.name:
            raise ValueError("leader cannot remove itself")
        self._config_change({"op": "remove", "name": name}, timeout)

    def _config_change(self, change: dict, timeout: float) -> None:
        with self._lock:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id or "",
                                     self.peers.get(self.leader_id or ""))
            # one membership change at a time (raft single-server rule):
            # an uncommitted config entry must finish first
            for idx in range(self.commit_index + 1,
                             self.log.last_index() + 1):
                e = self.log.get(idx)
                if e is not None and e.type == "config":
                    raise RuntimeError("membership change already in "
                                       "flight")
            entry = LogEntry(index=self.log.last_index() + 1,
                             term=self.current_term, type="config",
                             data=change)
            self.log.append(entry)
            # config takes effect as soon as it is APPENDED (standard
            # single-server-change semantics): quorum math and
            # replication immediately use the new set
            self._apply_config_change(self.peers, change)
            if change["op"] == "add" and change["name"] != self.name:
                peer = change["name"]
                self._next_index[peer] = self.log.last_index() + 1
                self._match_index[peer] = 0
                self._spawn_replicator_locked(peer, tuple(change["addr"]),
                                              self.current_term)
            self._match_self()
            pend = _Pending(self.current_term)
            self._pending[entry.index] = pend
        self._wake_replicators()
        self._maybe_advance_commit()
        if not pend.event.wait(timeout):
            with self._lock:
                self._pending.pop(entry.index, None)
            raise TimeoutError("membership change timed out")
        if pend.error is not None:
            raise pend.error

    def _spawn_replicator_locked(self, peer: str, addr,
                                 term: int) -> None:
        ev = self._repl_events.setdefault(peer, threading.Event())
        ev.set()
        t = threading.Thread(target=self._replicate_loop,
                             args=(peer, addr, term),
                             daemon=True,
                             name=f"raft-repl-{self.name}->{peer}")
        t.start()
        self._repl_threads.append(t)

    def _rebuild_peers_locked(self) -> None:
        """Re-derive peers from the baseline + surviving log entries
        (a follower truncation may have dropped an uncommitted config)."""
        peers = dict(self._base_peers)
        for idx in range(self.log.first_index() or 1,
                         self.log.last_index() + 1):
            e = self.log.get(idx)
            if e is not None and e.type == "config":
                self._apply_config_change(peers, e.data)
        self.peers = peers

    def barrier(self, timeout: float = 10.0) -> int:
        """Commit a noop; after it applies, local reads reflect every write
        committed before the call (linearizable read point)."""
        self.apply(None, timeout=timeout, entry_type="barrier")
        with self._lock:
            return self.last_applied

    def configuration(self) -> List[Tuple[str, Tuple[str, int]]]:
        """Copied peer list for observers (the live dict mutates under
        membership changes)."""
        with self._lock:
            return sorted(self.peers.items())

    def stats(self) -> dict:
        with self._lock:
            return {"state": self.state, "term": self.current_term,
                    "leader": self.leader_id,
                    "commit_index": self.commit_index,
                    "last_applied": self.last_applied,
                    "last_log_index": self.log.last_index(),
                    "snapshot_index": self._snap_last_index}

    # -- persistence ---------------------------------------------------
    def _load_meta(self) -> None:
        if self._meta_path and os.path.exists(self._meta_path):
            try:
                with open(self._meta_path, encoding="utf-8") as fh:
                    m = json.load(fh)
                self.current_term = m.get("term", 0)
                self.voted_for = m.get("voted_for")
            except (json.JSONDecodeError, OSError):
                pass

    def _save_meta(self) -> None:
        if not self._meta_path:
            return
        os.makedirs(os.path.dirname(self._meta_path), exist_ok=True)
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"term": self.current_term,
                       "voted_for": self.voted_for}, fh)
        os.replace(tmp, self._meta_path)

    # -- helpers -------------------------------------------------------
    def _rand_deadline(self) -> float:
        return time.monotonic() + self.election_timeout * (
            1.0 + random.random())

    def _last_log(self) -> Tuple[int, int]:
        """(last index, last term) accounting for a compacted prefix."""
        li = self.log.last_index()
        if li <= self._snap_last_index or self.log.first_index() == 0:
            return self._snap_last_index, self._snap_last_term
        return li, self.log.last_term()

    def _term_at(self, index: int) -> Optional[int]:
        if index == 0:
            return 0
        if index == self._snap_last_index:
            return self._snap_last_term
        e = self.log.get(index)
        return e.term if e else None

    def _match_self(self) -> None:
        self._match_index[self.name] = self.log.last_index()

    def _become_follower(self, term: int, leader: Optional[str]) -> None:
        was_leader = self.state == LEADER
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._save_meta()
        self.state = FOLLOWER
        if leader is not None:
            self.leader_id = leader
        self._election_deadline = self._rand_deadline()
        if was_leader:
            # fail in-flight applies immediately (hashicorp/raft fails
            # futures on stepdown rather than letting them time out)
            err = NotLeaderError(leader or "", self.peers.get(leader or ""))
            for pend in self._pending.values():
                pend.error = err
                pend.event.set()
            self._pending.clear()
            self._notify_leadership(False)

    def _notify_leadership(self, is_leader: bool) -> None:
        """Dispatch on a separate thread: callbacks run raft operations
        (barrier, apply) and must not run under self._lock. A serialized
        queue preserves gained/lost ordering (reference: the
        leaderCh/monitorLeadership pattern, nomad/leader.go:90)."""
        self._leadership_q.append(is_leader)
        self._leadership_signal.set()

    def _leadership_dispatch_loop(self) -> None:
        while not self._shutdown.is_set():
            self._leadership_signal.wait(0.5)
            self._leadership_signal.clear()
            while self._leadership_q:
                is_leader = self._leadership_q.pop(0)
                for cb in self._leadership_cbs:
                    try:
                        cb(is_leader)
                    except Exception:   # noqa: BLE001
                        pass

    def _wake_replicators(self) -> None:
        for ev in self._repl_events.values():
            ev.set()

    # -- ticker / elections --------------------------------------------
    def _ticker(self) -> None:
        while not self._shutdown.wait(self.heartbeat_interval / 2):
            with self._lock:
                if self.state == LEADER or self._joining:
                    continue
                expired = time.monotonic() >= self._election_deadline
            if expired:
                self._run_election()

    def _run_election(self) -> None:
        with self._lock:
            self.state = CANDIDATE
            self.current_term += 1
            self.voted_for = self.name
            self._save_meta()
            term = self.current_term
            self.leader_id = None
            self._election_deadline = self._rand_deadline()
            last_idx, last_term = self._last_log()
        votes = {self.name}
        vote_lock = threading.Lock()
        done = threading.Event()
        majority = len(self.peers) // 2 + 1

        def ask(peer: str, addr) -> None:
            try:
                reply = self.transport.send(addr, {
                    "type": "request_vote", "term": term,
                    "candidate": self.name,
                    "last_log_index": last_idx, "last_log_term": last_term,
                }, timeout=self.election_timeout)
            except (OSError, ConnectionError):
                return
            with self._lock:
                if reply.get("term", 0) > self.current_term:
                    self._become_follower(reply["term"], None)
                    done.set()
                    return
            if reply.get("granted"):
                with vote_lock:
                    votes.add(peer)
                    if len(votes) >= majority:
                        done.set()

        threads = []
        for peer, addr in self.peers.items():
            if peer == self.name:
                continue
            t = threading.Thread(target=ask, args=(peer, addr), daemon=True)
            t.start()
            threads.append(t)
        done.wait(self.election_timeout)
        with self._lock:
            if (self.state == CANDIDATE and self.current_term == term
                    and len(votes) >= majority):
                self._become_leader()

    def _become_leader(self) -> None:
        self.state = LEADER
        self.leader_id = self.name
        last = self.log.last_index()
        for peer in self.peers:
            if peer == self.name:
                continue
            self._next_index[peer] = last + 1
            self._match_index[peer] = 0
            ev = self._repl_events.setdefault(peer, threading.Event())
            ev.set()
        self._match_self()
        for peer, addr in self.peers.items():
            if peer == self.name:
                continue
            self._spawn_replicator_locked(peer, addr, self.current_term)
        # Commit a noop from the new term so earlier-term entries commit
        # (Raft safety: only current-term entries commit by counting).
        noop = LogEntry(index=self.log.last_index() + 1,
                        term=self.current_term, type="noop", data=None)
        self.log.append(noop)
        self._match_self()
        self._notify_leadership(True)
        self._wake_replicators()

    # -- replication (leader side) -------------------------------------
    def _replicate_loop(self, peer: str, addr, term: int) -> None:
        ev = self._repl_events[peer]
        while not self._shutdown.is_set():
            ev.wait(self.heartbeat_interval)
            ev.clear()
            with self._lock:
                if self.state != LEADER or self.current_term != term:
                    return
                if peer not in self.peers:      # removed from the config
                    self._next_index.pop(peer, None)
                    self._match_index.pop(peer, None)
                    return
            try:
                self._replicate_once(peer, addr, term)
            except (OSError, ConnectionError):
                time.sleep(self.heartbeat_interval)

    def _replicate_once(self, peer: str, addr, term: int) -> None:
        with self._lock:
            next_idx = self._next_index.get(peer, self.log.last_index() + 1)
            first = self.log.first_index()
            need_snapshot = (self._snap_last_index > 0
                             and next_idx <= self._snap_last_index
                             and (first == 0 or next_idx < first))
            if need_snapshot:
                snap = self.snapshots.latest()
            else:
                prev_index = next_idx - 1
                prev_term = self._term_at(prev_index)
                if prev_term is None:       # compacted under us: snapshot
                    need_snapshot = True
                    snap = self.snapshots.latest()
                else:
                    entries = self.log.entries_from(next_idx, limit=256)
                    commit = self.commit_index
        if need_snapshot and snap is None:
            return              # nothing to send yet
        if need_snapshot:
            reply = self.transport.send(addr, {
                "type": "install_snapshot", "term": term,
                "leader": self.name, "last_index": snap.last_index,
                "last_term": snap.last_term, "state": snap.state,
                "peers": snap.peers,
            }, timeout=10.0)
            with self._lock:
                if reply.get("term", 0) > self.current_term:
                    self._become_follower(reply["term"], None)
                    return
                self._next_index[peer] = snap.last_index + 1
                self._match_index[peer] = snap.last_index
            self._maybe_advance_commit()
            return
        reply = self.transport.send(addr, {
            "type": "append_entries", "term": term, "leader": self.name,
            "prev_log_index": prev_index, "prev_log_term": prev_term,
            "entries": [{"index": e.index, "term": e.term, "type": e.type,
                         "data": e.data} for e in entries],
            "leader_commit": commit,
        }, timeout=2.0)
        with self._lock:
            if reply.get("term", 0) > self.current_term:
                self._become_follower(reply["term"], None)
                return
            if self.state != LEADER or self.current_term != term:
                return
            if reply.get("success"):
                if entries:
                    self._next_index[peer] = entries[-1].index + 1
                    self._match_index[peer] = entries[-1].index
            else:
                # follower hints its last index to speed backtracking
                hint = reply.get("last_index")
                if hint is not None and hint + 1 < next_idx:
                    self._next_index[peer] = hint + 1
                else:
                    self._next_index[peer] = max(1, next_idx - 1)
                self._repl_events[peer].set()
        if reply.get("success") and entries:
            self._maybe_advance_commit()
            with self._lock:
                more = self._next_index.get(peer, 1) <= self.log.last_index()
            if more:
                self._repl_events[peer].set()

    def _maybe_advance_commit(self) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            majority = len(self.peers) // 2 + 1
            matches = sorted(
                (self._match_index.get(p, 0) for p in self.peers),
                reverse=True)
            candidate = matches[majority - 1]
            if candidate > self.commit_index and \
                    self._term_at(candidate) == self.current_term:
                self.commit_index = candidate
                self._apply_cond.notify_all()

    # -- apply loop ----------------------------------------------------
    def _apply_loop(self) -> None:
        while not self._shutdown.is_set():
            with self._apply_cond:
                while (self.last_applied >= self.commit_index
                       and not self._shutdown.is_set()):
                    self._apply_cond.wait(0.2)
                if self._shutdown.is_set():
                    return
                start = self.last_applied + 1
                end = self.commit_index
            for idx in range(start, end + 1):
                pend = None
                # _fsm_lock serializes with InstallSnapshot: a concurrent
                # restore must not interleave with entry application, and
                # entries the snapshot already covers must be skipped.
                with self._fsm_lock:
                    with self._lock:
                        if idx <= self.last_applied:
                            continue        # snapshot advanced past us
                        entry = self.log.get(idx)
                    result, error = None, None
                    if entry is not None and entry.type == "command":
                        try:
                            result = self.fsm.apply(entry.data)
                        except Exception as e:   # noqa: BLE001
                            error = e
                    with self._lock:
                        self.last_applied = idx
                        pend = self._pending.pop(idx, None)
                        if pend is not None and entry is not None and \
                                pend.term != entry.term:
                            # a different leader's entry landed at this
                            # index: the original write was lost
                            error = NotLeaderError(self.leader_id or "")
                            result = None
                if pend is not None:
                    pend.result, pend.error = result, error
                    pend.event.set()
            self._maybe_snapshot()

    def _maybe_snapshot(self) -> None:
        # _fsm_lock FIRST: a concurrent InstallSnapshot must not slip in
        # between reading last_applied and dumping the FSM (the dump would
        # carry newer state than its label, corrupting later restores).
        with self._fsm_lock:
            with self._lock:
                log_len = self.log.last_index() - self.log.first_index() + 1
                if (self.log.first_index() == 0
                        or log_len < self.snapshot_threshold):
                    return
                last = self.last_applied
                if last <= self._snap_last_index:
                    return
                term = self._term_at(last) or self.current_term
            blob = self.fsm.snapshot()
            with self._lock:
                # peers AS OF the snapshot point, NOT current: an
                # uncommitted config entry past `last` is applied-on-
                # append in self.peers but may still be truncated away --
                # baking it into the baseline would make it permanent
                peers_at = dict(self._base_peers)
                for idx in range(self.log.first_index() or 1, last + 1):
                    e = self.log.get(idx)
                    if e is not None and e.type == "config":
                        self._apply_config_change(peers_at, e.data)
                peers_wire = {k: list(v) for k, v in peers_at.items()}
            self.snapshots.save(Snapshot(last_index=last, last_term=term,
                                         state=blob, peers=peers_wire))
            with self._lock:
                self._snap_last_index = last
                self._snap_last_term = term
                # compaction drops replayable config entries: re-baseline
                self._base_peers = peers_at
                self.log.compact_to(last)

    # -- RPC handlers (follower side) ----------------------------------
    def _handle_request_vote(self, msg: dict) -> dict:
        with self._lock:
            # a server outside the current configuration (removed, or not
            # yet added) must not disrupt the cluster: deny WITHOUT
            # adopting its term (hashicorp/raft's non-voter guard). Only
            # enforced when this node has LEARNED a multi-member config --
            # a fresh joiner still on its {self} bootstrap must keep
            # granting votes or a post-add leader loss can deadlock the
            # election (quorum includes the joiner, which knows nobody).
            if len(self.peers) > 1 and \
                    msg.get("candidate") not in self.peers:
                return {"term": self.current_term, "granted": False}
            term = msg["term"]
            if term < self.current_term:
                return {"term": self.current_term, "granted": False}
            if term > self.current_term:
                self._become_follower(term, None)
            last_idx, last_term = self._last_log()
            up_to_date = (msg["last_log_term"], msg["last_log_index"]) >= (
                last_term, last_idx)
            if up_to_date and self.voted_for in (None, msg["candidate"]):
                self.voted_for = msg["candidate"]
                self._save_meta()
                self._election_deadline = self._rand_deadline()
                return {"term": self.current_term, "granted": True}
            return {"term": self.current_term, "granted": False}

    def _handle_append_entries(self, msg: dict) -> dict:
        with self._lock:
            term = msg["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False,
                        "last_index": self.log.last_index()}
            if term > self.current_term or self.state != FOLLOWER:
                self._become_follower(term, msg["leader"])
            self.leader_id = msg["leader"]
            self._joining = False           # heard the cluster: full member
            self._election_deadline = self._rand_deadline()

            prev_index = msg["prev_log_index"]
            prev_term = msg["prev_log_term"]
            my_term = self._term_at(prev_index)
            if my_term is None or my_term != prev_term:
                return {"term": self.current_term, "success": False,
                        "last_index": min(self.log.last_index(),
                                          prev_index - 1)}
            for e in msg["entries"]:
                existing = self.log.get(e["index"])
                if existing is not None:
                    if existing.term == e["term"]:
                        continue
                    self.log.truncate_after(e["index"] - 1)
                    # a dropped uncommitted config entry must un-apply
                    self._rebuild_peers_locked()
                if self.log.first_index() == 0 and e["index"] > 1 and \
                        self.log.last_index() + 1 != e["index"]:
                    # empty log after snapshot restore: entries continue
                    # from the snapshot point
                    self.log.reset(e["index"])
                self.log.append(LogEntry(index=e["index"], term=e["term"],
                                         type=e["type"], data=e["data"]))
                if e["type"] == "config":
                    self._apply_config_change(self.peers, e["data"])
            if msg["leader_commit"] > self.commit_index:
                self.commit_index = min(msg["leader_commit"],
                                        self.log.last_index())
                self._apply_cond.notify_all()
            return {"term": self.current_term, "success": True}

    def _handle_install_snapshot(self, msg: dict) -> dict:
        with self._lock:
            term = msg["term"]
            if term < self.current_term:
                return {"term": self.current_term}
            self._become_follower(term, msg["leader"])
            self._election_deadline = self._rand_deadline()
            if msg["last_index"] <= self._snap_last_index:
                return {"term": self.current_term}
        with self._fsm_lock:        # serialize with the apply loop
            self.fsm.restore(msg["state"])
            with self._lock:
                self.snapshots.save(Snapshot(last_index=msg["last_index"],
                                             last_term=msg["last_term"],
                                             state=msg["state"],
                                             peers=msg.get("peers")))
                self._snap_last_index = msg["last_index"]
                self._snap_last_term = msg["last_term"]
                if msg.get("peers"):
                    self._base_peers = {k: tuple(v) for k, v
                                        in msg["peers"].items()}
                    self.peers = dict(self._base_peers)
                self.log.reset(msg["last_index"] + 1)
                self.commit_index = max(self.commit_index, msg["last_index"])
                self.last_applied = max(self.last_applied, msg["last_index"])
        return {"term": self.current_term}
