"""Feasibility iterators: boolean filters over candidate nodes.

Semantic parity with /root/reference/scheduler/feasible.go:
  StaticIterator/RandomIterator (feasible.go:60-146), DriverChecker (:476),
  ConstraintChecker (:760) with the full operand set of checkConstraint
  (:833), DeviceChecker (:1270), HostVolumeChecker (:148),
  NetworkChecker (:379), DistinctHostsIterator (:555),
  DistinctPropertyIterator (:661), FeasibilityWrapper with computed-class
  memoization (:1126).
"""
from __future__ import annotations

import operator
import re
from typing import Dict, Iterable, List, Optional, Set

from ..structs import (
    Constraint, Job, Node, TaskGroup,
    CONSTRAINT_ATTR_IS_NOT_SET, CONSTRAINT_ATTR_IS_SET,
    CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY,
    CONSTRAINT_REGEX, CONSTRAINT_SEMVER, CONSTRAINT_SET_CONTAINS,
    CONSTRAINT_SET_CONTAINS_ALL, CONSTRAINT_SET_CONTAINS_ANY,
    CONSTRAINT_VERSION,
)
from .context import (
    ELIGIBILITY_ELIGIBLE, ELIGIBILITY_ESCAPED, ELIGIBILITY_INELIGIBLE,
    ELIGIBILITY_UNKNOWN, EvalContext,
)
from .util import resolve_target, shuffle_nodes

FILTER_CONSTRAINT_HOST_VOLUMES = "missing compatible host volumes"
FILTER_CONSTRAINT_DRIVERS = "missing drivers"
FILTER_CONSTRAINT_DEVICES = "missing devices"
FILTER_CONSTRAINT_CSI_VOLUMES = "CSI volume has exhausted its available writer claims"
FILTER_CONSTRAINT_CSI_PLUGINS = "CSI plugin is missing or unhealthy"


class FeasibleIterator:
    """Iterator protocol: next() -> Node | None, reset()."""

    def next(self) -> Optional[Node]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class StaticIterator(FeasibleIterator):
    """Returns nodes in a fixed order (reference: feasible.go:60)."""

    def __init__(self, ctx: EvalContext, nodes: List[Node]):
        self.ctx = ctx
        self.nodes = list(nodes)
        self.offset = 0
        self.seen = 0

    def next(self) -> Optional[Node]:
        if self.offset == len(self.nodes) or self.seen == len(self.nodes):
            return None
        n = self.nodes[self.offset]
        self.offset += 1
        self.seen += 1
        self.ctx.metrics.nodes_evaluated += 1
        return n

    def reset(self) -> None:
        self.offset = 0
        self.seen = 0

    def set_nodes(self, nodes: List[Node]) -> None:
        self.nodes = list(nodes)
        self.offset = 0
        self.seen = 0


def new_random_iterator(ctx: EvalContext, nodes: List[Node]) -> StaticIterator:
    """Shuffled StaticIterator (reference: feasible.go:129 NewRandomIterator);
    the shuffle itself happens in GenericStack.set_nodes so it can be seeded
    with the eval id."""
    return StaticIterator(ctx, nodes)


# ---------------------------------------------------------------------------
# Constraint checking primitives
# ---------------------------------------------------------------------------

_ORDER_OPS = {"<": operator.lt, "<=": operator.le,
              ">": operator.gt, ">=": operator.ge}


def _check_order(op: str, lval, rval) -> bool:
    """Numeric if both parse as ints, then floats, else lexical
    (reference: feasible.go checkOrder)."""
    l, r = str(lval), str(rval)
    for conv in (int, float):
        try:
            return _ORDER_OPS[op](conv(l), conv(r))
        except (ValueError, TypeError):
            continue
    return _ORDER_OPS[op](l, r)


def parse_version(v: str) -> Optional[tuple]:
    """Parse '1.2.3-beta.1+meta' into a comparable tuple.
    Prerelease versions sort before releases (semver rule)."""
    v = str(v).strip().lstrip("v")
    v = v.split("+", 1)[0]
    if "-" in v:
        core, pre = v.split("-", 1)
    else:
        core, pre = v, None
    try:
        nums = tuple(int(x) for x in core.split("."))
    except ValueError:
        return None
    while len(nums) < 3:
        nums = nums + (0,)
    # (release=1) > (prerelease=0); prerelease idents compare component-wise
    if pre is None:
        return nums + ((1,),)
    pre_ids = tuple((0, int(p)) if p.isdigit() else (1, p)
                    for p in pre.split("."))
    return nums + ((0, pre_ids),)


_VER_CONSTRAINT_RE = re.compile(r"^\s*(>=|<=|!=|>|<|=|~>)?\s*(.+?)\s*$")


def check_version_constraint(lval, constraint_expr: str,
                             allow_prerelease: bool = True) -> bool:
    """Evaluate 'version' / 'semver' constraints like '>= 1.2, < 2.0'
    (reference: feasible.go checkVersionMatch with go-version semantics;
    'semver' is strict -- prereleases never satisfy range constraints)."""
    actual = parse_version(str(lval))
    if actual is None:
        return False
    is_prerelease = actual[3][0] == 0
    for part in str(constraint_expr).split(","):
        m = _VER_CONSTRAINT_RE.match(part)
        if not m:
            return False
        op = m.group(1) or "="
        want = parse_version(m.group(2))
        if want is None:
            return False
        if not allow_prerelease and is_prerelease and op != "=":
            return False
        if op == "=":
            ok = actual == want
        elif op == "!=":
            ok = actual != want
        elif op == "~>":   # pessimistic: >= want, < next significant
            raw = m.group(2).lstrip("v").split("-")[0]
            n = len(raw.split("."))
            bump = list(want[:3])
            if n <= 1:
                bump = [bump[0] + 1, 0, 0]
            elif n == 2:
                bump = [bump[0] + 1, 0, 0]
            else:
                bump = [bump[0], bump[1] + 1, 0]
            ok = actual >= want and actual[:3] < tuple(bump)
        else:
            ok = _ORDER_OPS[op](actual, want)
        if not ok:
            return False
    return True


def check_set_contains_all(lval, rval) -> bool:
    have = {p.strip() for p in str(lval).split(",")}
    want = [p.strip() for p in str(rval).split(",")]
    return all(w in have for w in want)


def check_set_contains_any(lval, rval) -> bool:
    have = {p.strip() for p in str(lval).split(",")}
    want = [p.strip() for p in str(rval).split(",")]
    return any(w in have for w in want)


def check_constraint(ctx: EvalContext, operand: str, lval, rval,
                     l_found: bool, r_found: bool) -> bool:
    """The full operand dispatch (reference: feasible.go:833 checkConstraint)."""
    if operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
        return True  # handled by dedicated iterators
    if operand in ("=", "==", "is"):
        return l_found and r_found and str(lval) == str(rval)
    if operand in ("!=", "not"):
        return str(lval) != str(rval)
    if operand in _ORDER_OPS:
        return l_found and r_found and _check_order(operand, lval, rval)
    if operand == CONSTRAINT_ATTR_IS_SET:
        return l_found
    if operand == CONSTRAINT_ATTR_IS_NOT_SET:
        return not l_found
    if operand == CONSTRAINT_VERSION:
        return l_found and r_found and check_version_constraint(
            lval, rval, allow_prerelease=True)
    if operand == CONSTRAINT_SEMVER:
        return l_found and r_found and check_version_constraint(
            lval, rval, allow_prerelease=False)
    if operand == CONSTRAINT_REGEX:
        if not (l_found and r_found):
            return False
        pat = ctx.regex(str(rval))
        return pat is not None and pat.search(str(lval)) is not None
    if operand in (CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL):
        return l_found and r_found and check_set_contains_all(lval, rval)
    if operand == CONSTRAINT_SET_CONTAINS_ANY:
        return l_found and r_found and check_set_contains_any(lval, rval)
    return False


def nodes_meet_constraint(ctx: EvalContext, node: Node,
                          constraint: Constraint) -> bool:
    lval, l_ok = resolve_target(constraint.l_target, node)
    rval, r_ok = resolve_target(constraint.r_target, node)
    return check_constraint(ctx, constraint.operand, lval, rval, l_ok, r_ok)


# ---------------------------------------------------------------------------
# Checkers (single-node predicates used inside the FeasibilityWrapper)
# ---------------------------------------------------------------------------

class ConstraintChecker:
    """(reference: feasible.go:760)"""

    def __init__(self, ctx: EvalContext, constraints: List[Constraint]):
        self.ctx = ctx
        self.constraints = constraints or []

    def set_constraints(self, constraints: List[Constraint]) -> None:
        self.constraints = constraints or []

    def feasible(self, node: Node) -> bool:
        for c in self.constraints:
            if not nodes_meet_constraint(self.ctx, node, c):
                self.ctx.metrics.filter_node(node.computed_class, str(c))
                return False
        return True


class DriverChecker:
    """(reference: feasible.go:476)"""

    def __init__(self, ctx: EvalContext, drivers: Set[str]):
        self.ctx = ctx
        self.drivers = drivers or set()

    def set_drivers(self, drivers: Set[str]) -> None:
        self.drivers = drivers

    def feasible(self, node: Node) -> bool:
        for driver in self.drivers:
            info = node.drivers.get(driver)
            if info is not None:
                if not (info.detected and info.healthy):
                    self.ctx.metrics.filter_node(
                        node.computed_class, FILTER_CONSTRAINT_DRIVERS)
                    return False
                continue
            # fall back to fingerprint attribute driver.<name> in {1,true}
            raw = node.attributes.get(f"driver.{driver}", "")
            if str(raw).lower() not in ("1", "true"):
                self.ctx.metrics.filter_node(
                    node.computed_class, FILTER_CONSTRAINT_DRIVERS)
                return False
        return True


class DeviceChecker:
    """Do the node's device groups cover the TG's device asks, constraints
    included? (reference: feasible.go:1270)"""

    def __init__(self, ctx: EvalContext):
        self.ctx = ctx
        self.required: list = []

    def set_task_group(self, tg: TaskGroup) -> None:
        self.required = []
        for task in tg.tasks:
            self.required.extend(task.resources.devices)

    def feasible(self, node: Node) -> bool:
        if not self.required:
            return True
        for req in self.required:
            if not self._has_device(node, req):
                self.ctx.metrics.filter_node(
                    node.computed_class, FILTER_CONSTRAINT_DEVICES)
                return False
        return True

    def _has_device(self, node: Node, req) -> bool:
        for group in node.node_resources.devices:
            if not group.matches_request(req.name):
                continue
            if len(group.instance_ids) < req.count:
                continue
            if req.constraints and not self._check_device_constraints(
                    group, req.constraints):
                continue
            return True
        return False

    def _check_device_constraints(self, group, constraints) -> bool:
        for c in constraints:
            lval, l_ok = self._resolve_device_target(c.l_target, group)
            rval, r_ok = self._resolve_device_target(c.r_target, group)
            if not check_constraint(self.ctx, c.operand, lval, rval, l_ok, r_ok):
                return False
        return True

    @staticmethod
    def _resolve_device_target(target: str, group):
        if not target.startswith("${"):
            return target, True
        inner = target[2:-1]
        if inner.startswith("device.attr."):
            key = inner[len("device.attr."):]
            if key in group.attributes:
                return group.attributes[key], True
            return "", False
        if inner == "device.model":
            return group.name, True
        if inner == "device.vendor":
            return group.vendor, True
        if inner == "device.type":
            return group.type, True
        return "", False


class HostVolumeChecker:
    """(reference: feasible.go:148)"""

    def __init__(self, ctx: EvalContext):
        self.ctx = ctx
        self.volumes: Dict[str, object] = {}

    def set_volumes(self, alloc_name: str, volumes: Dict[str, object]) -> None:
        self.volumes = {}
        for name, req in (volumes or {}).items():
            if req.type != "host":
                continue
            self.volumes[name] = (req.source_for(alloc_name), req.read_only)

    def feasible(self, node: Node) -> bool:
        for name, (source, read_only) in self.volumes.items():
            cfg = node.host_volumes.get(source)
            if cfg is None:
                self.ctx.metrics.filter_node(
                    node.computed_class, FILTER_CONSTRAINT_HOST_VOLUMES)
                return False
            if cfg.read_only and not read_only:
                self.ctx.metrics.filter_node(
                    node.computed_class, FILTER_CONSTRAINT_HOST_VOLUMES)
                return False
        return True


class CSIVolumeChecker:
    """Volume exists + schedulable + claimable + node runs a healthy
    instance of its plugin (reference: feasible.go:230 CSIVolumeChecker)."""

    def __init__(self, ctx: EvalContext):
        self.ctx = ctx
        self.namespace = "default"
        self.volumes: Dict[str, object] = {}

    def set_namespace(self, namespace: str) -> None:
        self.namespace = namespace

    def set_volumes(self, alloc_name: str, volumes: Dict[str, object]) -> None:
        self.volumes = {}
        for name, req in (volumes or {}).items():
            if req.type != "csi":
                continue
            self.volumes[name] = (req.source_for(alloc_name), req.read_only)

    def feasible(self, node: Node) -> bool:
        if not self.volumes:
            return True
        from ..structs.csi import plugin_healthy
        snap = self.ctx.state
        for name, (source, read_only) in self.volumes.items():
            vol = (snap.csi_volume_by_id(self.namespace, source)
                   if hasattr(snap, "csi_volume_by_id") else None)
            if vol is None or not vol.schedulable:
                self.ctx.metrics.filter_node(
                    node.computed_class, FILTER_CONSTRAINT_CSI_VOLUMES)
                return False
            mode = "read" if read_only else "write"
            # claims held by THIS node's allocs don't block re-placement
            # onto the same node, for reads and writes alike (reference:
            # feasible.go claim checks via WriteFreeClaims w/ ownership)
            if not vol.claim_ok(mode):
                holders = set(c.node_id for c in vol.write_claims.values())
                holders |= set(c.node_id for c in vol.read_claims.values())
                if holders != {node.id}:
                    self.ctx.metrics.filter_node(
                        node.computed_class, FILTER_CONSTRAINT_CSI_VOLUMES)
                    return False
            # plugin presence on the node, healthy
            if not plugin_healthy(
                    (node.csi_node_plugins or {}).get(vol.plugin_id)):
                self.ctx.metrics.filter_node(
                    node.computed_class, FILTER_CONSTRAINT_CSI_PLUGINS)
                return False
        return True


class NetworkChecker:
    """Does the node expose the asked host networks / network mode?
    (reference: feasible.go:379)"""

    def __init__(self, ctx: EvalContext):
        self.ctx = ctx
        self.network = None

    def set_network(self, network) -> None:
        self.network = network

    def feasible(self, node: Node) -> bool:
        if self.network is None:
            return True
        mode = self.network.mode or "host"
        if mode.startswith("cni/"):
            plugin = mode[len("cni/"):]
            if f"plugins.cni.version.{plugin}" not in node.attributes:
                self.ctx.metrics.filter_node(
                    node.computed_class, f"missing network CNI plugin {plugin}")
                return False
            return True
        if mode == "bridge":
            if str(node.attributes.get("nomad.bridge", "true")).lower() == "false":
                self.ctx.metrics.filter_node(
                    node.computed_class, "missing bridge network")
                return False
            return True
        # host networks referenced by ports must exist on the node
        wanted = set()
        for p in list(self.network.reserved_ports) + list(self.network.dynamic_ports):
            if p.host_network and p.host_network != "default":
                wanted.add(p.host_network)
        if wanted:
            have = {n.device for n in node.node_resources.networks}
            missing = wanted - have
            if missing:
                self.ctx.metrics.filter_node(
                    node.computed_class,
                    f"missing host network {sorted(missing)[0]!r} for port")
                return False
        return True


# ---------------------------------------------------------------------------
# Wrapper + distinct iterators
# ---------------------------------------------------------------------------

class FeasibilityWrapper(FeasibleIterator):
    """Runs job-level then tg-level checkers with computed-node-class
    memoization (reference: feasible.go:1126 FeasibilityWrapper)."""

    def __init__(self, ctx: EvalContext, source: FeasibleIterator,
                 job_checkers: list, tg_checkers: list,
                 avail_checkers: list):
        self.ctx = ctx
        self.source = source
        self.job_checkers = job_checkers
        self.tg_checkers = tg_checkers
        self.avail_checkers = avail_checkers   # per-alloc, never class-cached
        self.tg_name = ""

    def set_task_group(self, tg_name: str) -> None:
        self.tg_name = tg_name

    def reset(self) -> None:
        self.source.reset()

    def next(self) -> Optional[Node]:
        elig = self.ctx.eligibility()
        while True:
            node = self.source.next()
            if node is None:
                return None
            cls = node.computed_class

            # job-level
            job_status = elig.job_status(cls)
            if job_status == ELIGIBILITY_INELIGIBLE:
                self.ctx.metrics.filter_node(cls, "")
                continue
            if job_status in (ELIGIBILITY_ESCAPED, ELIGIBILITY_UNKNOWN):
                ok = all(c.feasible(node) for c in self.job_checkers)
                if job_status == ELIGIBILITY_UNKNOWN:
                    elig.set_job_eligibility(ok, cls)
                if not ok:
                    continue

            # tg-level
            tg_status = elig.task_group_status(self.tg_name, cls)
            if tg_status == ELIGIBILITY_INELIGIBLE:
                self.ctx.metrics.filter_node(cls, "")
                continue
            if tg_status in (ELIGIBILITY_ESCAPED, ELIGIBILITY_UNKNOWN):
                ok = all(c.feasible(node) for c in self.tg_checkers)
                if tg_status == ELIGIBILITY_UNKNOWN:
                    elig.set_task_group_eligibility(ok, self.tg_name, cls)
                if not ok:
                    continue

            # availability checkers always run per node
            if not all(c.feasible(node) for c in self.avail_checkers):
                continue
            return node


class DistinctHostsIterator(FeasibleIterator):
    """Filters nodes that already hold an alloc of this job/TG when
    distinct_hosts is set (reference: feasible.go:555)."""

    def __init__(self, ctx: EvalContext, source: FeasibleIterator):
        self.ctx = ctx
        self.source = source
        self.tg = None
        self.job = None
        self.tg_distinct = False
        self.job_distinct = False

    def set_task_group(self, tg: TaskGroup) -> None:
        self.tg = tg
        self.tg_distinct = self._has_distinct(tg.constraints)

    def set_job(self, job: Job) -> None:
        self.job = job
        self.job_distinct = self._has_distinct(job.constraints)

    @staticmethod
    def _has_distinct(constraints) -> bool:
        return any(c.operand == CONSTRAINT_DISTINCT_HOSTS and
                   str(c.r_target).lower() not in ("false",)
                   for c in constraints or [])

    def next(self) -> Optional[Node]:
        while True:
            node = self.source.next()
            if node is None or not (self.tg_distinct or self.job_distinct):
                return node
            if self._satisfies(node):
                return node
            self.ctx.metrics.filter_node(
                node.computed_class, CONSTRAINT_DISTINCT_HOSTS)

    def _satisfies(self, node: Node) -> bool:
        proposed = self.ctx.proposed_allocs(node.id)
        for alloc in proposed:
            job_collision = alloc.job_id == self.job.id and \
                alloc.namespace == self.job.namespace
            task_collision = alloc.task_group == self.tg.name
            if self.job_distinct and job_collision:
                return False
            if self.tg_distinct and job_collision and task_collision:
                return False
        return True

    def reset(self) -> None:
        self.source.reset()


class DistinctPropertyIterator(FeasibleIterator):
    """distinct_property constraint: bound allocs per attribute value
    (reference: feasible.go:661, propertyset.go)."""

    def __init__(self, ctx: EvalContext, source: FeasibleIterator):
        self.ctx = ctx
        self.source = source
        self.job = None
        self.tg = None
        self.job_property_sets: list = []
        self.tg_property_sets: list = []

    def set_job(self, job: Job) -> None:
        self.job = job
        self.job_property_sets = [
            c for c in job.constraints
            if c.operand == CONSTRAINT_DISTINCT_PROPERTY]

    def set_task_group(self, tg: TaskGroup) -> None:
        self.tg = tg
        self.tg_property_sets = [
            c for c in tg.constraints
            if c.operand == CONSTRAINT_DISTINCT_PROPERTY]

    def _count_limit(self, c: Constraint) -> int:
        try:
            return max(1, int(c.r_target)) if c.r_target else 1
        except ValueError:
            return 1

    def next(self) -> Optional[Node]:
        while True:
            node = self.source.next()
            if node is None:
                return None
            if not self.job_property_sets and not self.tg_property_sets:
                return node
            if self._satisfies(node):
                return node
            self.ctx.metrics.filter_node(
                node.computed_class, CONSTRAINT_DISTINCT_PROPERTY)

    def _satisfies(self, node: Node) -> bool:
        node_val_cache: Dict[str, tuple] = {}

        def node_value(target: str):
            if target not in node_val_cache:
                node_val_cache[target] = resolve_target(target, node)
            return node_val_cache[target]

        # Count allocs per property value among this job's allocs
        allocs = [a for a in self.ctx.state.allocs_by_job(
            self.job.namespace, self.job.id) if not a.terminal_status()]
        # include plan placements, exclude plan stops
        removed = set()
        for na in self.ctx.plan.node_update.values():
            removed.update(a.id for a in na)
        allocs = [a for a in allocs if a.id not in removed]
        for na in self.ctx.plan.node_allocation.values():
            allocs.extend(na)

        for scope, csets in (("job", self.job_property_sets),
                             ("tg", self.tg_property_sets)):
            for c in csets:
                val, ok = node_value(c.l_target)
                if not ok:
                    return False
                limit = self._count_limit(c)
                used = 0
                for alloc in allocs:
                    if scope == "tg" and alloc.task_group != self.tg.name:
                        continue
                    other = self.ctx.state.node_by_id(alloc.node_id)
                    if other is None:
                        continue
                    oval, ook = resolve_target(c.l_target, other)
                    if ook and str(oval) == str(val):
                        used += 1
                if used >= limit:
                    return False
        return True

    def reset(self) -> None:
        self.source.reset()
