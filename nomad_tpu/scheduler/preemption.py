"""Preemption search: which lower-priority allocs to evict for a placement.

Semantic parity with /root/reference/scheduler/preemption.go:
  Preemptor (:201 region), PreemptForTaskGroup (greedy pick by resource
  distance then superset filter), filterAndGroupPreemptibleAllocs (:666,
  only priority <= jobPriority-10 eligible), basicResourceDistance (:611),
  scoreForTaskGroup with maxParallelPenalty=50 (:16), filterSuperset (:705),
  PreemptForNetwork (:273) and PreemptForDevice (:475).

Network preemption is re-designed around ports (the reference scores by
deprecated MBits; our network model is port-bitmap based -- see
structs/network.py), keeping the same candidate filtering and net-priority
minimization contract.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..structs import (
    Allocation, ComparableResources, NetworkIndex, Node,
)
from .context import EvalContext

MAX_PARALLEL_PENALTY = 50.0


def basic_resource_distance(ask: ComparableResources,
                            used: ComparableResources) -> float:
    """Euclidean distance in normalized (cpu, mem, disk) space
    (reference: preemption.go:611)."""
    mem_c = cpu_c = disk_c = 0.0
    if ask.memory_mb > 0:
        mem_c = (float(ask.memory_mb) - float(used.memory_mb)) / float(ask.memory_mb)
    if ask.cpu_shares > 0:
        cpu_c = (float(ask.cpu_shares) - float(used.cpu_shares)) / float(ask.cpu_shares)
    if ask.disk_mb > 0:
        disk_c = (float(ask.disk_mb) - float(used.disk_mb)) / float(ask.disk_mb)
    return math.sqrt(mem_c ** 2 + cpu_c ** 2 + disk_c ** 2)


def score_for_task_group(ask: ComparableResources, used: ComparableResources,
                         max_parallel: int, num_preempted: int) -> float:
    """Distance + max_parallel penalty (reference: preemption.go:644)."""
    penalty = 0.0
    if max_parallel > 0 and num_preempted >= max_parallel:
        penalty = float((num_preempted + 1) - max_parallel) * MAX_PARALLEL_PENALTY
    return basic_resource_distance(ask, used) + penalty


def filter_and_group_preemptible(job_priority: int,
                                 current: List[Allocation]
                                 ) -> List[Tuple[int, List[Allocation]]]:
    """Group by priority ascending; only allocs at least 10 priority levels
    below are eligible (reference: preemption.go:666)."""
    by_priority: Dict[int, List[Allocation]] = {}
    for alloc in current:
        if alloc.job is None:
            continue
        if job_priority - alloc.job.priority < 10:
            continue
        by_priority.setdefault(alloc.job.priority, []).append(alloc)
    return sorted(by_priority.items(), key=lambda kv: kv[0])


class Preemptor:
    """(reference: preemption.go Preemptor)"""

    def __init__(self, job_priority: int, ctx: Optional[EvalContext],
                 job_ns_id: Tuple[str, str]):
        self.job_priority = job_priority
        self.ctx = ctx
        self.job_ns_id = job_ns_id
        self.current_allocs: List[Allocation] = []
        self.alloc_details: Dict[str, Tuple[int, ComparableResources]] = {}
        self.current_preemptions: Dict[Tuple[str, str, str], int] = {}
        self.node_remaining: Optional[ComparableResources] = None
        self.node: Optional[Node] = None

    def set_node(self, node: Node) -> None:
        self.node = node
        remaining = node.node_resources.comparable()
        remaining.subtract(node.reserved_resources.comparable())
        self.node_remaining = remaining

    def set_candidates(self, allocs: List[Allocation]) -> None:
        self.current_allocs = []
        self.alloc_details = {}
        for alloc in allocs:
            # Skip this job's own allocs and anything already terminal
            if (alloc.namespace, alloc.job_id) == self.job_ns_id:
                continue
            if alloc.terminal_status():
                continue
            max_parallel = 0
            if alloc.job is not None:
                tg = alloc.job.lookup_task_group(alloc.task_group)
                if tg is not None and tg.migrate is not None:
                    max_parallel = tg.migrate.max_parallel
            self.alloc_details[alloc.id] = (
                max_parallel, alloc.allocated_resources.comparable())
            self.current_allocs.append(alloc)

    def set_preemptions(self, allocs: List[Allocation]) -> None:
        self.current_preemptions = {}
        for alloc in allocs:
            key = (alloc.namespace, alloc.job_id, alloc.task_group)
            self.current_preemptions[key] = self.current_preemptions.get(key, 0) + 1

    def _num_preemptions(self, alloc: Allocation) -> int:
        return self.current_preemptions.get(
            (alloc.namespace, alloc.job_id, alloc.task_group), 0)

    # -- CPU/memory/disk path (reference: PreemptForTaskGroup) --------------
    def preempt_for_task_group(self, resource_ask) -> List[Allocation]:
        # comparable() results are cached on the ask and shared between
        # the three calls in this method; this one is mutated (subtract
        # below), so it must be a private copy
        resources_needed = resource_ask.comparable().copy()
        node_remaining = self.node_remaining.copy()
        for alloc in self.current_allocs:
            node_remaining.subtract(self.alloc_details[alloc.id][1])

        groups = filter_and_group_preemptible(self.job_priority,
                                              self.current_allocs)
        best: List[Allocation] = []
        all_met = False
        available = node_remaining.copy()
        resources_asked = resource_ask.comparable()

        for _prio, group in groups:
            group = list(group)
            while group and not all_met:
                best_dist = math.inf
                best_idx = -1
                for idx, alloc in enumerate(group):
                    max_parallel, used = self.alloc_details[alloc.id]
                    dist = score_for_task_group(
                        resources_needed, used, max_parallel,
                        self._num_preemptions(alloc))
                    if dist < best_dist:
                        best_dist = dist
                        best_idx = idx
                closest = group.pop(best_idx)
                closest_res = self.alloc_details[closest.id][1]
                available.add(closest_res)
                all_met, _ = available.superset(resources_asked)
                best.append(closest)
                resources_needed.subtract(closest_res)
            if all_met:
                break

        if not all_met:
            return []

        return self._filter_superset(best, node_remaining,
                                     resource_ask.comparable())

    def _filter_superset(self, best: List[Allocation],
                         node_remaining: ComparableResources,
                         ask: ComparableResources) -> List[Allocation]:
        """Drop allocs whose resources are already covered by the rest
        (reference: preemption.go:705 filterSuperset)."""
        best = sorted(
            best,
            key=lambda a: basic_resource_distance(
                ask, self.alloc_details[a.id][1]),
            reverse=True)
        available = node_remaining.copy()
        out: List[Allocation] = []
        met = False
        for alloc in best:
            if met:
                break
            available.add(self.alloc_details[alloc.id][1])
            out.append(alloc)
            met, _ = available.superset(ask)
        return out

    # -- network path (port-based re-design of PreemptForNetwork) -----------
    def preempt_for_network(self, ask, net_idx: NetworkIndex
                            ) -> Optional[List[Allocation]]:
        """Free ports by preempting the cheapest (lowest net-priority) set of
        eligible allocs whose released ports make the ask assignable."""
        if not self.current_allocs:
            return None
        wanted_static = {p.value for p in ask.reserved_ports}
        groups = filter_and_group_preemptible(self.job_priority,
                                              self.current_allocs)
        chosen: List[Allocation] = []
        for _prio, group in groups:
            for alloc in group:
                ports = {pm.value for pm in
                         alloc.allocated_resources.shared.ports}
                for net in alloc.allocated_resources.shared.networks:
                    ports.update(p.value for p in net.reserved_ports)
                    ports.update(p.value for p in net.dynamic_ports)
                if wanted_static & ports or (not wanted_static and ports):
                    chosen.append(alloc)
                    # Would the ask fit with these preempted?
                    if self._network_ask_fits_without(chosen, ask):
                        return chosen
        return None

    def _network_ask_fits_without(self, preempted: List[Allocation],
                                  ask) -> bool:
        idx = NetworkIndex()
        if self.node is not None:
            idx.set_node(self.node)
        removed = {a.id for a in preempted}
        idx.add_allocs([a for a in self.current_allocs
                        if a.id not in removed])
        offer, _ = idx.assign_ports([ask])
        return offer is not None

    # -- device path (reference: PreemptForDevice) --------------------------
    def preempt_for_device(self, req, dev_allocator
                           ) -> Optional[List[Allocation]]:
        """Free device instances by preempting holders; chooses the option
        with minimal net priority (reference: preemption.go:475-558)."""
        # Map device group -> allocs holding instances of it
        holders: Dict[str, List[Tuple[Allocation, int]]] = {}
        for alloc in self.current_allocs:
            if alloc.job is None:
                continue
            if self.job_priority - alloc.job.priority < 10:
                continue
            for tr in alloc.allocated_resources.tasks.values():
                for dev in tr.devices:
                    holders.setdefault(dev.id_string(), []).append(
                        (alloc, len(dev.device_ids)))

        best_option: Optional[List[Allocation]] = None
        best_net_priority = math.inf
        for group in self.node.node_resources.devices:
            if not group.matches_request(req.name):
                continue
            entries = holders.get(group.id_string(), [])
            if not entries:
                continue
            free = len(group.instance_ids) - sum(
                n for _, n in entries)
            needed = req.count - max(free, 0)
            if needed <= 0:
                continue
            # Sort holders by instance count descending, take until covered
            entries = sorted(entries, key=lambda e: -e[1])
            covered = 0
            option: List[Allocation] = []
            priorities = set()
            net_prio = 0
            for alloc, n in entries:
                if covered >= needed:
                    break
                covered += n
                option.append(alloc)
                p = alloc.job.priority
                if p not in priorities:
                    priorities.add(p)
                    net_prio += p
            if covered >= needed and net_prio < best_net_priority:
                best_net_priority = net_prio
                best_option = option
        return best_option
