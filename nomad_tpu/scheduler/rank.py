"""Rank iterators: the bin-packing hot loop and the scoring chain.

Semantic parity with /root/reference/scheduler/rank.go:
  RankedNode (:33), FeasibleRankIterator (:96), BinPackIterator (:156,
  Next :205 -- the whole outer loop: proposed allocs, network index, port
  assignment, device allocation, core reservation, AllocsFit, score),
  JobAntiAffinityIterator (:622), NodeReschedulingPenaltyIterator (:684),
  NodeAffinityIterator (:756), ScoreNormalizationIterator (:815),
  PreemptionScoringIterator (:851).
This host path is the parity oracle; nomad_tpu/solver/binpack.py computes
the same math vectorized on TPU.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

from ..structs import (
    AllocatedDeviceResource, AllocatedPortMapping, AllocatedResources,
    AllocatedSharedResources, AllocatedTaskResources, Allocation, Job,
    NetworkIndex, NetworkResource, Node, TaskGroup, allocs_fit,
    score_fit_binpack, score_fit_spread, BINPACK_MAX_FIT_SCORE,
    SchedulerConfiguration, SCHED_ALG_SPREAD, SCHED_ALG_TPU_SPREAD,
)
from .context import EvalContext
from .util import resolve_target

BINPACKING_MAX_FIT_SCORE = BINPACK_MAX_FIT_SCORE


class RankedNode:
    """A candidate node moving through the scoring chain
    (reference: rank.go:33)."""

    __slots__ = ("node", "final_score", "scores", "task_resources",
                 "alloc_resources", "preempted_allocs")

    def __init__(self, node: Node):
        self.node = node
        self.final_score = 0.0
        self.scores: List[float] = []
        self.task_resources: Dict[str, AllocatedTaskResources] = {}
        self.alloc_resources: Optional[AllocatedSharedResources] = None
        self.preempted_allocs: Optional[List[Allocation]] = None


class RankIterator:
    def next(self) -> Optional[RankedNode]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class FeasibleRankIterator(RankIterator):
    """Upgrades a feasibility iterator into the ranking chain
    (reference: rank.go:96)."""

    def __init__(self, ctx: EvalContext, source):
        self.ctx = ctx
        self.source = source

    def next(self) -> Optional[RankedNode]:
        node = self.source.next()
        if node is None:
            return None
        return RankedNode(node)

    def reset(self) -> None:
        self.source.reset()


class DeviceAllocator:
    """Fits device asks against node device groups, tracking instance usage
    (reference: scheduler/device.go)."""

    def __init__(self, ctx: EvalContext, node: Node):
        self.ctx = ctx
        self.node = node
        # id_string -> set of used instance ids
        self.used: Dict[str, set] = {}

    def add_allocs(self, allocs: List[Allocation]) -> None:
        for alloc in allocs:
            if alloc.client_terminal_status():
                continue
            for tr in alloc.allocated_resources.tasks.values():
                for dev in tr.devices:
                    self.used.setdefault(dev.id_string(), set()).update(
                        dev.device_ids)

    def add_reserved(self, offer: AllocatedDeviceResource) -> None:
        self.used.setdefault(offer.id_string(), set()).update(offer.device_ids)

    def assign_device(self, req):
        """Returns (offer, sum_matched_affinity_weights, err). Picks the
        feasible group with the highest affinity score
        (reference: device.go AssignDevice)."""
        best = None
        best_score = 0.0
        for group in self.node.node_resources.devices:
            if not group.matches_request(req.name):
                continue
            free = [i for i in group.instance_ids
                    if i not in self.used.get(group.id_string(), set())]
            if len(free) < req.count:
                continue
            if req.constraints:
                if not DeviceChecker._check_device_constraints(
                        _DeviceCheckerShim(self.ctx), group, req.constraints):
                    continue
            score = 0.0
            if req.affinities:
                for aff in req.affinities:
                    lval, l_ok = DeviceChecker._resolve_device_target(
                        aff.l_target, group)
                    rval, r_ok = DeviceChecker._resolve_device_target(
                        aff.r_target, group)
                    from .feasible import check_constraint
                    if check_constraint(self.ctx, aff.operand, lval, rval,
                                        l_ok, r_ok):
                        score += float(aff.weight)
            if best is None or score > best_score:
                best = (group, free)
                best_score = score
        if best is None:
            return None, 0.0, "no devices match request"
        group, free = best
        offer = AllocatedDeviceResource(
            vendor=group.vendor, type=group.type, name=group.name,
            device_ids=free[:req.count])
        return offer, best_score, ""


class _DeviceCheckerShim:
    """Adapter so DeviceAllocator can reuse DeviceChecker's static helpers."""

    def __init__(self, ctx):
        self.ctx = ctx


from .feasible import DeviceChecker  # noqa: E402  (cycle-free tail import)


def select_reserved_cores(node: Node, consumed, count: int):
    """Deterministic lowest-id selection of free reservable cores
    (reference: rank.go:481-524, simplified from NUMA-preferring to
    lowest-id). Excludes agent-reserved cores (the same availability rule
    allocs_fit enforces, structs/funcs.py) and anything in ``consumed``.
    Returns the core ids, or None when fewer than ``count`` are free.
    BOTH the host BinPackIterator and the dense path's materialize replay
    use this helper -- core-id parity depends on there being one copy."""
    usable = (set(node.node_resources.cpu.reservable_cores)
              - set(node.reserved_resources.cores) - set(consumed))
    if len(usable) < count:
        return None
    return sorted(usable)[:count]


class BinPackIterator(RankIterator):
    """The hot inner loop (reference: rank.go:156-598)."""

    def __init__(self, ctx: EvalContext, source: RankIterator,
                 evict: bool = False, priority: int = 0):
        self.ctx = ctx
        self.source = source
        self.evict = evict
        self.priority = priority
        self.job_ns_id = ("", "")
        self.task_group: Optional[TaskGroup] = None
        self.memory_oversubscription = False
        self.score_fit = score_fit_binpack

    def set_job(self, job: Job) -> None:
        self.priority = job.priority
        self.job_ns_id = (job.namespace, job.id)

    def set_task_group(self, tg: TaskGroup) -> None:
        self.task_group = tg

    def set_scheduler_configuration(self, cfg: SchedulerConfiguration) -> None:
        alg = cfg.scheduler_algorithm
        self.score_fit = (score_fit_spread
                          if alg in (SCHED_ALG_SPREAD, SCHED_ALG_TPU_SPREAD)
                          else score_fit_binpack)
        self.memory_oversubscription = cfg.memory_oversubscription_enabled

    def next(self) -> Optional[RankedNode]:
        while True:
            option = self.source.next()
            if option is None:
                return None

            proposed = self.ctx.proposed_allocs(option.node.id)

            # Index existing network usage; collisions here mean state is
            # corrupt -- emit an event (reference: rank.go:226 PortCollisionEvent)
            net_idx = NetworkIndex()
            err = net_idx.set_node(option.node)
            if err:
                self.ctx.send_event({"type": "port_collision", "reason": err,
                                     "node": option.node.id})
                self.ctx.metrics.exhausted_node(
                    option.node.id, option.node.computed_class,
                    "network: invalid node")
                continue
            collide, reason = net_idx.add_allocs(proposed)
            if collide:
                self.ctx.send_event({"type": "port_collision",
                                     "reason": reason, "node": option.node.id})
                self.ctx.metrics.exhausted_node(
                    option.node.id, option.node.computed_class,
                    "network: port collision")
                continue

            dev_allocator = DeviceAllocator(self.ctx, option.node)
            dev_allocator.add_allocs(proposed)
            total_device_affinity_weight = 0.0
            sum_matching_affinities = 0.0

            total = AllocatedResources(
                tasks={},
                shared=AllocatedSharedResources(
                    disk_mb=self.task_group.ephemeral_disk.size_mb))

            allocs_to_preempt: List[Allocation] = []

            # Task-group-level network ask (reference: rank.go:283-365)
            if self.task_group.networks:
                ask = self.task_group.networks[0].copy()
                bad_template = False
                for p in ask.dynamic_ports + ask.reserved_ports:
                    if p.host_network and p.host_network.startswith("${"):
                        val, ok = resolve_target(p.host_network, option.node)
                        if not ok:
                            bad_template = True
                            break
                        p.host_network = val
                if bad_template:
                    continue
                offer, aerr = net_idx.assign_ports([ask])
                if offer is None:
                    if not self.evict:
                        self.ctx.metrics.exhausted_node(
                            option.node.id, option.node.computed_class,
                            f"network: {aerr}")
                        continue
                    # preemption for network handled via PreemptForNetwork
                    from .preemption import Preemptor
                    preemptor = Preemptor(self.priority, self.ctx,
                                          self.job_ns_id)
                    preemptor.set_node(option.node)
                    preemptor.set_preemptions(self._current_preemptions())
                    preemptor.set_candidates(proposed)
                    net_preempts = preemptor.preempt_for_network(ask, net_idx)
                    if not net_preempts:
                        self.ctx.metrics.exhausted_node(
                            option.node.id, option.node.computed_class,
                            f"network: {aerr}")
                        continue
                    allocs_to_preempt.extend(net_preempts)
                    removed = {a.id for a in net_preempts}
                    proposed = [a for a in proposed if a.id not in removed]
                    net_idx = NetworkIndex()
                    net_idx.set_node(option.node)
                    net_idx.add_allocs(proposed)
                    offer, aerr = net_idx.assign_ports([ask])
                    if offer is None:
                        self.ctx.metrics.exhausted_node(
                            option.node.id, option.node.computed_class,
                            f"network: {aerr}")
                        continue
                # Commit the offer into the index so later asks in this eval
                # can't collide; route each port to its host network's bitmap
                # (reference: rank.go:352 netIdx.AddReservedPorts(offer)).
                for pm in offer.ports:
                    net_idx.add_reserved_port(
                        pm.value, net_idx._network_for_ip(pm.host_ip))
                nw_res = NetworkResource(
                    mode=ask.mode, device="",
                    reserved_ports=[], dynamic_ports=[])
                total.shared.networks = [nw_res]
                total.shared.ports = offer.ports
                option.alloc_resources = AllocatedSharedResources(
                    networks=[nw_res],
                    disk_mb=self.task_group.ephemeral_disk.size_mb,
                    ports=offer.ports)

            exhausted = False
            for task in self.task_group.tasks:
                task_res = AllocatedTaskResources(
                    cpu_shares=task.resources.cpu,
                    memory_mb=task.resources.memory_mb)
                if self.memory_oversubscription:
                    task_res.memory_max_mb = task.resources.memory_max_mb

                # Device asks
                for req in task.resources.devices:
                    offer, sum_aff, derr = dev_allocator.assign_device(req)
                    if offer is None:
                        if not self.evict:
                            self.ctx.metrics.exhausted_node(
                                option.node.id, option.node.computed_class,
                                f"devices: {derr}")
                            exhausted = True
                            break
                        from .preemption import Preemptor
                        preemptor = Preemptor(self.priority, self.ctx,
                                              self.job_ns_id)
                        preemptor.set_node(option.node)
                        preemptor.set_preemptions(self._current_preemptions())
                        preemptor.set_candidates(proposed)
                        dev_preempts = preemptor.preempt_for_device(
                            req, dev_allocator)
                        if not dev_preempts:
                            exhausted = True
                            break
                        allocs_to_preempt.extend(dev_preempts)
                        removed = {a.id for a in allocs_to_preempt}
                        proposed = [a for a in proposed if a.id not in removed]
                        dev_allocator = DeviceAllocator(self.ctx, option.node)
                        dev_allocator.add_allocs(proposed)
                        offer, sum_aff, derr = dev_allocator.assign_device(req)
                        if offer is None:
                            exhausted = True
                            break
                    dev_allocator.add_reserved(offer)
                    task_res.devices.append(offer)
                    if req.affinities:
                        for a in req.affinities:
                            total_device_affinity_weight += abs(float(a.weight))
                        sum_matching_affinities += sum_aff
                if exhausted:
                    break

                # Reserved cores (reference: rank.go:481-524; NUMA-aware
                # selection simplified to lowest-id free cores)
                if task.resources.cores > 0:
                    consumed = set()
                    for alloc in proposed:
                        consumed.update(
                            alloc.allocated_resources.comparable().reserved_cores)
                    for tr in total.tasks.values():
                        consumed.update(tr.reserved_cores)
                    cores = select_reserved_cores(
                        option.node, consumed, task.resources.cores)
                    if cores is None:
                        self.ctx.metrics.exhausted_node(
                            option.node.id, option.node.computed_class, "cores")
                        exhausted = True
                        break
                    task_res.reserved_cores = cores
                    total_cores = option.node.node_resources.cpu.total_core_count
                    if total_cores:
                        mhz_per_core = (option.node.node_resources.cpu.cpu_shares
                                        // total_cores)
                        task_res.cpu_shares = mhz_per_core * len(cores)

                option.task_resources[task.name] = task_res
                total.tasks[task.name] = task_res
            if exhausted:
                continue

            current = proposed
            ghost = Allocation(allocated_resources=total)
            proposed = proposed + [ghost]

            fit, dim, util = allocs_fit(option.node, proposed, net_idx,
                                        check_devices=False)
            if not fit:
                if not self.evict:
                    self.ctx.metrics.exhausted_node(
                        option.node.id, option.node.computed_class, dim)
                    continue
                from .preemption import Preemptor
                preemptor = Preemptor(self.priority, self.ctx, self.job_ns_id)
                preemptor.set_node(option.node)
                preemptor.set_preemptions(self._current_preemptions())
                preemptor.set_candidates(current)
                preempted = preemptor.preempt_for_task_group(total)
                allocs_to_preempt.extend(preempted)
                if not preempted:
                    self.ctx.metrics.exhausted_node(
                        option.node.id, option.node.computed_class, dim)
                    continue
                # util after preemption: recompute from remaining + ghost
                removed = {a.id for a in allocs_to_preempt}
                remaining = [a for a in current if a.id not in removed] + [ghost]
                fit2, _, util = allocs_fit(option.node, remaining, None,
                                           check_devices=False)
                if not fit2:
                    self.ctx.metrics.exhausted_node(
                        option.node.id, option.node.computed_class, dim)
                    continue
            if allocs_to_preempt:
                option.preempted_allocs = allocs_to_preempt

            fitness = self.score_fit(option.node, util)
            normalized = fitness / BINPACKING_MAX_FIT_SCORE
            option.scores.append(normalized)
            self.ctx.metrics.score_node(option.node.id, "binpack", normalized)

            if total_device_affinity_weight != 0.0:
                sum_matching_affinities /= total_device_affinity_weight
                option.scores.append(sum_matching_affinities)
                self.ctx.metrics.score_node(
                    option.node.id, "devices", sum_matching_affinities)
            return option

    def _current_preemptions(self) -> List[Allocation]:
        out: List[Allocation] = []
        for allocs in self.ctx.plan.node_preemptions.values():
            out.extend(allocs)
        return out

    def reset(self) -> None:
        self.source.reset()


class JobAntiAffinityIterator(RankIterator):
    """Penalty −(collisions+1)/desired_count for co-placement with this
    job's allocs (reference: rank.go:622)."""

    def __init__(self, ctx: EvalContext, source: RankIterator, job_id: str):
        self.ctx = ctx
        self.source = source
        self.job_id = job_id
        self.task_group = ""
        self.desired_count = 0

    def set_job(self, job: Job) -> None:
        self.job_id = job.id

    def set_task_group(self, tg: TaskGroup) -> None:
        self.task_group = tg.name
        self.desired_count = tg.count

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        proposed = self.ctx.proposed_allocs(option.node.id)
        collisions = sum(1 for a in proposed
                         if a.job_id == self.job_id
                         and a.task_group == self.task_group)
        if collisions > 0 and self.desired_count > 0:
            penalty = -1.0 * float(collisions + 1) / float(self.desired_count)
            option.scores.append(penalty)
            self.ctx.metrics.score_node(
                option.node.id, "job-anti-affinity", penalty)
        else:
            self.ctx.metrics.score_node(option.node.id, "job-anti-affinity", 0)
        return option

    def reset(self) -> None:
        self.source.reset()


class NodeReschedulingPenaltyIterator(RankIterator):
    """−1 for nodes where the previous attempt failed (reference: rank.go:684)."""

    def __init__(self, ctx: EvalContext, source: RankIterator):
        self.ctx = ctx
        self.source = source
        self.penalty_nodes: set = set()

    def set_penalty_nodes(self, penalty_nodes) -> None:
        self.penalty_nodes = set(penalty_nodes or ())

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        if option.node.id in self.penalty_nodes:
            option.scores.append(-1.0)
            self.ctx.metrics.score_node(
                option.node.id, "node-reschedule-penalty", -1)
        else:
            self.ctx.metrics.score_node(
                option.node.id, "node-reschedule-penalty", 0)
        return option

    def reset(self) -> None:
        self.penalty_nodes = set()
        self.source.reset()


class NodeAffinityIterator(RankIterator):
    """Σ matched weights / Σ |weights| (reference: rank.go:756)."""

    def __init__(self, ctx: EvalContext, source: RankIterator):
        self.ctx = ctx
        self.source = source
        self.job_affinities: list = []
        self.affinities: list = []

    def set_job(self, job: Job) -> None:
        self.job_affinities = list(job.affinities)

    def set_task_group(self, tg: TaskGroup) -> None:
        self.affinities = list(self.job_affinities)
        self.affinities.extend(tg.affinities)
        for task in tg.tasks:
            self.affinities.extend(task.affinities)

    def has_affinities(self) -> bool:
        return bool(self.affinities)

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        if not self.has_affinities():
            self.ctx.metrics.score_node(option.node.id, "node-affinity", 0)
            return option
        from .feasible import check_constraint
        sum_weight = sum(abs(float(a.weight)) for a in self.affinities)
        total = 0.0
        for aff in self.affinities:
            lval, l_ok = resolve_target(aff.l_target, option.node)
            rval, r_ok = resolve_target(aff.r_target, option.node)
            if check_constraint(self.ctx, aff.operand, lval, rval, l_ok, r_ok):
                total += float(aff.weight)
        if total != 0.0:
            norm = total / sum_weight
            option.scores.append(norm)
            self.ctx.metrics.score_node(option.node.id, "node-affinity", norm)
        return option

    def reset(self) -> None:
        self.source.reset()
        self.affinities = []


class ScoreNormalizationIterator(RankIterator):
    """final = mean(scores) (reference: rank.go:815)."""

    def __init__(self, ctx: EvalContext, source: RankIterator):
        self.ctx = ctx
        self.source = source

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None or not option.scores:
            return option
        option.final_score = sum(option.scores) / len(option.scores)
        self.ctx.metrics.score_node(
            option.node.id, "normalized-score", option.final_score)
        return option

    def reset(self) -> None:
        self.source.reset()


def net_priority(allocs: List[Allocation]) -> float:
    """max priority + sum/max penalty (reference: rank.go netPriority)."""
    sum_priority = 0
    mx = 0.0
    for alloc in allocs:
        p = alloc.job.priority if alloc.job is not None else 50
        if float(p) > mx:
            mx = float(p)
        sum_priority += p
    if mx == 0.0:
        return 0.0
    return mx + (float(sum_priority) / mx)


def preemption_score(net_prio: float) -> float:
    """Logistic decay, inflection at 2048 (reference: rank.go preemptionScore)."""
    rate = 0.0048
    origin = 2048.0
    return 1.0 / (1.0 + math.exp(rate * (net_prio - origin)))


class PreemptionScoringIterator(RankIterator):
    """(reference: rank.go:851)"""

    def __init__(self, ctx: EvalContext, source: RankIterator):
        self.ctx = ctx
        self.source = source

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None or not option.preempted_allocs:
            return option
        score = preemption_score(net_priority(option.preempted_allocs))
        option.scores.append(score)
        self.ctx.metrics.score_node(option.node.id, "preemption", score)
        return option

    def reset(self) -> None:
        self.source.reset()
