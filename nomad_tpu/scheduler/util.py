"""Scheduler utilities (reference: /root/reference/scheduler/util.go).

The deterministic node shuffle is a re-design of the reference's
Go-rand-seeded Fisher-Yates (util.go:167 shuffleNodes): we keep the same
seeding contract (last 8 bytes of the eval ID XOR the refresh index, so
retried plans reshuffle) but use splitmix64 as the PRNG so the host oracle,
the TPU solver, and any future C++ runtime can reproduce the order exactly
from the same integer seed.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..structs import (
    Allocation, Job, Node, Plan, NODE_STATUS_DOWN, NODE_STATUS_DISCONNECTED,
)

MASK64 = (1 << 64) - 1


def splitmix64(state: int) -> Tuple[int, int]:
    """One step of splitmix64; returns (new_state, output)."""
    state = (state + 0x9E3779B97F4A7C15) & MASK64
    z = state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & MASK64
    z = z ^ (z >> 31)
    return state, z


def shuffle_seed(eval_id: str, index: int) -> int:
    """Derive the shuffle seed from eval ID + refresh index
    (reference contract: util.go:167-177)."""
    raw = eval_id.encode()[-8:].rjust(8, b"\0")
    seed = int.from_bytes(raw, "big") ^ (index & MASK64)
    return seed & MASK64


def shuffle_nodes(plan: Plan, index: int, nodes: List[Node]) -> None:
    """In-place deterministic Fisher-Yates (reference: util.go shuffleNodes)."""
    state = shuffle_seed(plan.eval_id, index)
    n = len(nodes)
    for i in range(n - 1, 0, -1):
        state, out = splitmix64(state)
        j = out % (i + 1)
        nodes[i], nodes[j] = nodes[j], nodes[i]


def shuffled_order(eval_id: str, index: int, n: int) -> List[int]:
    """The permutation shuffle_nodes applies, as index positions -- used by
    the TPU solver to reproduce the host shuffle on dense arrays."""
    order = list(range(n))
    state = shuffle_seed(eval_id, index)
    for i in range(n - 1, 0, -1):
        state, out = splitmix64(state)
        j = out % (i + 1)
        order[i], order[j] = order[j], order[i]
    return order


def tainted_nodes(state, allocs: List[Allocation]) -> Dict[str, Optional[Node]]:
    """Map of node id -> node for nodes that are down/draining/disconnected
    or deregistered (None) among the allocs' nodes
    (reference: util.go:130 taintedNodes)."""
    out: Dict[str, Optional[Node]] = {}
    for alloc in allocs:
        if alloc.node_id in out:
            continue
        node = state.node_by_id(alloc.node_id)
        if node is None:
            out[alloc.node_id] = None
            continue
        if node.status == NODE_STATUS_DOWN or node.drain:
            out[alloc.node_id] = node
        elif node.status == NODE_STATUS_DISCONNECTED:
            out[alloc.node_id] = node
    return out


def retry_max(max_attempts: int, cb, reset_cb=None):
    """Retry cb up to max_attempts, resetting the count when reset_cb says
    progress was made (reference: util.go:94 retryMax)."""
    attempts = 0
    while attempts < max_attempts:
        done, err = cb()
        if done:
            return None
        if reset_cb is not None and reset_cb():
            attempts = 0
        else:
            attempts += 1
    from .generic import SetStatusError  # local import to avoid cycle
    return SetStatusError(f"maximum attempts reached ({max_attempts})")


def progress_made(result) -> bool:
    """Did the plan application commit anything? (reference: util.go:120)"""
    return result is not None and (
        result.node_update or result.node_allocation
        or result.deployment is not None or result.deployment_updates)


def alloc_name(job_id: str, tg_name: str, idx: int) -> str:
    return f"{job_id}.{tg_name}[{idx}]"


def resolve_target(target: str, node: Node):
    """Resolve an interpolation target like ${attr.kernel.name} against a
    node (reference: feasible.go resolveTarget). Returns (value, found)."""
    if not target.startswith("${"):
        # raw values are returned as-is (constraint RTarget side)
        return target, True
    inner = target[2:-1] if target.endswith("}") else target[2:]
    if inner == "node.unique.id":
        return node.id, True
    if inner == "node.datacenter":
        return node.datacenter, True
    if inner == "node.unique.name":
        return node.name, True
    if inner == "node.class":
        return node.node_class, True
    if inner == "node.pool":
        return node.node_pool, True
    if inner.startswith("attr."):
        key = inner[len("attr."):]
        if key in node.attributes:
            return node.attributes[key], True
        return "", False
    if inner.startswith("meta."):
        key = inner[len("meta."):]
        if key in node.meta:
            return node.meta[key], True
        return "", False
    return "", False
