"""Allocation reconciler: pure diff of desired vs actual state.

Semantic parity with /root/reference/scheduler/reconcile.go
(NewAllocReconciler :201, Compute :239, computeGroup :434,
computePlacements :798, computeStop :1029) and reconcile_util.go
(allocSet filtering, allocNameIndex). Canary/promotion flow and
disconnect/reconnect grace handling follow the same structure; the
disconnect paths are handled by marking allocs lost/unknown per
max_client_disconnect (reference: reconcile.go:1157,1301).
"""
from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..structs import (
    Allocation, AllocDeploymentStatus, Deployment, DeploymentState,
    DeploymentStatusUpdate, DesiredTransition, Evaluation, Job, Node,
    RescheduleEvent, RescheduleTracker, TaskGroup, generate_uuid,
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST,
    ALLOC_CLIENT_PENDING, ALLOC_CLIENT_RUNNING, ALLOC_CLIENT_UNKNOWN,
    ALLOC_DESIRED_STOP,
    DEPLOYMENT_STATUS_CANCELLED, DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL, EVAL_STATUS_PENDING,
    JOB_TYPE_BATCH, JOB_TYPE_SERVICE,
    NODE_STATUS_DISCONNECTED, NODE_STATUS_DOWN,
    TRIGGER_FAILED_FOLLOW_UP, TRIGGER_MAX_DISCONNECT_TIMEOUT,
)

# Descriptions used on stopped allocs (reference: reconcile.go consts)
ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_RESCHEDULED = "alloc was rescheduled because it failed"
ALLOC_LOST = "alloc is lost since its node is down"
ALLOC_UNKNOWN = "alloc is unknown since its node is disconnected"
ALLOC_MIGRATING = "alloc is being migrated"


@dataclass
class AllocPlaceResult:
    """One placement ask (reference: reconcile.go allocPlaceResult)."""

    name: str = ""
    canary: bool = False
    task_group: Optional[TaskGroup] = None
    previous_alloc: Optional[Allocation] = None
    reschedule: bool = False
    previous_lost: bool = False
    downgrade_non_canary: bool = False
    min_job_version: int = 0


@dataclass
class AllocStopResult:
    alloc: Allocation = None
    client_status: str = ""
    status_description: str = ""
    followup_eval_id: str = ""


@dataclass
class AllocDestructiveResult:
    place_name: str = ""
    place_task_group: Optional[TaskGroup] = None
    stop_alloc: Allocation = None
    stop_status_description: str = ""


@dataclass
class DesiredUpdates:
    """Per-TG summary for eval annotations (reference: structs.DesiredUpdates)."""

    ignore: int = 0
    place: int = 0
    migrate: int = 0
    stop: int = 0
    in_place_update: int = 0
    destructive_update: int = 0
    canary: int = 0
    preemptions: int = 0
    reschedule_now: int = 0
    reschedule_later: int = 0
    disconnect_updates: int = 0
    reconnect_updates: int = 0


@dataclass
class ReconcileResults:
    """(reference: reconcile.go reconcileResults)"""

    place: List[AllocPlaceResult] = field(default_factory=list)
    destructive_update: List[AllocDestructiveResult] = field(default_factory=list)
    inplace_update: List[Allocation] = field(default_factory=list)
    stop: List[AllocStopResult] = field(default_factory=list)
    disconnect_updates: Dict[str, Allocation] = field(default_factory=dict)
    reconnect_updates: Dict[str, Allocation] = field(default_factory=dict)
    desired_tg_updates: Dict[str, DesiredUpdates] = field(default_factory=dict)
    desired_followup_evals: Dict[str, List[Evaluation]] = field(default_factory=dict)
    deployment: Optional[Deployment] = None
    deployment_updates: List[DeploymentStatusUpdate] = field(default_factory=list)


def tasks_updated(job_a: Job, job_b: Job, tg_name: str) -> bool:
    """Would moving from job_a to job_b require a destructive update?
    (reference: util.go:217 tasksUpdated)"""
    a = job_a.lookup_task_group(tg_name)
    b = job_b.lookup_task_group(tg_name)
    if a is None or b is None:
        return True
    if len(a.tasks) != len(b.tasks):
        return True
    if (a.ephemeral_disk.size_mb != b.ephemeral_disk.size_mb
            or a.ephemeral_disk.sticky != b.ephemeral_disk.sticky
            or a.ephemeral_disk.migrate != b.ephemeral_disk.migrate):
        return True
    if _networks_updated(a.networks, b.networks):
        return True
    if {k: (v.source, v.read_only, v.type) for k, v in a.volumes.items()} != \
       {k: (v.source, v.read_only, v.type) for k, v in b.volumes.items()}:
        return True
    for ta in a.tasks:
        tb = b.lookup_task(ta.name)
        if tb is None:
            return True
        if (ta.driver != tb.driver or ta.user != tb.user
                or ta.config != tb.config or ta.env != tb.env
                or ta.artifacts != tb.artifacts
                or ta.templates != tb.templates
                or ta.vault != tb.vault or ta.meta != tb.meta
                or ta.kind != tb.kind or ta.leader != tb.leader):
            return True
        ra, rb = ta.resources, tb.resources
        if (ra.cpu != rb.cpu or ra.memory_mb != rb.memory_mb
                or ra.memory_max_mb != rb.memory_max_mb
                or ra.cores != rb.cores
                or _networks_updated(ra.networks, rb.networks)
                or [(d.name, d.count) for d in ra.devices]
                != [(d.name, d.count) for d in rb.devices]):
            return True
    return False


def _networks_updated(na, nb) -> bool:
    if len(na) != len(nb):
        return True
    for x, y in zip(na, nb):
        if x.mode != y.mode:
            return True
        if ([(p.label, p.value, p.to, p.host_network) for p in x.reserved_ports]
                != [(p.label, p.value, p.to, p.host_network) for p in y.reserved_ports]):
            return True
        if ([(p.label, p.to, p.host_network) for p in x.dynamic_ports]
                != [(p.label, p.to, p.host_network) for p in y.dynamic_ports]):
            return True
    return False


class AllocNameIndex:
    """Tracks which alloc name indexes [0, count) are in use so replacements
    reuse names (reference: reconcile_util.go allocNameIndex)."""

    def __init__(self, job_id: str, tg_name: str, count: int,
                 in_use: List[Allocation]):
        self.job_id = job_id
        self.tg_name = tg_name
        self.count = count
        self.b: Set[int] = set()
        self.duplicates: List[int] = []
        seen: Set[int] = set()
        for a in in_use:
            idx = a.index()
            if idx < 0:
                continue
            if idx in seen:
                self.duplicates.append(idx)
            seen.add(idx)
            self.b.add(idx)

    def has(self, idx: int) -> bool:
        return idx in self.b

    def unset_highest(self, n: int) -> Set[int]:
        """Return the n highest indexes in use (candidates for stopping)."""
        out = set(sorted(self.b, reverse=True)[:n])
        return out

    def next_n(self, n: int) -> List[str]:
        """The next n unused names (reference: allocNameIndex.Next)."""
        out = []
        idx = 0
        picked = 0
        while picked < n:
            if idx not in self.b:
                out.append(f"{self.job_id}.{self.tg_name}[{idx}]")
                self.b.add(idx)
                picked += 1
            idx += 1
        return out


def _filter_by_terminal(allocs: List[Allocation]) -> List[Allocation]:
    return [a for a in allocs if not a.server_terminal_status()]


def reschedule_eligible(policy, alloc: Allocation, now: float,
                        is_batch: bool) -> Tuple[bool, float]:
    """Can this failed alloc be rescheduled, and if so when?
    Returns (eligible, wait_until_unix; 0 for now)
    (reference: structs.go Allocation.NextRescheduleTime +
    reconcile_util.go updateByReschedulable)."""
    if policy is None:
        return False, 0.0
    if alloc.desired_transition.should_force_reschedule():
        return True, 0.0
    attempts = 0
    last_reschedule = 0.0
    if alloc.reschedule_tracker is not None:
        events = alloc.reschedule_tracker.events
        if policy.unlimited:
            attempts = len(events)
        else:
            window_start = now - policy.interval_s
            attempts = sum(1 for e in events
                           if e.reschedule_time >= window_start)
        if events:
            last_reschedule = events[-1].reschedule_time
    if not policy.unlimited and attempts >= policy.attempts:
        return False, 0.0
    delay = _reschedule_delay(policy, attempts)
    # Batch jobs compute delay from failure time; we approximate with now
    wait_until = (alloc.client_terminal_time or now) + delay
    if wait_until <= now:
        return True, 0.0
    return True, wait_until


def _reschedule_delay(policy, attempts: int) -> float:
    base = policy.delay_s
    if attempts == 0:
        return base
    if policy.delay_function == "constant":
        return base
    if policy.delay_function == "exponential":
        d = base * (2 ** attempts)
    elif policy.delay_function == "fibonacci":
        a, b = base, base
        for _ in range(attempts):
            a, b = b, a + b
        d = a
    else:
        d = base
    return min(d, policy.max_delay_s or d)


class AllocReconciler:
    """(reference: reconcile.go:201)"""

    def __init__(self, batch: bool, job_id: str, job: Optional[Job],
                 deployment: Optional[Deployment],
                 existing_allocs: List[Allocation],
                 tainted_nodes: Dict[str, Optional[Node]],
                 eval_id: str, eval_priority: int,
                 supports_disconnected_clients: bool = True,
                 now: Optional[float] = None):
        self.batch = batch
        self.job_id = job_id
        self.job = job
        self.deployment = deployment
        self.existing = existing_allocs
        self.tainted = tainted_nodes
        self.eval_id = eval_id
        self.eval_priority = eval_priority
        self.supports_disconnected = supports_disconnected_clients
        self.now = now if now is not None else _time.time()
        self.job_stopped = job is None or job.stopped()
        self.deployment_paused = False
        self.deployment_failed = False
        if deployment is not None:
            self.deployment_paused = deployment.status == "paused"
            self.deployment_failed = deployment.status == "failed"
        self.result = ReconcileResults()

    # ------------------------------------------------------------------
    def compute(self) -> ReconcileResults:
        """(reference: reconcile.go:239 Compute)"""
        by_tg: Dict[str, List[Allocation]] = {}
        for a in self.existing:
            by_tg.setdefault(a.task_group, []).append(a)

        if self.job_stopped:
            self._handle_stop_all()
            return self.result

        # cancel deployments for older job versions
        self._cancel_unneeded_deployments()

        deployment_complete = True
        for tg in self.job.task_groups:
            allocs = by_tg.pop(tg.name, [])
            complete = self._compute_group(tg, allocs)
            deployment_complete = deployment_complete and complete

        # allocs for TGs that no longer exist -> stop
        for tg_name, allocs in by_tg.items():
            du = self.result.desired_tg_updates.setdefault(
                tg_name, DesiredUpdates())
            for a in _filter_by_terminal(allocs):
                self.result.stop.append(AllocStopResult(
                    alloc=a, status_description=ALLOC_NOT_NEEDED))
                du.stop += 1

        self._finalize_deployment(deployment_complete)
        return self.result

    # ------------------------------------------------------------------
    def _handle_stop_all(self) -> None:
        for a in _filter_by_terminal(self.existing):
            du = self.result.desired_tg_updates.setdefault(
                a.task_group, DesiredUpdates())
            if a.client_terminal_status():
                continue
            self.result.stop.append(AllocStopResult(
                alloc=a, status_description="alloc not needed as job is stopped"))
            du.stop += 1
        if self.deployment is not None and self.deployment.active():
            self.result.deployment_updates.append(DeploymentStatusUpdate(
                deployment_id=self.deployment.id,
                status=DEPLOYMENT_STATUS_CANCELLED,
                status_description="Cancelled because job is stopped"))

    def _cancel_unneeded_deployments(self) -> None:
        d = self.deployment
        if d is None:
            return
        if d.job_version < self.job.version and d.active():
            self.result.deployment_updates.append(DeploymentStatusUpdate(
                deployment_id=d.id,
                status=DEPLOYMENT_STATUS_CANCELLED,
                status_description="Cancelled due to newer version of job"))
            self.deployment = None
        elif not d.active():
            self.deployment = None

    # ------------------------------------------------------------------
    def _compute_group(self, tg: TaskGroup, all_allocs: List[Allocation]) -> bool:
        du = self.result.desired_tg_updates.setdefault(tg.name, DesiredUpdates())
        allocs = _filter_by_terminal(all_allocs)

        # Partition by node state (reference: reconcile_util.go filterByTainted)
        untainted: List[Allocation] = []
        migrate: List[Allocation] = []
        lost: List[Allocation] = []
        disconnecting: List[Allocation] = []
        reconnecting: List[Allocation] = []
        for a in allocs:
            node = self.tainted.get(a.node_id)
            if a.node_id in self.tainted:
                if node is None or node.status == NODE_STATUS_DOWN:
                    # Down or deregistered: running allocs are lost (the
                    # disconnect grace path requires NODE_STATUS_DISCONNECTED,
                    # handled in the next branch).
                    if a.client_status in (ALLOC_CLIENT_RUNNING,
                                           ALLOC_CLIENT_PENDING):
                        lost.append(a)
                    else:
                        untainted.append(a)
                elif node is not None and node.status == NODE_STATUS_DISCONNECTED:
                    if a.client_status in (ALLOC_CLIENT_RUNNING,
                                           ALLOC_CLIENT_PENDING):
                        if (tg.max_client_disconnect_s is not None
                                and self.supports_disconnected):
                            disconnecting.append(a)
                        else:
                            lost.append(a)
                    else:
                        untainted.append(a)
                elif node is not None and node.drain:
                    if a.client_status == ALLOC_CLIENT_UNKNOWN:
                        untainted.append(a)
                    elif a.desired_transition.should_migrate():
                        migrate.append(a)
                    else:
                        untainted.append(a)
                else:
                    untainted.append(a)
            else:
                if (a.client_status == ALLOC_CLIENT_UNKNOWN
                        and a.node_id not in self.tainted):
                    # node is back -> reconnect path
                    reconnecting.append(a)
                elif a.desired_transition.should_migrate():
                    # operator-requested move on a HEALTHY node
                    # (reference: alloc stop -> DesiredTransition.Migrate;
                    # filterByTainted migrates these regardless of taint)
                    migrate.append(a)
                else:
                    untainted.append(a)

        # Failed allocs eligible for reschedule (reference:
        # reconcile_util.go filterByRescheduleable)
        reschedule_now: List[Allocation] = []
        reschedule_later: List[Tuple[Allocation, float]] = []
        still_untainted: List[Allocation] = []
        batch_complete: List[Allocation] = []
        for a in untainted:
            if self.batch:
                failed = a.client_status == ALLOC_CLIENT_FAILED
                succeeded = a.client_status == ALLOC_CLIENT_COMPLETE
                if succeeded:
                    # Completed batch allocs keep their name slot; they are
                    # never replaced (reference: reconcile_util.go
                    # filterByRescheduleable batch handling).
                    du.ignore += 1
                    batch_complete.append(a)
                    continue
                if not failed:
                    still_untainted.append(a)
                    continue
            else:
                if a.client_status != ALLOC_CLIENT_FAILED:
                    still_untainted.append(a)
                    continue
            policy = tg.reschedule_policy
            ok, wait_until = reschedule_eligible(policy, a, self.now, self.batch)
            if ok and wait_until == 0.0:
                reschedule_now.append(a)
            elif ok:
                reschedule_later.append((a, wait_until))
                still_untainted.append(a)
            else:
                # Failed and not rescheduleable: the alloc keeps its name
                # slot so NO replacement is placed (reference:
                # reconcile_util.go:429-431 keeps it in untainted).
                du.ignore += 1
                still_untainted.append(a)
        untainted = still_untainted

        # Disconnecting allocs -> mark unknown + followup eval at deadline
        if disconnecting:
            timeout_evals = self._create_timeout_evals(tg, disconnecting)
            for a, ev in timeout_evals:
                updated = a.copy_skip_job()
                updated.client_status = ALLOC_CLIENT_UNKNOWN
                updated.client_description = ALLOC_UNKNOWN
                updated.followup_eval_id = ev.id
                self.result.disconnect_updates[updated.id] = updated
                du.disconnect_updates += 1
            untainted.extend(disconnecting)

        # Reconnecting allocs -> pick up again, stop duplicates
        if reconnecting:
            for a in reconnecting:
                updated = a.copy_skip_job()
                updated.client_status = ALLOC_CLIENT_RUNNING
                self.result.reconnect_updates[updated.id] = updated
                du.reconnect_updates += 1
            untainted.extend(reconnecting)

        # Canary separation (reference: reconcile.go cancelUnneededCanaries
        # runs BEFORE the shrink): while the deployment is unpromoted,
        # canary allocs live OUTSIDE the count -- they must not trigger
        # the excess-shrink of old-version allocs, and the canary gate
        # below owns their placement/replacement entirely.
        update = tg.update or (self.job.update if self.job else None)
        canaries_desired = (update.canary
                            if update is not None and not update.is_empty()
                            else 0)
        dep_state = (self.deployment.task_groups.get(tg.name)
                     if self.deployment is not None else None)
        promoted = bool(dep_state.promoted) if dep_state is not None \
            else False
        canary_live: List[Allocation] = []
        canary_lost: List[Allocation] = []
        if canaries_desired and not promoted and self.deployment is not None:
            def is_canary(a):
                return (a.deployment_status is not None
                        and a.deployment_status.canary
                        and a.deployment_id == self.deployment.id
                        and a.job_version == self.job.version)

            keep = []
            for a in untainted:
                (canary_live if is_canary(a) else keep).append(a)
            untainted = keep
            keep = []
            for a in migrate:
                # a migrating canary is replaced via the gate, not the
                # generic migrate path (which would drop the flag)
                (canary_lost if is_canary(a) else keep).append(a)
            migrate = keep
            keep = []
            for a in lost:
                (canary_lost if is_canary(a) else keep).append(a)
            lost = keep

        # Determine stops for count shrink; name index over live allocs
        # (+ completed batch allocs, whose names stay reserved)
        live = untainted + migrate
        name_index = AllocNameIndex(self.job_id, tg.name, tg.count,
                                    live + batch_complete)

        n_live = len(untainted) + len(migrate)
        if n_live > tg.count:
            excess = n_live - tg.count
            # OLD-version allocs shrink first: after a canary promotion
            # the surviving canaries ARE the new version and the excess
            # is exactly the old allocs they replace -- index-order alone
            # could stop a canary instead (duplicate canary indexes)
            old_first = sorted(
                (a for a in untainted
                 if a.job_version != self.job.version),
                key=lambda a: -a.index())[:excess]
            stop_ids = {a.id for a in old_first}
            new_untainted = []
            for a in untainted:
                if a.id in stop_ids:
                    self.result.stop.append(AllocStopResult(
                        alloc=a, status_description=ALLOC_NOT_NEEDED))
                    du.stop += 1
                    name_index.b.discard(a.index())
                else:
                    new_untainted.append(a)
            untainted = new_untainted
            excess -= len(stop_ids)
            if excess > 0:
                remove_idx = name_index.unset_highest(excess)
                removed = 0
                new_untainted = []
                for a in untainted:
                    if removed < excess and a.index() in remove_idx:
                        self.result.stop.append(AllocStopResult(
                            alloc=a, status_description=ALLOC_NOT_NEEDED))
                        du.stop += 1
                        name_index.b.discard(a.index())
                        removed += 1
                    else:
                        new_untainted.append(a)
                untainted = new_untainted

        # In-place vs destructive updates for allocs on old job versions
        inplace: List[Allocation] = []
        destructive: List[Allocation] = []
        ignore: List[Allocation] = []
        for a in untainted:
            if a.job_version == self.job.version:
                ignore.append(a)
                continue
            if a.job is not None and tasks_updated(a.job, self.job, tg.name):
                destructive.append(a)
            else:
                inplace.append(a)
        du.ignore += len(ignore)
        du.in_place_update += len(inplace)
        for a in inplace:
            updated = a.copy_skip_job()
            updated.job = self.job
            updated.job_version = self.job.version
            self.result.inplace_update.append(updated)

        # Canary gate (reference: reconcile.go computeCanaries): with
        # update.canary > 0 and an unpromoted deployment, destructive
        # updates are BLOCKED; up to `canary` new-version allocs place
        # ALONGSIDE the old ones. Lost/migrating canaries stop and are
        # re-placed HERE (fresh canary indexes, the reference's
        # NextCanaries) so replacements keep the canary marking. After
        # promotion the surviving canaries count toward the new version,
        # so an equal number of old allocs stop outright and the rest
        # roll through the max_parallel gate.
        # update-needed count BEFORE any gating: completion must reflect
        # outstanding work, not what this round deferred
        destructive_total = len(destructive)
        # the gate applies even before the deployment object exists (the
        # FIRST eval of a canary update creates it via du.canary)
        if canaries_desired and not promoted and \
                (destructive or canary_live or canary_lost):
            for a in canary_lost:
                du.stop += 1
                self.result.stop.append(AllocStopResult(
                    alloc=a, client_status=ALLOC_CLIENT_LOST,
                    status_description=ALLOC_LOST))
            canary_missing = canaries_desired - len(canary_live)
            used_idx = {a.index() for a in canary_live}
            next_idx = 0
            for _ in range(max(0, canary_missing)):
                while next_idx in used_idx:
                    next_idx += 1
                used_idx.add(next_idx)
                du.canary += 1
                self.result.place.append(AllocPlaceResult(
                    name=f"{self.job_id}.{tg.name}[{next_idx}]",
                    task_group=tg, canary=True))
            du.ignore += len(destructive) + len(canary_live)
            destructive = []
        # post-promotion no special stop pass is needed: promoted
        # canaries rejoin `untainted` as current-version allocs and the
        # old-first count shrink above retires the old allocs they
        # replaced; the remaining old allocs roll via max_parallel

        # Rolling-update gate: with an update strategy, at most max_parallel
        # destructive updates per round; in-flight (placed-but-unhealthy)
        # deployment allocs consume slots (reference: reconcile.go
        # computeUpdates + getDeploymentLimit).
        if destructive and update is not None and not update.is_empty():
            in_flight = 0
            if self.deployment is not None:
                st = self.deployment.task_groups.get(tg.name)
                if st is not None:
                    in_flight = max(0, st.placed_allocs - st.healthy_allocs
                                    - st.unhealthy_allocs)
            limit = max(0, update.max_parallel - in_flight)
            deferred = destructive[limit:]
            destructive = destructive[:limit]
            du.ignore += len(deferred)
        for a in destructive:
            du.destructive_update += 1
            self.result.destructive_update.append(AllocDestructiveResult(
                place_name=a.name, place_task_group=tg, stop_alloc=a,
                stop_status_description=ALLOC_NOT_NEEDED))

        # Migrating allocs: stop + replace elsewhere
        for a in migrate:
            du.migrate += 1
            self.result.stop.append(AllocStopResult(
                alloc=a, status_description=ALLOC_MIGRATING,
                client_status=ALLOC_CLIENT_COMPLETE
                if self.batch else ""))
            name_index.b.discard(a.index())
            self.result.place.append(AllocPlaceResult(
                name=a.name, task_group=tg, previous_alloc=a,
                reschedule=False))
            name_index.b.add(a.index())

        # Lost allocs: stop (client lost) + replace
        for a in lost:
            du.stop += 1
            self.result.stop.append(AllocStopResult(
                alloc=a, client_status=ALLOC_CLIENT_LOST,
                status_description=ALLOC_LOST))
            if not tg.prevent_reschedule_on_lost:
                self.result.place.append(AllocPlaceResult(
                    name=a.name, task_group=tg, previous_alloc=a,
                    reschedule=False, previous_lost=True))
                du.place += 1

        # Reschedule-now placements (replacement keeps the name)
        for a in reschedule_now:
            du.reschedule_now += 1
            self.result.stop.append(AllocStopResult(
                alloc=a, status_description=ALLOC_RESCHEDULED))
            self.result.place.append(AllocPlaceResult(
                name=a.name, task_group=tg, previous_alloc=a,
                reschedule=True))

        # Reschedule-later -> followup evals with wait_until
        if reschedule_later:
            evals = self._create_followup_evals(tg, reschedule_later)
            self.result.desired_followup_evals.setdefault(
                tg.name, []).extend(evals)
            du.reschedule_later += len(reschedule_later)

        # New placements to reach desired count
        existing_n = (len(untainted) + len(migrate) + len(batch_complete)
                      + len([a for a in lost
                             if not tg.prevent_reschedule_on_lost])
                      + len(reschedule_now))
        missing = max(0, tg.count - existing_n)
        if missing > 0:
            for name in name_index.next_n(missing):
                self.result.place.append(AllocPlaceResult(
                    name=name, task_group=tg))
                du.place += 1

        # Deployment bookkeeping (service jobs with update strategy)
        complete = destructive_total == 0 and not migrate and missing == 0
        self._update_deployment_for_group(tg, du, complete)
        return complete

    # ------------------------------------------------------------------
    def _create_followup_evals(self, tg: TaskGroup,
                               later: List[Tuple[Allocation, float]]
                               ) -> List[Evaluation]:
        """Batch failed allocs by wait time into delayed evals
        (reference: reconcile.go createRescheduleLaterEvals)."""
        evals = []
        by_time: Dict[float, List[Allocation]] = {}
        for a, t in later:
            by_time.setdefault(t, []).append(a)
        for t, allocs in sorted(by_time.items()):
            ev = Evaluation(
                id=generate_uuid(),
                namespace=self.job.namespace,
                priority=self.eval_priority,
                type=self.job.type,
                triggered_by=TRIGGER_FAILED_FOLLOW_UP,
                job_id=self.job.id,
                status=EVAL_STATUS_PENDING,
                wait_until=t,
            )
            evals.append(ev)
            for a in allocs:
                updated = a.copy_skip_job()
                updated.followup_eval_id = ev.id
                self.result.disconnect_updates.setdefault(
                    "_followup_" + updated.id, updated)
        return evals

    def _create_timeout_evals(self, tg: TaskGroup,
                              disconnecting: List[Allocation]):
        out = []
        deadline = self.now + (tg.max_client_disconnect_s or 0.0)
        ev = Evaluation(
            id=generate_uuid(),
            namespace=self.job.namespace,
            priority=self.eval_priority,
            type=self.job.type,
            triggered_by=TRIGGER_MAX_DISCONNECT_TIMEOUT,
            job_id=self.job.id,
            status=EVAL_STATUS_PENDING,
            wait_until=deadline,
        )
        self.result.desired_followup_evals.setdefault(tg.name, []).append(ev)
        for a in disconnecting:
            out.append((a, ev))
        return out

    # ------------------------------------------------------------------
    def _update_deployment_for_group(self, tg: TaskGroup, du: DesiredUpdates,
                                     complete: bool) -> None:
        if self.batch or self.job.type != JOB_TYPE_SERVICE:
            return
        update = tg.update or self.job.update
        if update is None or update.is_empty():
            return
        if self.deployment_failed or self.deployment_paused:
            return
        # Create a deployment when the job version has no active deployment
        # and there is work to do (reference: reconcile.go createDeployment)
        work = (du.place or du.destructive_update or du.canary)
        if self.deployment is None and work:
            self.deployment = Deployment(
                id=generate_uuid(),
                namespace=self.job.namespace,
                job_id=self.job.id,
                job_version=self.job.version,
                job_create_index=self.job.create_index,
                job_modify_index=self.job.job_modify_index,
                status=DEPLOYMENT_STATUS_RUNNING,
                status_description="Deployment is running",
                eval_priority=self.eval_priority,
            )
            self.result.deployment = self.deployment
        if self.deployment is not None and \
                self.deployment.job_version == self.job.version:
            st = self.deployment.task_groups.get(tg.name)
            if st is None:
                st = DeploymentState(
                    auto_revert=update.auto_revert,
                    auto_promote=update.auto_promote,
                    progress_deadline_s=update.progress_deadline_s,
                    desired_total=tg.count,
                    desired_canaries=update.canary,
                )
                self.deployment.task_groups[tg.name] = st

    def _finalize_deployment(self, deployment_complete: bool) -> None:
        d = self.deployment
        if d is None:
            return
        if deployment_complete and d.status == DEPLOYMENT_STATUS_RUNNING:
            healthy = all(
                st.healthy_allocs >= st.desired_total
                for st in d.task_groups.values()) if d.task_groups else False
            if healthy and not d.requires_promotion():
                self.result.deployment_updates.append(DeploymentStatusUpdate(
                    deployment_id=d.id,
                    status=DEPLOYMENT_STATUS_SUCCESSFUL,
                    status_description="Deployment completed successfully"))
