"""Compiled host-baseline oracle driver.

Packs a (nodes, job, task-group) world into the dense arrays the native
`nt_solve_eval` kernel consumes and runs the reference scheduler's per-eval
inner loop (seeded shuffle + log2-window binpack select + usage carry,
reference: scheduler/rank.go:205, stack.go:82-95, select.go, util.go:167)
as compiled C++. This is the *baseline* the TPU solver's `vs_native_host`
speedup is measured against in bench.py; parity against the Python oracle
is gated in tests/test_native_oracle.py.

Scope matches the bench workload: cpu/mem/disk asks, eligibility from
job+tg constraints and driver presence, binpack or spread scoring, job
anti-affinity. Asks with ports/devices/cores route to the full Python
oracle in production and are outside this baseline.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from .. import native
from ..structs import Job, Node, TaskGroup
from .context import EvalContext
from .feasible import ConstraintChecker, DriverChecker
from .util import shuffle_seed


class PackedWorld:
    """Dense node-axis arrays for the native oracle, in base node order."""

    def __init__(self, nodes: List[Node], ctx: EvalContext, job: Job,
                 tg: TaskGroup):
        n = len(nodes)
        self.nodes = nodes
        self.cpu_cap = np.empty(n, dtype=np.float64)
        self.mem_cap = np.empty(n, dtype=np.float64)
        self.disk_cap = np.empty(n, dtype=np.float64)
        self.used_cpu = np.zeros(n, dtype=np.float64)
        self.used_mem = np.zeros(n, dtype=np.float64)
        self.used_disk = np.zeros(n, dtype=np.float64)
        self.placed_jobtg = np.zeros(n, dtype=np.int32)
        self.eligible = np.ones(n, dtype=np.uint8)

        for k, node in enumerate(nodes):
            nr, rr = node.node_resources, node.reserved_resources
            self.cpu_cap[k] = nr.cpu.cpu_shares - rr.cpu_shares
            self.mem_cap[k] = nr.memory.memory_mb - rr.memory_mb
            self.disk_cap[k] = nr.disk.disk_mb - rr.disk_mb
            for alloc in ctx.proposed_allocs(node.id):
                cr = alloc.allocated_resources.comparable()
                self.used_cpu[k] += cr.cpu_shares
                self.used_mem[k] += cr.memory_mb
                self.used_disk[k] += cr.disk_mb
                if alloc.job_id == job.id and alloc.task_group == tg.name:
                    self.placed_jobtg[k] += 1

        # Eligibility: job + tg constraints and driver presence -- the same
        # boolean the FeasibilityWrapper memoizes per computed class.
        drivers = set()
        constraints = list(job.constraints) + list(tg.constraints)
        for task in tg.tasks:
            drivers.add(task.driver)
            constraints.extend(task.constraints)
        ccheck = ConstraintChecker(ctx, constraints)
        dcheck = DriverChecker(ctx, drivers)
        for k, node in enumerate(nodes):
            if not (dcheck.feasible(node) and ccheck.feasible(node)):
                self.eligible[k] = 0

        # The task-group ask (single combined alloc footprint).
        self.ask_cpu = float(sum(t.resources.cpu for t in tg.tasks))
        self.ask_mem = float(sum(t.resources.memory_mb for t in tg.tasks))
        self.ask_disk = float(tg.ephemeral_disk.size_mb
                              if tg.ephemeral_disk else 0)


def supported(tg: TaskGroup) -> bool:
    """True when the native baseline covers this ask shape."""
    if tg.networks:
        return False
    for task in tg.tasks:
        if task.resources.devices or task.resources.cores:
            return False
    return True


def scan_limit(n_nodes: int, batch: bool) -> int:
    """max(2, ceil(log2 n)) for service jobs (reference: stack.go:82-95)."""
    limit = 2
    if not batch and n_nodes > 1:
        limit = max(limit, int(math.ceil(math.log2(n_nodes))))
    return limit


def solve(world: PackedWorld, eval_id: str, state_index: int,
          n_placements: int, desired_count: int, batch: bool = False,
          spread_alg: bool = False) -> Optional[Dict[str, Optional[str]]]:
    """Run the native oracle; returns {alloc_index: node_id or None} or
    None when the native library is unavailable. Mutates the world's usage
    arrays (same carry the plan provides the Python oracle)."""
    choices = native.solve_eval(
        world.cpu_cap, world.mem_cap, world.disk_cap,
        world.used_cpu, world.used_mem, world.used_disk,
        world.placed_jobtg, world.eligible,
        shuffle_seed(eval_id, state_index),
        world.ask_cpu, world.ask_mem, world.ask_disk,
        desired_count, scan_limit(len(world.nodes), batch), n_placements,
        spread_alg=spread_alg)
    if choices is None:
        return None
    return {i: (world.nodes[int(c)].id if c >= 0 else None)
            for i, c in enumerate(choices)}
