"""Spread scoring (reference: /root/reference/scheduler/spread.go and
propertyset.go)."""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..structs import Job, Node, Spread, TaskGroup
from .context import EvalContext
from .rank import RankedNode, RankIterator
from .util import resolve_target

IMPLICIT_TARGET = "*"


class PropertySet:
    """Counts this job's allocs per value of one attribute
    (reference: scheduler/propertyset.go). Includes plan placements,
    excludes plan stops; client-terminal allocs don't count."""

    def __init__(self, ctx: EvalContext, job: Job, target_attribute: str):
        self.ctx = ctx
        self.job = job
        self.target_attribute = target_attribute
        self.tg_name: Optional[str] = None
        self._existing: Optional[Dict[str, int]] = None

    def set_tg_name(self, name: str) -> None:
        self.tg_name = name
        self._existing = None

    def _node_value(self, node: Node) -> Tuple[str, bool]:
        return resolve_target(self.target_attribute, node)

    def _gather(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        allocs = self.ctx.state.allocs_by_job(self.job.namespace, self.job.id)
        stopped = set()
        for na in self.ctx.plan.node_update.values():
            stopped.update(a.id for a in na)
        for na in self.ctx.plan.node_preemptions.values():
            stopped.update(a.id for a in na)
        live = [a for a in allocs
                if a.id not in stopped and not a.terminal_status()]
        for na in self.ctx.plan.node_allocation.values():
            live.extend(na)
        for alloc in live:
            if self.tg_name is not None and alloc.task_group != self.tg_name:
                continue
            node = self.ctx.state.node_by_id(alloc.node_id)
            if node is None:
                continue
            val, ok = self._node_value(node)
            if not ok:
                continue
            counts[str(val)] = counts.get(str(val), 0) + 1
        return counts

    def used_count(self, node: Node) -> Tuple[str, str, int]:
        """(node's value, errMsg, used count for that value)
        (reference: propertyset.go UsedCount)."""
        val, ok = self._node_value(node)
        if not ok:
            return "", f"missing property {self.target_attribute}", 0
        counts = self.combined_use_map()
        return str(val), "", counts.get(str(val), 0)

    def combined_use_map(self) -> Dict[str, int]:
        # Recomputed per call because the plan mutates between placements
        # within one eval (reference recomputes from plan similarly).
        return self._gather()


class SpreadIterator(RankIterator):
    """(reference: spread.go:128 SpreadIterator.Next)"""

    def __init__(self, ctx: EvalContext, source: RankIterator):
        self.ctx = ctx
        self.source = source
        self.job: Optional[Job] = None
        self.tg: Optional[TaskGroup] = None
        self.job_spreads: List[Spread] = []
        self.spreads: List[Spread] = []
        self.property_sets: Dict[str, PropertySet] = {}
        self.sum_spread_weights = 0
        self.lowest_spread_boost = -1.0

    def set_job(self, job: Job) -> None:
        self.job = job
        self.job_spreads = list(job.spreads)

    def set_task_group(self, tg: TaskGroup) -> None:
        self.tg = tg
        self.spreads = list(self.job_spreads) + list(tg.spreads)
        self.sum_spread_weights = sum(s.weight for s in self.spreads)
        self.property_sets = {}
        self.lowest_spread_boost = -1.0
        for s in self.spreads:
            ps = PropertySet(self.ctx, self.job, s.attribute)
            ps.set_tg_name(tg.name)
            self.property_sets[s.attribute] = ps

    def has_spreads(self) -> bool:
        return bool(self.spreads)

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None or not self.has_spreads():
            return option

        total = 0.0
        for spread in self.spreads:
            pset = self.property_sets[spread.attribute]
            nvalue, err, used = pset.used_count(option.node)
            used += 1  # include this placement
            if err:
                total -= 1.0
                continue
            desired = {t.value: t.percent for t in spread.spread_target}
            if not desired:
                total += even_spread_score_boost(pset, option.node)
                continue
            tg_count = self.tg.count or 1
            pct = desired.get(nvalue, desired.get(IMPLICIT_TARGET))
            if pct is None:
                total -= 1.0
                continue
            desired_count = (pct / 100.0) * tg_count
            spread_weight = float(spread.weight) / float(self.sum_spread_weights)
            if desired_count == 0:
                total += self.lowest_spread_boost
                continue
            boost = ((desired_count - float(used)) / desired_count) * spread_weight
            total += boost
            if boost < self.lowest_spread_boost:
                self.lowest_spread_boost = boost

        if total != 0.0:
            option.scores.append(total)
            self.ctx.metrics.score_node(option.node.id, "allocation-spread", total)
        return option

    def reset(self) -> None:
        self.source.reset()


def even_spread_score_boost(pset: PropertySet, node: Node) -> float:
    """Even spreading when no targets given (reference: spread.go:216)."""
    combined = pset.combined_use_map()
    if not combined:
        return 0.0
    nvalue, ok = resolve_target(pset.target_attribute, node)
    if not ok:
        return -1.0
    current = combined.get(str(nvalue), 0)
    counts = list(combined.values())
    min_count = min(counts)
    max_count = max(counts)
    if current != min_count:
        if min_count == 0:
            return -1.0
        return float(min_count - current) / float(min_count)
    elif min_count == max_count:
        return -1.0
    elif min_count == 0:
        return 1.0
    delta = max_count - min_count
    return float(delta) / float(min_count)
