"""Placement stacks: the chained iterator pipelines.

Semantic parity with /root/reference/scheduler/stack.go:
  GenericStack (:46, chain order at NewGenericStack :370), SystemStack
  (:201), the log2 candidate limit (:82-95) and the >=100-node override for
  spread/affinity jobs (:176-185).
"""
from __future__ import annotations

import math
import time
from typing import List, Optional, Set

from ..structs import (
    Job, Node, SchedulerConfiguration, TaskGroup,
)
from .context import EvalContext
from .feasible import (
    ConstraintChecker, DeviceChecker, DistinctHostsIterator,
    DistinctPropertyIterator, DriverChecker, FeasibilityWrapper,
    CSIVolumeChecker, HostVolumeChecker, NetworkChecker, StaticIterator,
)
from .rank import (
    BinPackIterator, FeasibleRankIterator, JobAntiAffinityIterator,
    NodeAffinityIterator, NodeReschedulingPenaltyIterator,
    PreemptionScoringIterator, RankedNode, ScoreNormalizationIterator,
)
from .select import LimitIterator, MaxScoreIterator
from .spread import SpreadIterator
from .util import shuffle_nodes


class SelectOptions:
    """(reference: stack.go:37)"""

    def __init__(self, penalty_node_ids: Optional[Set[str]] = None,
                 preferred_nodes: Optional[List[Node]] = None,
                 preempt: bool = False, alloc_name: str = ""):
        self.penalty_node_ids = penalty_node_ids or set()
        self.preferred_nodes = preferred_nodes or []
        self.preempt = preempt
        self.alloc_name = alloc_name


def _tg_constraints(tg: TaskGroup):
    """Collect drivers + merged constraints for a task group
    (reference: stack.go taskGroupConstraints)."""
    drivers = set()
    constraints = list(tg.constraints)
    for task in tg.tasks:
        drivers.add(task.driver)
        constraints.extend(task.constraints)
    return drivers, constraints


class GenericStack:
    """Service/batch placement stack (reference: stack.go:46)."""

    def __init__(self, batch: bool, ctx: EvalContext):
        self.batch = batch
        self.ctx = ctx
        self.job_version: Optional[int] = None

        self.source = StaticIterator(ctx, [])
        self._pending_shuffle = None
        self.job_constraint = ConstraintChecker(ctx, [])
        self.tg_drivers = DriverChecker(ctx, set())
        self.tg_constraint = ConstraintChecker(ctx, [])
        self.tg_devices = DeviceChecker(ctx)
        self.tg_host_volumes = HostVolumeChecker(ctx)
        self.tg_csi_volumes = CSIVolumeChecker(ctx)
        self.tg_network = NetworkChecker(ctx)
        self.wrapped_checks = FeasibilityWrapper(
            ctx, self.source,
            job_checkers=[self.job_constraint],
            tg_checkers=[self.tg_drivers, self.tg_constraint,
                         self.tg_devices, self.tg_network],
            avail_checkers=[self.tg_host_volumes, self.tg_csi_volumes])
        self.distinct_hosts = DistinctHostsIterator(ctx, self.wrapped_checks)
        self.distinct_property = DistinctPropertyIterator(
            ctx, self.distinct_hosts)
        rank_source = FeasibleRankIterator(ctx, self.distinct_property)
        self.binpack = BinPackIterator(ctx, rank_source, evict=False, priority=0)
        self.job_anti_aff = JobAntiAffinityIterator(ctx, self.binpack, "")
        self.resched_penalty = NodeReschedulingPenaltyIterator(
            ctx, self.job_anti_aff)
        self.node_affinity = NodeAffinityIterator(ctx, self.resched_penalty)
        self.spread = SpreadIterator(ctx, self.node_affinity)
        preemption_scorer = PreemptionScoringIterator(ctx, self.spread)
        self.score_norm = ScoreNormalizationIterator(ctx, preemption_scorer)
        self.limit = LimitIterator(ctx, self.score_norm)
        self.max_score = MaxScoreIterator(ctx, self.limit)

    def set_nodes(self, base_nodes: List[Node]) -> None:
        """Set candidate nodes + apply the log2 scan limit (reference:
        stack.go:75-95 GenericStack.SetNodes). The Fisher-Yates shuffle
        is DEFERRED to the first select(): it is an O(N)-python pass
        over the whole fleet, and the TPU placement path consults the
        stack only when a lane falls back to the host iterators -- the
        shuffle seed (plan eval id + the state index captured HERE)
        makes deferral invisible to semantics."""
        self._pending_shuffle = (list(base_nodes),
                                 self.ctx.state.latest_index())

        limit = 2
        n = len(base_nodes)
        if not self.batch and n > 0:
            log_limit = int(math.ceil(math.log2(n))) if n > 1 else 1
            if log_limit > limit:
                limit = log_limit
        self.limit.set_limit(limit)

    def _materialize_nodes(self) -> None:
        pending = self._pending_shuffle
        if pending is None:
            return
        self._pending_shuffle = None
        nodes, idx = pending
        shuffle_nodes(self.ctx.plan, idx, nodes)
        self.source.set_nodes(nodes)

    def set_job(self, job: Job) -> None:
        if self.job_version is not None and self.job_version == job.version:
            return
        self.job_version = job.version
        self.tg_csi_volumes.set_namespace(job.namespace)
        self.job_constraint.set_constraints(job.constraints)
        self.distinct_hosts.set_job(job)
        self.distinct_property.set_job(job)
        self.binpack.set_job(job)
        self.job_anti_aff.set_job(job)
        self.node_affinity.set_job(job)
        self.spread.set_job(job)
        self.ctx.eligibility().set_job(job)

    def set_scheduler_configuration(self, cfg: SchedulerConfiguration) -> None:
        self.binpack.set_scheduler_configuration(cfg)

    def select(self, tg: TaskGroup,
               options: Optional[SelectOptions] = None) -> Optional[RankedNode]:
        """(reference: stack.go:128 GenericStack.Select)"""
        options = options or SelectOptions()
        self._materialize_nodes()

        if options.preferred_nodes:
            original = self.source.nodes
            self.source.set_nodes(options.preferred_nodes)
            sub = SelectOptions(options.penalty_node_ids, [], options.preempt,
                                options.alloc_name)
            option = self.select(tg, sub)
            self.source.set_nodes(original)
            if option is not None:
                return option
            return self.select(tg, sub)

        self.max_score.reset()
        self.ctx.reset()
        start = time.perf_counter_ns()

        drivers, constraints = _tg_constraints(tg)
        self.tg_drivers.set_drivers(drivers)
        self.tg_constraint.set_constraints(constraints)
        self.tg_devices.set_task_group(tg)
        self.tg_host_volumes.set_volumes(options.alloc_name, tg.volumes)
        self.tg_csi_volumes.set_volumes(options.alloc_name, tg.volumes)
        if tg.networks:
            self.tg_network.set_network(tg.networks[0])
        else:
            self.tg_network.set_network(None)
        self.distinct_hosts.set_task_group(tg)
        self.distinct_property.set_task_group(tg)
        self.wrapped_checks.set_task_group(tg.name)
        self.binpack.set_task_group(tg)
        self.binpack.evict = options.preempt
        self.job_anti_aff.set_task_group(tg)
        self.resched_penalty.set_penalty_nodes(options.penalty_node_ids)
        self.node_affinity.set_task_group(tg)
        self.spread.set_task_group(tg)

        if self.node_affinity.has_affinities() or self.spread.has_spreads():
            # spread/affinity scoring needs a wide scan
            # (reference: stack.go:176-185)
            limit = tg.count
            if tg.count < 100:
                limit = 100
            self.limit.set_limit(limit)

        option = self.max_score.next()
        self.ctx.metrics.allocation_time_ns = time.perf_counter_ns() - start
        return option


class SystemStack:
    """System/sysbatch stack: every feasible node, no limit
    (reference: stack.go:201 SystemStack)."""

    def __init__(self, ctx: EvalContext, sysbatch: bool = False):
        self.ctx = ctx
        self.sysbatch = sysbatch

        self.source = StaticIterator(ctx, [])
        self.job_constraint = ConstraintChecker(ctx, [])
        self.tg_drivers = DriverChecker(ctx, set())
        self.tg_constraint = ConstraintChecker(ctx, [])
        self.tg_devices = DeviceChecker(ctx)
        self.tg_host_volumes = HostVolumeChecker(ctx)
        self.tg_csi_volumes = CSIVolumeChecker(ctx)
        self.tg_network = NetworkChecker(ctx)
        self.wrapped_checks = FeasibilityWrapper(
            ctx, self.source,
            job_checkers=[self.job_constraint],
            tg_checkers=[self.tg_drivers, self.tg_constraint,
                         self.tg_devices, self.tg_network],
            avail_checkers=[self.tg_host_volumes, self.tg_csi_volumes])
        self.distinct_property = DistinctPropertyIterator(
            ctx, self.wrapped_checks)
        rank_source = FeasibleRankIterator(ctx, self.distinct_property)
        self.binpack = BinPackIterator(ctx, rank_source, evict=False, priority=0)
        self.score_norm = ScoreNormalizationIterator(ctx, self.binpack)

    def set_nodes(self, base_nodes: List[Node]) -> None:
        self.source.set_nodes(list(base_nodes))

    def set_job(self, job: Job) -> None:
        self.tg_csi_volumes.set_namespace(job.namespace)
        self.job_constraint.set_constraints(job.constraints)
        self.distinct_property.set_job(job)
        self.binpack.set_job(job)
        self.ctx.eligibility().set_job(job)

    def set_scheduler_configuration(self, cfg: SchedulerConfiguration) -> None:
        self.binpack.set_scheduler_configuration(cfg)

    def select(self, tg: TaskGroup,
               options: Optional[SelectOptions] = None) -> Optional[RankedNode]:
        self.ctx.reset()
        start = time.perf_counter_ns()
        options = options or SelectOptions()
        drivers, constraints = _tg_constraints(tg)
        self.tg_drivers.set_drivers(drivers)
        self.tg_constraint.set_constraints(constraints)
        self.tg_devices.set_task_group(tg)
        self.tg_host_volumes.set_volumes(options.alloc_name, tg.volumes)
        self.tg_csi_volumes.set_volumes(options.alloc_name, tg.volumes)
        if tg.networks:
            self.tg_network.set_network(tg.networks[0])
        else:
            self.tg_network.set_network(None)
        self.distinct_property.set_task_group(tg)
        self.wrapped_checks.set_task_group(tg.name)
        self.binpack.set_task_group(tg)
        self.binpack.evict = options.preempt
        option = self.score_norm.next()
        self.ctx.metrics.allocation_time_ns = time.perf_counter_ns() - start
        return option
