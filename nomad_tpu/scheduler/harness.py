"""Scheduler test harness (reference: /root/reference/scheduler/testing.go).

Wraps a real StateStore with a fake Planner that locally applies submitted
plans to the store -- the mechanism the reference uses for all scheduler
unit tests, and the parity-diff mechanism between the host oracle and the
TPU solver path (SURVEY.md section 4).
"""
from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from ..state import StateStore
from ..structs import (
    Evaluation, Plan, PlanResult, allocs_fit,
)
from .factory import new_scheduler


class Harness:
    """(reference: testing.go:50 Harness)"""

    def __init__(self, state: Optional[StateStore] = None):
        self.state = state if state is not None else StateStore()
        self.plans: List[Plan] = []
        self.evals: List[Evaluation] = []
        self.create_evals: List[Evaluation] = []
        self.reblock_evals: List[Evaluation] = []
        self.reject_plan = False
        self.reject_tracker = 0
        self._lock = threading.Lock()

    # -- Planner interface ---------------------------------------------------
    def submit_plan(self, plan: Plan) -> Tuple[Optional[PlanResult], object]:
        with self._lock:
            self.plans.append(plan)
            if self.reject_plan:
                self.reject_tracker += 1
                result = PlanResult(refresh_index=self.state.latest_index())
                return result, self.state.snapshot()

            result = PlanResult(
                node_update={k: list(v) for k, v in plan.node_update.items()},
                node_allocation={k: list(v)
                                 for k, v in plan.node_allocation.items()},
                node_preemptions={k: list(v)
                                  for k, v in plan.node_preemptions.items()},
                deployment=plan.deployment,
                deployment_updates=list(plan.deployment_updates),
            )
            index = self.state.upsert_plan_results(result)
            result.alloc_index = index
            return result, None

    def update_eval(self, ev: Evaluation) -> None:
        with self._lock:
            self.evals.append(ev)

    def create_eval(self, ev: Evaluation) -> None:
        with self._lock:
            self.create_evals.append(ev)
            self.state.upsert_evals([ev])

    def reblock_eval(self, ev: Evaluation) -> None:
        with self._lock:
            self.reblock_evals.append(ev)

    def scheduler_config(self):
        return self.state.scheduler_config()

    # -- driving -------------------------------------------------------------
    def process(self, factory_name_or_fn, ev: Evaluation):
        """Instantiate the scheduler for the eval type and run it
        (reference: testing.go Process). Runs under an eval-scoped
        trace like the server's workers, so parity harness runs and
        bench worlds produce the same flight-recorder artifacts."""
        from ..server.tracing import tracer

        snap = self.state.snapshot()
        if callable(factory_name_or_fn):
            sched = factory_name_or_fn(snap, self)
        else:
            sched = new_scheduler(factory_name_or_fn, snap, self)
        ctx = tracer.begin(ev.id, job=ev.job_id, lane=ev.type,
                           trigger=ev.triggered_by, source="harness")
        err = None
        try:
            with tracer.activate(ctx), \
                    tracer.span("harness.process", ctx=ctx):
                result = sched.process(ev)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            raise
        finally:
            tracer.end(ev.id, status="failed" if err else "complete",
                       error=err)
        return result

    def assert_eval_status(self, testcase, count: int, status: str) -> None:
        assert len(self.evals) == count, \
            f"expected {count} eval updates, got {len(self.evals)}"
        assert self.evals[-1].status == status, \
            f"expected status {status}, got {self.evals[-1].status}"
