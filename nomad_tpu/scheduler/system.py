"""SystemScheduler: place one instance of each TG on every feasible node.

Semantic parity with /root/reference/scheduler/scheduler_system.go (:31
SystemScheduler, :78 Process) and system_util.go (diffSystemAllocs).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..structs import (
    AllocatedResources, AllocatedSharedResources, Allocation, Evaluation,
    Node, Plan, generate_uuid,
    ALLOC_CLIENT_LOST, ALLOC_DESIRED_RUN, EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED, JOB_TYPE_SYSBATCH, JOB_TYPE_SYSTEM,
    NODE_STATUS_DOWN, ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED,
)
from .context import EvalContext
from .generic import SetStatusError
from .reconcile import tasks_updated
from .stack import SelectOptions, SystemStack
from .util import progress_made, tainted_nodes

MAX_SYSTEM_SCHEDULE_ATTEMPTS = 5
MAX_SYSBATCH_SCHEDULE_ATTEMPTS = 2


class SystemScheduler:
    """(reference: scheduler_system.go:31)"""

    def __init__(self, state, planner, sysbatch: bool = False, logger=None):
        self.state = state
        self.planner = planner
        self.sysbatch = sysbatch
        self.logger = logger
        self.eval: Optional[Evaluation] = None
        self.job = None
        self.plan: Optional[Plan] = None
        self.plan_result = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[SystemStack] = None
        self.failed_tg_allocs: Dict[str, object] = {}
        self.queued_allocs: Dict[str, int] = {}

    def process(self, evaluation: Evaluation):
        self.eval = evaluation
        limit = (MAX_SYSBATCH_SCHEDULE_ATTEMPTS if self.sysbatch
                 else MAX_SYSTEM_SCHEDULE_ATTEMPTS)
        attempts = 0
        while attempts < limit:
            try:
                done = self._process_once()
            except SetStatusError as e:
                self.planner.update_eval(self._eval_with_status(
                    e.eval_status, str(e)))
                return e
            if done:
                self.planner.update_eval(self._eval_with_status(
                    EVAL_STATUS_COMPLETE, ""))
                return None
            if progress_made(self.plan_result):
                attempts = 0
            else:
                attempts += 1
        err = SetStatusError(f"maximum attempts reached ({limit})")
        self.planner.update_eval(self._eval_with_status(
            EVAL_STATUS_FAILED, str(err)))
        return err

    def _eval_with_status(self, status: str, desc: str) -> Evaluation:
        ev = self.eval.copy()
        ev.status = status
        ev.status_description = desc
        ev.failed_tg_allocs = dict(self.failed_tg_allocs)
        ev.queued_allocations = dict(self.queued_allocs)
        return ev

    def _process_once(self) -> bool:
        self.failed_tg_allocs = {}
        ns, job_id = self.eval.namespace, self.eval.job_id
        self.job = self.state.job_by_id(ns, job_id)

        self.plan = Plan(eval_id=self.eval.id, priority=self.eval.priority,
                         job=self.job)
        self.ctx = EvalContext(self.state, self.plan, self.logger)
        self.stack = SystemStack(self.ctx, self.sysbatch)

        nodes: List[Node] = []
        if self.job is not None and not self.job.stopped():
            if hasattr(self.state, "scheduler_config"):
                self.stack.set_scheduler_configuration(
                    self.state.scheduler_config())
            self.stack.set_job(self.job)
            nodes = self.state.ready_nodes_in_pool(self.job.node_pool)
            dcs = set(self.job.datacenters)
            if "*" not in dcs:
                nodes = [n for n in nodes if n.datacenter in dcs]

        existing = self.state.allocs_by_job(ns, job_id)
        tainted = tainted_nodes(self.state, existing)

        self._compute_diff(nodes, existing, tainted)

        if self.plan.is_no_op():
            self.plan_result = None
            return True
        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result
        if result is None:
            return False
        full, _, _ = result.full_commit(self.plan)
        if not full:
            if new_state is not None:
                self.state = new_state
            return False
        return True

    def _compute_diff(self, nodes: List[Node], existing: List[Allocation],
                      tainted: Dict[str, Optional[Node]]) -> None:
        """diffSystemAllocs: per node x TG decide place/ignore/update/stop
        (reference: system_util.go)."""
        job_stopped = self.job is None or self.job.stopped()
        by_node_tg: Dict[tuple, Allocation] = {}
        for a in existing:
            if a.server_terminal_status():
                continue
            if self.sysbatch and a.client_status == ALLOC_CLIENT_COMPLETE:
                continue
            by_node_tg[(a.node_id, a.task_group)] = a

        # Stops: job stopped, node down/deregistered, or drain with a
        # migrate transition. Merely not-ready/ineligible nodes keep their
        # system allocs (reference: system_util.go:200-202 goto IGNORE).
        for (node_id, tg_name), alloc in list(by_node_tg.items()):
            node = tainted.get(node_id)
            stop_desc = None
            client_status = ""
            if job_stopped:
                stop_desc = "alloc not needed as job is stopped"
            elif node_id in tainted:
                if node is None or node.status == NODE_STATUS_DOWN:
                    stop_desc = "alloc lost since its node is down"
                    client_status = ALLOC_CLIENT_LOST
                elif node.drain and alloc.desired_transition.should_migrate():
                    stop_desc = "alloc is being migrated"
            if stop_desc is not None:
                self.plan.append_stopped_alloc(alloc, stop_desc, client_status)
                del by_node_tg[(node_id, tg_name)]

        if job_stopped:
            return

        for tg in self.job.task_groups:
            placed = 0
            # Pass 1: updates and destructive stops, collecting the nodes
            # that need a fresh placement. Stops land in the plan BEFORE
            # the dense solve packs usage, so the freed capacity is seen
            # (coupling is within-node only; the host's interleaved order
            # is equivalent because placements go to distinct nodes).
            to_place: List[Node] = []
            for node in nodes:
                current = by_node_tg.get((node.id, tg.name))
                if current is not None:
                    if current.job_version == self.job.version:
                        continue  # ignore: up to date
                    if current.job is not None and tasks_updated(
                            current.job, self.job, tg.name):
                        # destructive update
                        self.plan.append_stopped_alloc(
                            current, "alloc not needed due to job update")
                    else:
                        updated = current.copy_skip_job()
                        updated.job = self.job
                        updated.job_version = self.job.version
                        self.plan.append_alloc(updated)
                        continue
                to_place.append(node)

            # Pass 2: dense TPU solve (one vectorized fit+score over every
            # node -- the system form has no sequential dependence at all)
            # with per-node host fallback when ineligible.
            dense = self._dense_system(tg, to_place)
            preempt = self._preemption_enabled()
            for i, node in enumerate(to_place):
                alloc_metrics = None
                option = None
                if dense is not None:
                    sp = dense[i]
                    if sp.node is not None and sp.task_resources is not None:
                        option = sp
                        # dense selects never touch ctx.metrics: record
                        # the same evaluation trail the host path leaves
                        # (1 candidate node, normalized score)
                        self.ctx.reset()
                        alloc_metrics = self.ctx.metrics.copy()
                        alloc_metrics.nodes_evaluated = 1
                        alloc_metrics.score_node(
                            sp.node.id, "normalized-score", sp.score)
                    elif preempt:
                        # full node + preemption enabled: the eviction
                        # search is host-only -- retry just this node
                        # through the stack with evict on (reference:
                        # system jobs preempt by default,
                        # PreemptionConfig.SystemSchedulerEnabled)
                        self.stack.set_nodes([node])
                        option = self.stack.select(tg, SelectOptions(
                            alloc_name=f"{self.job.id}.{tg.name}[0]",
                            preempt=True))
                else:
                    self.stack.set_nodes([node])
                    option = self.stack.select(tg, SelectOptions(
                        alloc_name=f"{self.job.id}.{tg.name}[0]",
                        preempt=preempt))
                if option is None:
                    if tg.name in self.failed_tg_allocs:
                        self.failed_tg_allocs[tg.name].coalesced_failures += 1
                    else:
                        if dense is not None and not preempt:
                            # no host select ran: synthesize the trail
                            self.ctx.reset()
                            m = self.ctx.metrics.copy()
                            m.nodes_evaluated = 1
                            m.exhausted_node(node.id, node.computed_class,
                                             "resources exhausted")
                            self.failed_tg_allocs[tg.name] = m
                        else:
                            self.failed_tg_allocs[tg.name] = \
                                self.ctx.metrics.copy()
                    continue
                resources = AllocatedResources(
                    tasks=dict(option.task_resources),
                    shared=option.alloc_resources
                    if option.alloc_resources is not None
                    else AllocatedSharedResources(
                        disk_mb=tg.ephemeral_disk.size_mb))
                alloc = Allocation(
                    id=generate_uuid(),
                    namespace=self.job.namespace,
                    eval_id=self.eval.id,
                    name=f"{self.job.id}.{tg.name}[0]",
                    job_id=self.job.id,
                    job=self.job,
                    job_version=self.job.version,
                    task_group=tg.name,
                    node_id=option.node.id,
                    node_name=option.node.name,
                    allocated_resources=resources,
                    desired_status=ALLOC_DESIRED_RUN,
                    client_status="pending",
                    metrics=(alloc_metrics if alloc_metrics is not None
                             else self.ctx.metrics.copy()),
                )
                if option.preempted_allocs:
                    for p in option.preempted_allocs:
                        self.plan.append_preempted_alloc(p, alloc.id)
                self.plan.append_alloc(alloc)
                placed += 1
            self.queued_allocs[tg.name] = 0

    def _preemption_enabled(self) -> bool:
        """(reference: PreemptionConfig -- system on by default,
        sysbatch off by default)"""
        cfg = (self.state.scheduler_config()
               if hasattr(self.state, "scheduler_config") else None)
        if cfg is None:
            return False
        return cfg.preemption_config.is_enabled(
            JOB_TYPE_SYSBATCH if self.sysbatch else JOB_TYPE_SYSTEM)

    def _dense_system(self, tg, to_place: List[Node]):
        """TpuPlacement list aligned with to_place when the tpu algorithm
        is selected and the TG is dense-eligible, else None (host path).
        Gated out: distinct_property (its counts couple nodes through the
        plan) and device asks (allocation replay is generic-path only)."""
        if not to_place:
            return None
        if not hasattr(self.state, "scheduler_config"):
            return None
        cfg = self.state.scheduler_config()
        if cfg is None or not cfg.uses_tpu():
            return None
        from ..solver.guard import dispatch_allowed, note_host_fallback
        if not dispatch_allowed():
            note_host_fallback()
            return None
        from ..solver.service import TpuPlacementService, tg_solver_eligible
        from ..structs import CONSTRAINT_DISTINCT_PROPERTY, \
            SCHED_ALG_TPU_SPREAD
        if not tg_solver_eligible(tg, self.job):
            return None
        if any(t.resources.devices for t in tg.tasks):
            return None
        if any(c.operand == CONSTRAINT_DISTINCT_PROPERTY
               for c in list(self.job.constraints) + list(tg.constraints)):
            return None
        service = TpuPlacementService(
            self.ctx, self.job, batch_mode=self.sysbatch,
            spread_alg=cfg.scheduler_algorithm == SCHED_ALG_TPU_SPREAD)
        solved = service.solve_system(tg, to_place)
        if solved is None:
            return None
        from ..server.telemetry import metrics as _tm
        for sp in solved:
            if sp.node is not None:
                _tm.incr("nomad.scheduler.placements_tpu")
        return solved
