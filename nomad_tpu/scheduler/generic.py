"""GenericScheduler: service and batch evaluation processing.

Semantic parity with /root/reference/scheduler/generic_sched.go
(Process :149, process :248, computeJobAllocs :364, computePlacements :511)
and scheduler.go (Scheduler/State/Planner interfaces :59-151, Factory :27).
"""
from __future__ import annotations

import time as _time
from typing import Dict, List, Optional, Set

from .. import native as _native
from ..structs import (
    AllocatedResources, AllocatedSharedResources, Allocation, AllocMetric,
    Evaluation, Job, LazyAllocMetric,
    Plan, PlanResult, RescheduleEvent, RescheduleTracker, generate_uuid,
    ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST, ALLOC_DESIRED_RUN,
    ALLOC_DESIRED_STOP, EVAL_STATUS_BLOCKED, EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED, EVAL_STATUS_PENDING, JOB_TYPE_BATCH, JOB_TYPE_SERVICE,
    NODE_STATUS_DOWN, TRIGGER_ALLOC_STOP, TRIGGER_DEPLOYMENT_WATCHER,
    TRIGGER_JOB_DEREGISTER, TRIGGER_JOB_REGISTER, TRIGGER_MAX_DISCONNECT_TIMEOUT,
    TRIGGER_NODE_DRAIN, TRIGGER_NODE_UPDATE, TRIGGER_PERIODIC_JOB,
    TRIGGER_QUEUED_ALLOCS, TRIGGER_RECONNECT, TRIGGER_RETRY_FAILED_ALLOC,
    TRIGGER_ROLLING_UPDATE, TRIGGER_FAILED_FOLLOW_UP, TRIGGER_SCALING,
)
from .context import EvalContext
from .reconcile import (
    ALLOC_RESCHEDULED, AllocPlaceResult, AllocReconciler, ReconcileResults,
)
from .stack import GenericStack, SelectOptions
from .util import progress_made, tainted_nodes

MAX_SERVICE_SCHEDULE_ATTEMPTS = 5
MAX_BATCH_SCHEDULE_ATTEMPTS = 2

BLOCKED_EVAL_MAX_PLAN = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS = "created to place remaining allocations"


class SetStatusError(Exception):
    """Terminal scheduling failure that still sets eval status
    (reference: generic_sched.go SetStatusError)."""

    def __init__(self, msg: str, status: str = EVAL_STATUS_FAILED):
        super().__init__(msg)
        self.eval_status = status


class GenericScheduler:
    """(reference: generic_sched.go:101 GenericScheduler)"""

    def __init__(self, state, planner, batch: bool = False, logger=None,
                 solve_hook=None):
        self.state = state
        self.planner = planner
        self.batch = batch
        self.logger = logger
        # Batched-dispatch rendezvous (solver/batch.py make_solve_hook):
        # when set, dense solves route through the coordinator so many
        # evals fuse into one device dispatch. None = solo dispatch.
        self.solve_hook = solve_hook

        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.plan_result: Optional[PlanResult] = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[GenericStack] = None
        self.deployment = None

        self.base_nodes: List = []
        self.blocked: Optional[Evaluation] = None
        self.failed_tg_allocs: Dict[str, object] = {}
        self.queued_allocs: Dict[str, int] = {}
        self.followup_evals: Dict[str, List[Evaluation]] = {}

    # ------------------------------------------------------------------
    def process(self, evaluation: Evaluation):
        """Entry point (reference: generic_sched.go:149 Process)."""
        self.eval = evaluation

        ok_triggers = {
            TRIGGER_JOB_REGISTER, TRIGGER_JOB_DEREGISTER, TRIGGER_NODE_DRAIN,
            TRIGGER_NODE_UPDATE, TRIGGER_ALLOC_STOP, TRIGGER_ROLLING_UPDATE,
            TRIGGER_QUEUED_ALLOCS, TRIGGER_DEPLOYMENT_WATCHER,
            TRIGGER_RETRY_FAILED_ALLOC, TRIGGER_FAILED_FOLLOW_UP,
            TRIGGER_MAX_DISCONNECT_TIMEOUT, TRIGGER_RECONNECT,
            TRIGGER_PERIODIC_JOB, TRIGGER_SCALING, "job-scaling",
        }
        if evaluation.triggered_by not in ok_triggers:
            desc = f"scheduler cannot handle '{evaluation.triggered_by}' evaluation"
            self.planner.update_eval(self._eval_with_status(
                EVAL_STATUS_FAILED, desc))
            return None

        limit = (MAX_BATCH_SCHEDULE_ATTEMPTS if self.batch
                 else MAX_SERVICE_SCHEDULE_ATTEMPTS)
        attempts = 0
        err: Optional[Exception] = None
        while attempts < limit:
            try:
                done = self._process_once()
            except SetStatusError as e:
                self.planner.update_eval(self._eval_with_status(
                    e.eval_status, str(e)))
                return e
            if done:
                err = None
                break
            if progress_made(self.plan_result):
                attempts = 0
            else:
                attempts += 1
            if attempts >= limit:
                err = SetStatusError(
                    f"maximum attempts reached ({limit})")
        if err is not None:
            self.planner.update_eval(self._eval_with_status(
                EVAL_STATUS_FAILED, str(err)))
            return err

        self.planner.update_eval(self._eval_with_status(
            EVAL_STATUS_COMPLETE, ""))
        return None

    def _eval_with_status(self, status: str, desc: str) -> Evaluation:
        ev = self.eval.copy()
        ev.status = status
        ev.status_description = desc
        if self.blocked is not None:
            ev.blocked_eval = self.blocked.id
        ev.failed_tg_allocs = dict(self.failed_tg_allocs)
        ev.queued_allocations = dict(self.queued_allocs)
        return ev

    # ------------------------------------------------------------------
    def _process_once(self) -> bool:
        """(reference: generic_sched.go:248 process) Returns True when the
        plan fully committed (or was a no-op)."""
        self.blocked = None
        self.failed_tg_allocs = {}

        ns, job_id = self.eval.namespace, self.eval.job_id
        self.job = self.state.job_by_id(ns, job_id)
        num_tainted = 0

        self.plan = Plan(
            eval_id=self.eval.id,
            priority=self.eval.priority,
            job=self.job,
            all_at_once=self.job.all_at_once if self.job else False,
        )
        self.ctx = EvalContext(self.state, self.plan, self.logger)
        self.stack = GenericStack(self.batch, self.ctx)
        if self.job is not None and not self.job.stopped():
            if hasattr(self.state, "scheduler_config"):
                self.stack.set_scheduler_configuration(
                    self.state.scheduler_config())
            self.stack.set_job(self.job)
            # datacenter filter (reference: readyNodesInDCsAndPool),
            # memoized on the snapshot so a barrier generation's evals
            # share one ready list (and its pack key) instead of each
            # paying the O(N) scan; treat the shared list as read-only
            get_dcs = getattr(self.state, "ready_nodes_in_pool_dcs", None)
            dcs = frozenset(self.job.datacenters)
            if get_dcs is not None:
                nodes = get_dcs(self.job.node_pool, dcs)
            else:
                nodes = self.state.ready_nodes_in_pool(self.job.node_pool)
                if "*" not in dcs:
                    nodes = [n for n in nodes if n.datacenter in dcs]
            self.base_nodes = nodes         # pre-shuffle order, for the solver
            self.stack.set_nodes(nodes)
            self.ctx.metrics.nodes_in_pool = len(nodes)

        if not self._compute_job_allocs():
            return False

        # Queued allocations accounting for annotations
        return self._finish_plan()

    def _compute_job_allocs(self) -> bool:
        """(reference: generic_sched.go:364 computeJobAllocs)"""
        ns, job_id = self.eval.namespace, self.eval.job_id
        allocs = self.state.allocs_by_job(ns, job_id)
        tainted = tainted_nodes(self.state, allocs)

        # node-update evals mark running allocs on down nodes lost
        # (reference: generic_sched.go:382 updateNonTerminalAllocsToLost)
        reconciler = AllocReconciler(
            batch=self.batch,
            job_id=job_id,
            job=self.job if (self.job and not self.job.stopped()) else None,
            deployment=self.state.latest_deployment_by_job(ns, job_id),
            existing_allocs=allocs,
            tainted_nodes=tainted,
            eval_id=self.eval.id,
            eval_priority=self.eval.priority,
        )
        results = reconciler.compute()
        self.followup_evals = results.desired_followup_evals
        # the deployment placements attach to: existing-and-active or newly
        # created by the reconciler (reference: generic_sched.go s.deployment)
        self.deployment = reconciler.deployment

        if results.deployment is not None:
            self.plan.deployment = results.deployment
        self.plan.deployment_updates = list(results.deployment_updates)

        # Stops
        for stop in results.stop:
            self.plan.append_stopped_alloc(
                stop.alloc, stop.status_description, stop.client_status,
                stop.followup_eval_id)

        # Disconnect/reconnect attribute updates ride the plan as allocs
        for alloc in results.disconnect_updates.values():
            self.plan.append_alloc(alloc)
        for alloc in results.reconnect_updates.values():
            self.plan.append_alloc(alloc)

        # In-place updates
        for alloc in results.inplace_update:
            self.plan.append_alloc(alloc)

        # Followup evals must exist before failed allocs reference them
        for evals in self.followup_evals.values():
            for ev in evals:
                self.planner.create_eval(ev)

        # Queued per TG
        self.queued_allocs = {
            tg: du.place + du.destructive_update
            for tg, du in results.desired_tg_updates.items()}

        # Destructive updates: stop + place
        destructive_places: List[AllocPlaceResult] = []
        for d in results.destructive_update:
            self.plan.append_stopped_alloc(
                d.stop_alloc, d.stop_status_description)
            destructive_places.append(AllocPlaceResult(
                name=d.place_name, task_group=d.place_task_group,
                previous_alloc=d.stop_alloc))

        if self.job is None or self.job.stopped():
            return True

        return self._compute_placements(
            results.place + destructive_places)

    def _compute_placements(self, places: List[AllocPlaceResult]) -> bool:
        """(reference: generic_sched.go:511 computePlacements)

        When SchedulerConfiguration selects a tpu-* algorithm, whole
        task-group batches are solved in one dense dispatch on the
        accelerator (nomad_tpu/solver/); anything the dense path does not
        model falls back to the host iterator stack per placement."""
        from ..server.tracing import tracer

        tpu_alg = self._tpu_algorithm()
        if tpu_alg:
            places = self._compute_placements_tpu(places)
            if not places:
                if self.failed_tg_allocs and not self.batch:
                    self._queue_blocked_eval()
                return True

        deployment_id = self._deployment_id()

        if places:
            with tracer.span("sched.feasibility_rank",
                             places=len(places), tpu_carveout=tpu_alg):
                self._place_host(places, deployment_id, tpu_alg)

        # Any failures -> blocked eval for the remainder (service only)
        if self.failed_tg_allocs and not self.batch:
            self._queue_blocked_eval()
        return True

    def _place_host(self, places: List[AllocPlaceResult],
                    deployment_id: str, tpu_alg: bool) -> None:
        """Host iterator-stack placement loop (the per-place
        feasibility/rank path the reference runs for everything)."""
        for place in places:
            tg = place.task_group
            # Penalty node: previous alloc's node when rescheduling
            penalty: Set[str] = set()
            preferred = []
            prev = place.previous_alloc
            if prev is not None:
                if place.reschedule:
                    penalty.add(prev.node_id)
                if (tg.ephemeral_disk.sticky and not place.previous_lost):
                    node = self.state.node_by_id(prev.node_id)
                    # Only steer back to a node still accepting work
                    # (reference: generic_sched.go:889 preferredNode.Ready())
                    if node is not None and node.ready():
                        preferred = [node]

            option = self.stack.select(tg, SelectOptions(
                penalty_node_ids=penalty,
                preferred_nodes=preferred,
                alloc_name=place.name,
                preempt=self._preemption_enabled()))

            if option is None:
                # Failed placement: record metrics, coalesce
                if tg.name in self.failed_tg_allocs:
                    self.failed_tg_allocs[tg.name].coalesced_failures += 1
                else:
                    self.failed_tg_allocs[tg.name] = self.ctx.metrics.copy()
                continue

            # TPU-vs-host placement ratio: make solver carve-outs visible
            # (VERDICT r1 weak #4 -- silent fallbacks)
            from ..server.telemetry import metrics as _tm
            _tm.incr("nomad.scheduler.placements_host_fallback" if tpu_alg
                     else "nomad.scheduler.placements_host")

            resources = AllocatedResources(
                tasks=dict(option.task_resources),
                shared=option.alloc_resources
                if option.alloc_resources is not None
                else AllocatedSharedResources(
                    disk_mb=tg.ephemeral_disk.size_mb))

            alloc = Allocation(
                id=generate_uuid(),
                namespace=self.job.namespace,
                eval_id=self.eval.id,
                name=place.name,
                job_id=self.job.id,
                job=self.job,
                job_version=self.job.version,
                task_group=tg.name,
                node_id=option.node.id,
                node_name=option.node.name,
                deployment_id=deployment_id,
                allocated_resources=resources,
                desired_status=ALLOC_DESIRED_RUN,
                client_status="pending",
                metrics=self.ctx.metrics.copy(),
            )
            if place.canary:
                from ..structs import AllocDeploymentStatus
                alloc.deployment_status = AllocDeploymentStatus(canary=True)
            if prev is not None:
                alloc.previous_allocation = prev.id
                if place.reschedule:
                    tracker = RescheduleTracker()
                    if prev.reschedule_tracker is not None:
                        tracker.events = list(prev.reschedule_tracker.events)
                    tracker.events.append(RescheduleEvent(
                        reschedule_time=_time.time(),
                        prev_alloc_id=prev.id,
                        prev_node_id=prev.node_id))
                    alloc.reschedule_tracker = tracker

            if option.preempted_allocs:
                for p in option.preempted_allocs:
                    self.plan.append_preempted_alloc(p, alloc.id)

            self.plan.append_alloc(alloc)

    def _deployment_id(self) -> str:
        """Placements attach to the active deployment of the CURRENT job
        version (reference: generic_sched.go computePlacements
        deploymentID)."""
        d = self.deployment if self.deployment is not None \
            else self.plan.deployment
        if (d is not None and d.active() and self.job is not None
                and d.job_version == self.job.version):
            return d.id
        return ""

    def _tpu_algorithm(self) -> bool:
        if not hasattr(self.state, "scheduler_config"):
            return False
        cfg = self.state.scheduler_config()
        if cfg is None or not cfg.uses_tpu():
            return False
        # a wedged accelerator runtime must not strand worker threads:
        # degrade to the host oracle when backend init is down OR the
        # dispatch circuit breaker is open (solver/guard.py)
        from ..solver.guard import dispatch_allowed, note_host_fallback
        if not dispatch_allowed():
            note_host_fallback()
            return False
        return True

    def _compute_placements_tpu(self, places: List[AllocPlaceResult]
                                ) -> List[AllocPlaceResult]:
        """Solve eligible TG batches densely; returns the places the solver
        could NOT handle (devices/cores/sticky-disk/preemption) so the host
        path picks them up."""
        from ..solver.service import TpuPlacementService, tg_solver_eligible
        from ..structs import SCHED_ALG_TPU_SPREAD

        cfg = self.state.scheduler_config()
        spread_alg = cfg.scheduler_algorithm == SCHED_ALG_TPU_SPREAD

        groups: Dict[str, List[AllocPlaceResult]] = {}
        order: List[str] = []
        for place in places:
            if place.task_group.name not in groups:
                order.append(place.task_group.name)
            groups.setdefault(place.task_group.name, []).append(place)

        deployment_id = self._deployment_id()

        fallback: List[AllocPlaceResult] = []
        service = TpuPlacementService(
            self.ctx, self.job, self.batch, spread_alg,
            preempt=self._preemption_enabled())
        # the solver derives the same shuffle the stack applied from the
        # eval id, so hand it the pre-shuffle base ordering
        base_nodes = getattr(self, "base_nodes", None) or \
            self.state.ready_nodes_in_pool(self.job.node_pool)

        from ..server.tracing import tracer

        for tg_name in order:
            tg_places = groups[tg_name]
            tg = tg_places[0].task_group
            sticky = tg.ephemeral_disk.sticky and any(
                p.previous_alloc is not None for p in tg_places)
            if (sticky or not tg_solver_eligible(
                    tg, self.job, preempt=self._preemption_enabled())):
                fallback.extend(tg_places)
                continue
            penalties = [
                {p.previous_alloc.node_id} if (p.reschedule and
                                               p.previous_alloc) else set()
                for p in tg_places]
            with tracer.span("solver.solve_tg", tg=tg_name,
                             places=len(tg_places),
                             batched=self.solve_hook is not None) as _sp:
                if self.solve_hook is not None:
                    solved = self.solve_hook(service, tg, tg_places,
                                             base_nodes, penalties)
                else:
                    solved = service.solve(tg, tg_places, base_nodes,
                                           penalties)
                _sp.tag(host_fallback=solved is None)
            if solved is None:
                fallback.extend(tg_places)
                continue
            n_solved = 0
            for sp in solved:
                if sp.node is None:
                    if tg.name in self.failed_tg_allocs:
                        self.failed_tg_allocs[tg.name].coalesced_failures += 1
                    else:
                        m = self.ctx.metrics.copy()
                        m.nodes_evaluated = sp.n_yielded
                        self.failed_tg_allocs[tg.name] = m
                    continue
                self._append_solved_alloc(sp, deployment_id)
                n_solved += 1
            if n_solved:
                # one counter bump per TG batch, not per placement: the
                # per-alloc incr serialized 32 workers on the telemetry
                # lock at 64K placements/round (34% of thread-time)
                from ..server.telemetry import metrics as _tm
                _tm.incr("nomad.scheduler.placements_tpu", n_solved)
                import os as _os
                if _native.native_cp_enabled():
                    if _os.environ.get(
                            "NOMAD_TPU_LEAN_ALLOC_METRICS", "") == "1":
                        # lean stubs preempt the lazy path: count them
                        # as materialize fallbacks so the runbook's
                        # hits/fallbacks split stays truthful
                        _tm.incr("nomad.native.materialize_fallbacks",
                                 n_solved)
                    else:
                        _tm.incr("nomad.native.materialize_hits",
                                 n_solved)
        return fallback

    def _append_solved_alloc(self, sp, deployment_id: str) -> None:
        place = sp.place
        tg = place.task_group
        resources = getattr(sp, "resources_prebuilt", None)
        if resources is None:
            resources = AllocatedResources(
                tasks=sp.task_resources,
                shared=sp.alloc_resources
                if sp.alloc_resources is not None
                else AllocatedSharedResources(
                    disk_mb=tg.ephemeral_disk.size_mb))
        import os as _os
        lazy = False
        if _os.environ.get("NOMAD_TPU_LEAN_ALLOC_METRICS", "") == "1":
            # pruned stub for north-star-scale runs: the full per-
            # placement AllocMetric copy is ~10 container objects and
            # ~15us apiece -- at 2M live allocs that is GBs of resident
            # explainability detail. The lean stub keeps the fields
            # `alloc status` renders headline numbers from; placements
            # are identical either way (metrics are explanatory only).
            metrics = AllocMetric(nodes_evaluated=sp.n_yielded,
                                  nodes_in_pool=self.ctx.metrics
                                  .nodes_in_pool)
        elif _native.native_cp_enabled():
            # native control plane (ISSUE 17): defer the per-placement
            # AllocMetric build to first struct access -- the batch
            # path's object + dict churn was a profiled slice of the
            # per-eval fixed cost. Placements are identical either way
            # (metrics are explanatory only); hydration reproduces the
            # eager copy_for_alloc content from the same shared base.
            lazy = True
            preempt_score = None
            if sp.preempted_allocs:
                from .rank import net_priority, preemption_score as _ps
                preempt_score = _ps(net_priority(sp.preempted_allocs))
            metrics = LazyAllocMetric(self.ctx.metrics, sp.node.id,
                                      sp.score, sp.n_yielded,
                                      preempt_score)
        else:
            metrics = self.ctx.metrics.copy_for_alloc()
            metrics.nodes_evaluated = sp.n_yielded
        if not lazy:
            metrics.score_node(sp.node.id, "normalized-score", sp.score)
            if sp.preempted_allocs:
                # same component the host records (rank.py:575
                # PreemptionScoringIterator ->
                # preemption_score(net_priority))
                from .rank import net_priority, preemption_score
                metrics.score_node(
                    sp.node.id, "preemption",
                    preemption_score(net_priority(sp.preempted_allocs)))
        alloc = Allocation(
            id=generate_uuid(),
            namespace=self.job.namespace,
            eval_id=self.eval.id,
            name=place.name,
            job_id=self.job.id,
            job=self.job,
            job_version=self.job.version,
            task_group=tg.name,
            node_id=sp.node.id,
            node_name=sp.node.name,
            deployment_id=deployment_id,
            allocated_resources=resources,
            desired_status=ALLOC_DESIRED_RUN,
            client_status="pending",
            metrics=metrics,
        )
        if place.canary:
            from ..structs import AllocDeploymentStatus
            alloc.deployment_status = AllocDeploymentStatus(canary=True)
        prev = place.previous_alloc
        if prev is not None:
            alloc.previous_allocation = prev.id
            if place.reschedule:
                tracker = RescheduleTracker()
                if prev.reschedule_tracker is not None:
                    tracker.events = list(prev.reschedule_tracker.events)
                tracker.events.append(RescheduleEvent(
                    reschedule_time=_time.time(),
                    prev_alloc_id=prev.id,
                    prev_node_id=prev.node_id))
                alloc.reschedule_tracker = tracker
        if sp.preempted_allocs:
            for p in sp.preempted_allocs:
                self.plan.append_preempted_alloc(p, alloc.id)
        self.plan.append_alloc(alloc)

    def _preemption_enabled(self) -> bool:
        cfg = (self.state.scheduler_config()
               if hasattr(self.state, "scheduler_config") else None)
        if cfg is None:
            return False
        sched_type = JOB_TYPE_BATCH if self.batch else JOB_TYPE_SERVICE
        return cfg.preemption_config.is_enabled(sched_type)

    def _queue_blocked_eval(self) -> None:
        """(reference: generic_sched.go:300 + blocked eval creation)"""
        if self.blocked is not None:
            return
        elig = self.ctx.eligibility()
        blocked = Evaluation(
            id=generate_uuid(),
            namespace=self.eval.namespace,
            priority=self.eval.priority,
            type=self.eval.type,
            triggered_by=TRIGGER_QUEUED_ALLOCS,
            job_id=self.eval.job_id,
            status=EVAL_STATUS_BLOCKED,
            previous_eval=self.eval.id,
            class_eligibility=elig.class_eligibility(),
            escaped_computed_class=elig.has_escaped(),
        )
        self.blocked = blocked
        self.planner.create_eval(blocked)

    def _finish_plan(self) -> bool:
        if self.plan.is_no_op():
            self.plan_result = None
            return True
        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result
        if result is None:
            return False
        # Decrement queued allocations by what actually committed
        # (reference: generic_sched.go:339 adjustQueuedAllocations)
        for allocs in result.node_allocation.values():
            for alloc in allocs:
                if alloc.task_group in self.queued_allocs:
                    self.queued_allocs[alloc.task_group] -= 1
        full, expected, actual = result.full_commit(self.plan)
        if not full:
            if new_state is not None:
                self.state = new_state
            return False
        return True
