"""Scheduler factory registry (reference:
/root/reference/scheduler/scheduler.go:27-49 Factory + BuiltinSchedulers).

The TPU solver registers here too: scheduler type names stay {service,
batch, system, sysbatch}; the *algorithm* (binpack/spread/tpu-binpack/
tpu-spread) is a SchedulerConfiguration concern read by the stack
(reference: stack.go:292, rank.go:192)."""
from __future__ import annotations

from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}


def register_scheduler(name: str, factory: Callable) -> None:
    _REGISTRY[name] = factory


def new_scheduler(name: str, state, planner, **kwargs):
    factory = _REGISTRY.get(name)
    if factory is None:
        raise ValueError(f"unknown scheduler '{name}'")
    return factory(state, planner, **kwargs)


def registered_schedulers():
    return sorted(_REGISTRY)


def _register_builtins() -> None:
    from .generic import GenericScheduler
    from .system import SystemScheduler
    register_scheduler(
        "service", lambda state, planner, **kw:
        GenericScheduler(state, planner, batch=False, **kw))
    register_scheduler(
        "batch", lambda state, planner, **kw:
        GenericScheduler(state, planner, batch=True, **kw))
    # the whole-queue LP-relaxation tier (ISSUE 8): reference semantics
    # are unchanged (stock GenericScheduler per eval); the tier differs
    # only in its solve hook, which rendezvouses the coalesced queue at
    # solver/lpq.py's LpqBarrier instead of the greedy SolveBarrier.
    # The LPQ worker selects this entry when NOMAD_TPU_LPQ is live and
    # SchedulerConfiguration picks the tpu-lpq algorithm.
    register_scheduler(
        "tpu-lpq", lambda state, planner, batch=False, **kw:
        GenericScheduler(state, planner, batch=batch, **kw))
    register_scheduler(
        "system", lambda state, planner, **kw:
        SystemScheduler(state, planner, sysbatch=False, **kw))
    register_scheduler(
        "sysbatch", lambda state, planner, **kw:
        SystemScheduler(state, planner, sysbatch=True, **kw))


_register_builtins()
