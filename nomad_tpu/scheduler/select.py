"""Selection iterators (reference: /root/reference/scheduler/select.go plus
the limit/max-score constants at stack.go:13-20)."""
from __future__ import annotations

from typing import List, Optional

from .rank import RankedNode, RankIterator

SKIP_SCORE_THRESHOLD = 0.0
MAX_SKIP = 3


class LimitIterator(RankIterator):
    """Yields at most `limit` options, skipping up to MAX_SKIP options whose
    score is <= SKIP_SCORE_THRESHOLD (reference: select.go LimitIterator)."""

    def __init__(self, ctx, source: RankIterator, limit: int = 1,
                 skip_threshold: float = SKIP_SCORE_THRESHOLD,
                 max_skip: int = MAX_SKIP):
        self.ctx = ctx
        self.source = source
        self.limit = limit
        self.skip_threshold = skip_threshold
        self.max_skip = max_skip
        self.seen = 0
        self.skipped_nodes: List[RankedNode] = []
        self.skipped_index = 0

    def set_limit(self, limit: int) -> None:
        self.limit = limit

    def _next_option(self) -> Optional[RankedNode]:
        """Fall back to previously-skipped nodes once the source runs dry
        (reference: select.go:62 nextOption)."""
        option = self.source.next()
        if option is None and self.skipped_index < len(self.skipped_nodes):
            option = self.skipped_nodes[self.skipped_index]
            self.skipped_index += 1
        return option

    def next(self) -> Optional[RankedNode]:
        if self.seen == self.limit:
            return None
        option = self._next_option()
        if option is None:
            return None
        if len(self.skipped_nodes) < self.max_skip:
            while (option is not None
                   and option.final_score <= self.skip_threshold
                   and len(self.skipped_nodes) < self.max_skip):
                self.skipped_nodes.append(option)
                option = self.source.next()
        self.seen += 1
        if option is None:
            return self._next_option()
        return option

    def reset(self) -> None:
        self.source.reset()
        self.seen = 0
        self.skipped_nodes = []
        self.skipped_index = 0


class MaxScoreIterator(RankIterator):
    """Consumes the chain and returns the single best option
    (reference: select.go MaxScoreIterator)."""

    def __init__(self, ctx, source: RankIterator):
        self.ctx = ctx
        self.source = source
        self.max_option: Optional[RankedNode] = None

    def next(self) -> Optional[RankedNode]:
        if self.max_option is not None:
            return None
        best: Optional[RankedNode] = None
        while True:
            option = self.source.next()
            if option is None:
                break
            if best is None or option.final_score > best.final_score:
                best = option
        self.max_option = best
        return best

    def reset(self) -> None:
        self.source.reset()
        self.max_option = None
