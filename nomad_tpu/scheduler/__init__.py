"""Host-side reference-path scheduler -- the parity oracle
(reference: /root/reference/scheduler/)."""
from .context import EvalContext, EvalEligibility  # noqa: F401
from .factory import new_scheduler, register_scheduler, registered_schedulers  # noqa: F401
from .generic import GenericScheduler, SetStatusError  # noqa: F401
from .harness import Harness  # noqa: F401
from .rank import BinPackIterator, RankedNode  # noqa: F401
from .reconcile import AllocReconciler, ReconcileResults, tasks_updated  # noqa: F401
from .stack import GenericStack, SelectOptions, SystemStack  # noqa: F401
from .system import SystemScheduler  # noqa: F401
from .util import shuffle_nodes, shuffled_order, tainted_nodes  # noqa: F401
