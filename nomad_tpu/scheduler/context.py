"""Per-evaluation context (reference: /root/reference/scheduler/context.go).

Carries the plan under construction, metrics, compiled-regex/version caches,
and the computed-node-class eligibility cache that lets feasibility checks
skip whole equivalence classes of nodes (reference: context.go:261
EvalEligibility -- the key trick for 10K-node clusters, kept here because
the host oracle still runs per-node; the TPU path instead materializes the
full node axis).
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional

from ..structs import Allocation, AllocMetric, Job, Plan, TaskGroup

# Eligibility states (reference: context.go)
ELIGIBILITY_UNKNOWN = 0
ELIGIBILITY_ELIGIBLE = 1
ELIGIBILITY_INELIGIBLE = 2
ELIGIBILITY_ESCAPED = 3  # constraint references unique attrs; no class caching


class EvalEligibility:
    """Tracks job/taskgroup feasibility per computed node class
    (reference: context.go:261)."""

    def __init__(self) -> None:
        self.job: Dict[str, int] = {}
        self.job_escaped = False
        self.tg: Dict[str, Dict[str, int]] = {}
        self.tg_escaped: Dict[str, bool] = {}
        self.quota_reached = ""

    @staticmethod
    def _escaped(constraints) -> bool:
        for c in constraints:
            for t in (c.l_target, c.r_target):
                if "${node.unique." in t or "${attr.unique." in t or "${meta.unique." in t:
                    return True
            if c.operand in ("distinct_hosts", "distinct_property"):
                return True
        return False

    def set_job(self, job: Job) -> None:
        self.job_escaped = self._escaped(job.constraints)
        for tg in job.task_groups:
            constraints = list(tg.constraints)
            for t in tg.tasks:
                constraints.extend(t.constraints)
            self.tg_escaped[tg.name] = self._escaped(constraints)

    def has_escaped(self) -> bool:
        return self.job_escaped or any(self.tg_escaped.values())

    def job_status(self, node_class: str) -> int:
        if self.job_escaped or not node_class:
            return ELIGIBILITY_ESCAPED
        return self.job.get(node_class, ELIGIBILITY_UNKNOWN)

    def set_job_eligibility(self, eligible: bool, node_class: str) -> None:
        if node_class:
            self.job[node_class] = (
                ELIGIBILITY_ELIGIBLE if eligible else ELIGIBILITY_INELIGIBLE)

    def task_group_status(self, tg_name: str, node_class: str) -> int:
        if self.tg_escaped.get(tg_name, False) or not node_class:
            return ELIGIBILITY_ESCAPED
        return self.tg.get(tg_name, {}).get(node_class, ELIGIBILITY_UNKNOWN)

    def set_task_group_eligibility(self, eligible: bool, tg_name: str,
                                   node_class: str) -> None:
        if node_class:
            self.tg.setdefault(tg_name, {})[node_class] = (
                ELIGIBILITY_ELIGIBLE if eligible else ELIGIBILITY_INELIGIBLE)

    def class_eligibility(self) -> Dict[str, bool]:
        """Export for blocked evals (class-keyed unblocking, reference:
        context.go:325 GetClasses + blocked_evals.go:46-50): a class is
        eligible only if no job- or TG-level check marked it ineligible;
        any ineligible mark wins over eligible marks."""
        out: Dict[str, bool] = {}
        for cls, st in self.job.items():
            if st == ELIGIBILITY_ELIGIBLE:
                out.setdefault(cls, True)
            elif st == ELIGIBILITY_INELIGIBLE:
                out[cls] = False
        for tgmap in self.tg.values():
            for cls, st in tgmap.items():
                if st == ELIGIBILITY_ELIGIBLE:
                    out.setdefault(cls, True)
                elif st == ELIGIBILITY_INELIGIBLE:
                    out[cls] = False
        return out


class EvalContext:
    """State handed through the iterator stack (reference: context.go:130)."""

    def __init__(self, state, plan: Plan, logger=None, events=None):
        self.state = state
        self.plan = plan
        self.logger = logger
        self.metrics = AllocMetric()
        self._eligibility: Optional[EvalEligibility] = None
        self._regex_cache: Dict[str, re.Pattern] = {}
        self._version_cache: Dict[str, object] = {}
        self.events: List[object] = events if events is not None else []

    def reset(self) -> None:
        self.metrics = AllocMetric()

    def eligibility(self) -> EvalEligibility:
        if self._eligibility is None:
            self._eligibility = EvalEligibility()
        return self._eligibility

    def regex(self, pattern: str) -> Optional[re.Pattern]:
        pat = self._regex_cache.get(pattern)
        if pat is None:
            try:
                pat = re.compile(pattern)
            except re.error:
                return None
            self._regex_cache[pattern] = pat
        return pat

    def send_event(self, event) -> None:
        self.events.append(event)

    def proposed_allocs(self, node_id: str) -> List[Allocation]:
        """Existing non-client-terminal allocs on the node, minus plan stops
        and preemptions, plus plan placements (reference: context.go:176
        EvalContext.ProposedAllocs). Preserves insertion order so the scan
        is deterministic (the reference materializes from a map; our
        deterministic order is a superset contract the TPU path shares)."""
        existing = self.state.allocs_by_node(node_id)

        removed = set()
        for a in self.plan.node_update.get(node_id, ()):
            removed.add(a.id)
        for a in self.plan.node_preemptions.get(node_id, ()):
            removed.add(a.id)

        by_id: Dict[str, Allocation] = {}
        for alloc in existing:
            if alloc.id in removed:
                continue
            if alloc.client_terminal_status():
                continue
            by_id[alloc.id] = alloc
        for alloc in self.plan.node_allocation.get(node_id, ()):
            by_id[alloc.id] = alloc
        return list(by_id.values())
