"""Device-dispatch discipline sanitizer ("jitcheck") for the solver.

The paper's core bet is that the scheduler inner loop runs as dense
jitted kernels; the repo now has a large jitted surface (binpack.py
fused/wave kernels, lpq.py's LP solve, the batch.py arena dispatch,
constcache, parallel/mesh.py) and -- until this module -- zero tooling
to catch the failure modes that silently destroy that bet.  Before the
ROADMAP-1 pjit/mesh refactor multiplies call sites and shape buckets,
this is the dispatch layer's analog of lockcheck.py (PR 9): a runtime
sanitizer that turns "the TPU path got slow" into a named report.

What it checks while enabled:

  * **steady-state retraces** -- every repo-constructed ``jax.jit``
    callable is wrapped to account traces per construction site, keyed
    by the call's abstract signature (leaf shapes/dtypes/weak-types +
    static args).  Tracing the SAME signature at the same site more
    than ``NOMAD_TPU_JITCHECK_WARMUP`` times means the compile cache
    was defeated (the classic bug: a fresh ``@jax.jit`` closure built
    per call), and the report carries the witness signature pair.  A
    NEW signature arriving after a site has gone steady (served a call
    from cache) is recorded as a ``late_trace`` -- report-only, since
    new shape buckets legitimately appear as a fleet grows.
  * **hot-path host syncs** -- ``jax.device_get``, explicit
    ``__array__``, ``.item()``, ``float()``/``int()``/``bool()`` on
    device values while inside a solver dispatch stage
    (``guard.run_dispatch`` marks the region), attributed to the
    enclosing PR-3 tracing span.  The designed one-fetch-per-dispatch
    sites wrap their fetch in ``with jitcheck.sanctioned_fetch():``;
    everything else is a violation.  (CPU-backend gap, documented: on
    the CPU backend ``np.asarray`` reads a jax array through the
    buffer protocol, which Python cannot intercept -- explicit fetch
    forms are still caught, and real accelerators have no buffer
    protocol so ``__array__`` fires there.)
  * **dtype drift** -- float64 leaves crossing a ``device_put`` or jit
    boundary while x64 is not deliberately enabled (on TPU f64 is
    emulated; a leaked float64 table silently doubles transfer and
    compute), plus weak-typed Python scalars passed as traced args
    (signature jitter -- each flip is a retrace waiting to happen).
  * **fingerprint-cache mutation** -- constcache fingerprint sources,
    pack-memo and usage-base arrays register here when cached; a
    sampled content re-hash detects writes after fingerprinting, and
    every registered memo array must keep ``writeable=False`` (the
    frozen-memo invariant nomadlint checks statically).

Kill-switch semantics mirror lockcheck: OFF by default,
``NOMAD_TPU_JITCHECK=0``/unset is a true no-op -- ``jax.jit``,
``jax.device_get/put`` and the array dunders are untouched and no
wrapper is observable anywhere.  ``NOMAD_TPU_JITCHECK=1`` at process
start (or ``enable()`` at runtime, how the conftest fixture runs the
dispatch-pipeline/lpq/solver-parity suites) installs the patches;
jits constructed before enable stay raw (documented gap, same as
lockcheck's pre-enable locks -- the module-level ``solve_placements``
partials are covered by nomadlint's ``no-callsite-jit`` rule instead).

State rides the usual surfaces: ``stats.jitcheck`` in
``/v1/agent/self``, ``operator jitcheck [--sites]`` CLI (exit 1 on
steady-state retraces), ``jitcheck.json`` in operator debug bundles,
``nomad.jitcheck.{retrace,host_sync,x64_leak,mutated_cache}``
counters, and ``jit_*`` fields in bench artifacts gated by
scripts/check_bench_regress.py.

Knobs: ``NOMAD_TPU_JITCHECK`` (off; ``1`` installs at import),
``NOMAD_TPU_JITCHECK_WARMUP`` (1: traces allowed per (site, sig)),
``NOMAD_TPU_JITCHECK_STACK`` (16: witness stack depth),
``NOMAD_TPU_JITCHECK_MAX`` (256: retained reports per class),
``NOMAD_TPU_JITCHECK_REHASH`` (32: fingerprinted arrays re-hashed per
state() read), ``NOMAD_TPU_JITCHECK_X64`` (auto: flag float64 only
when ``jax_enable_x64`` is off; ``1`` always, ``0`` never).
"""
from __future__ import annotations

import functools
import hashlib
import os
import sys
import threading
import traceback
from collections import OrderedDict
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SELF_FILE = os.path.abspath(__file__).rstrip("co")  # .pyc -> .py

_ACTIVE = False                  # module-global fast gate (one dict read)
_REAL: dict = {}                 # originals, captured at first enable

# checker-internal state; _slock is a leaf: nothing is acquired under
# it and no user code runs under it
_slock = threading.Lock()

_warmup = 1
_stack_depth = 16
_max_reports = 256
_rehash_n = 32
_x64_flag = False                # resolved at enable() from _X64 knob

_SIG_CAP = 512                   # distinct signatures retained per site

# site -> {"calls", "traces", "steady", "jits", "sigs": {sig: {...}}}
_sites: "OrderedDict[str, dict]" = OrderedDict()
_retraces: List[dict] = []
_retrace_keys: Dict[tuple, dict] = {}
_late_traces: List[dict] = []
_late_keys: set = set()
_host_syncs: List[dict] = []
_host_sync_keys: Dict[tuple, dict] = {}
_dtype_drift: List[dict] = []
_dtype_keys: set = set()
_mutations: List[dict] = []
_mutation_keys: set = set()
# id(arr) -> (arr, digest, site). numpy arrays are not weakref-able,
# so the registries hold STRONG refs under a byte budget (FIFO): an
# opt-in sanitizer pinning a bounded sample of cached arrays is the
# price of being able to re-hash them later.
_fps: "OrderedDict[int, tuple]" = OrderedDict()
_frozen: "OrderedDict[int, tuple]" = OrderedDict()
_FPS_CAP = 1024
_FPS_MAX_BYTES = 64 * 1024 * 1024
_fps_bytes = [0, 0]              # [fingerprint bytes, frozen bytes]
_rehash_cursor = [0]
_counters = {"jits": 0, "calls": 0, "traces": 0, "retraces": 0,
             "host_syncs": 0, "sanctioned_fetches": 0, "x64_leaks": 0,
             "weak_scalars": 0, "mutations": 0, "reports_dropped": 0,
             "sigs_dropped": 0}
# sanctioned-fetch counts by ledger tag (the fetch-accounted tags the
# xferobs fetch decomposition uses)
_sanct_tags: Dict[str, int] = {}

_tls = threading.local()


def _tls_state():
    st = getattr(_tls, "st", None)
    if st is None:
        st = _tls.st = {"hot": 0, "sanct": 0, "label": "",
                        "calls": []}
    return st


def _rel(path: str) -> str:
    if path.startswith(_REPO_ROOT):
        return path[len(_REPO_ROOT) + 1:]
    return path


def _metrics():
    """Telemetry sink, or None mid-teardown -- the sanitizer must
    never take the process down with it."""
    try:
        from .server.telemetry import metrics
        return metrics
    except Exception:  # noqa: BLE001
        return None


def _span_ids() -> str:
    """The enclosing PR-3 tracing span's eval ids (host-sync
    attribution), or '-' outside any traced context."""
    try:
        from .server.tracing import tracer
        return ",".join(tracer.current_ids()) or "-"
    except Exception:  # noqa: BLE001
        return "-"


def _repo_site(skip_self: bool = True) -> Optional[str]:
    """First repo frame outside this module, as 'rel/path.py:line'."""
    f = sys._getframe(2)
    for _ in range(16):
        if f is None:
            return None
        fn = f.f_code.co_filename
        if fn.startswith(_REPO_ROOT) and not (
                skip_self and os.path.abspath(fn) == _SELF_FILE):
            return f"{_rel(fn)}:{f.f_lineno}"
        f = f.f_back
    return None


def _fmt_stack(limit: Optional[int] = None) -> str:
    try:
        return "".join(traceback.format_stack(
            sys._getframe(2), limit=limit or _stack_depth))
    except Exception:  # noqa: BLE001 -- diagnostics must never raise
        return "<stack unavailable>"


# ----------------------------------------------------------------------
# abstract signatures + dtype drift

import re as _re

_ADDR_RE = _re.compile(r"0x[0-9a-f]+")


def _describe_static(v, depth: int = 0):
    """Address-free structural description of a wrapped function's
    static closure (partials' keywords, nested closures, constants).
    Two jit callables built at one factory line for DIFFERENT static
    variants (spread_alg/dtype_name/B buckets) describe differently,
    so their one-trace-each does not read as a retrace; the nested-jit
    bug (a fresh but IDENTICAL closure per call) describes identically
    every time, so its re-traces still aggregate and trip the gate."""
    if depth > 4:
        return "..."
    if isinstance(v, functools.partial):
        return ("partial", _describe_static(v.func, depth + 1),
                tuple(_describe_static(a, depth + 1) for a in v.args),
                tuple(sorted(
                    (k, _describe_static(x, depth + 1))
                    for k, x in (v.keywords or {}).items())))
    if isinstance(v, (bool, int, float, str, bytes, type(None))):
        return v
    if callable(v):
        cells = []
        for cell in (getattr(v, "__closure__", None) or ()):
            try:
                cells.append(_describe_static(cell.cell_contents,
                                              depth + 1))
            except ValueError:
                cells.append("<empty>")
        code = getattr(v, "__code__", None)
        name = (code.co_name if code is not None
                else getattr(v, "__name__", "?"))
        return ("fn", name, tuple(cells))
    try:
        return _ADDR_RE.sub("@", repr(v))[:200]
    except Exception:  # noqa: BLE001 -- exotic closure contents
        return type(v).__name__


def _abstract_sig(args, kwargs) -> str:
    """Value-independent abstract signature of one jit call: leaf
    shapes/dtypes (weak-typed leaves marked '~'), static-looking
    scalars by value (bool/str) or by kind (int/float -- traced weak
    scalars are value-independent)."""
    import jax

    parts = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs)):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            weak = "~" if getattr(leaf, "weak_type", False) else ""
            parts.append(f"{weak}{dtype}{tuple(shape)}")
        elif isinstance(leaf, (bool, str)):
            parts.append(repr(leaf))
        elif isinstance(leaf, int):
            parts.append("int")
        elif isinstance(leaf, float):
            parts.append("float")
        else:
            parts.append(type(leaf).__name__)
    return "(" + ", ".join(parts) + ")"


def _note_dtype_drift(site: Optional[str], tree, where: str) -> None:
    """float64 leaves crossing a device boundary (+ weak Python-scalar
    traced args at jit boundaries). Deduped per (site, kind, where)."""
    import jax

    f64 = weak = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        dtype = getattr(leaf, "dtype", None)
        if dtype is not None and str(dtype) in ("float64", "complex128"):
            f64 += 1
        elif isinstance(leaf, float) and where == "jit":
            weak += 1
    if not f64 and not weak:
        return
    site = site or "?"
    m = _metrics()
    with _slock:
        if f64 and _x64_flag:
            key = (site, "float64", where)
            if key not in _dtype_keys:
                _dtype_keys.add(key)
                if len(_dtype_drift) < _max_reports:
                    _dtype_drift.append({
                        "kind": "float64", "where": where, "site": site,
                        "leaves": f64,
                        "thread": threading.current_thread().name})
                else:
                    _counters["reports_dropped"] += 1
            _counters["x64_leaks"] += 1
            if m is not None:
                m.incr("nomad.jitcheck.x64_leak")
        if weak:
            key = (site, "weak-scalar", where)
            if key not in _dtype_keys:
                _dtype_keys.add(key)
                if len(_dtype_drift) < _max_reports:
                    _dtype_drift.append({
                        "kind": "weak-scalar", "where": where,
                        "site": site, "leaves": weak,
                        "thread": threading.current_thread().name})
                else:
                    _counters["reports_dropped"] += 1
            _counters["weak_scalars"] += 1


# ----------------------------------------------------------------------
# jit wrapping + trace accounting


class _JitWrapper:
    """Instrumented jitted callable: counts traces per abstract
    signature at its construction site. Delegates everything else to
    the real jit object (lower/clear_cache/etc. via __getattr__)."""

    def __init__(self, fun, kwargs, site):
        self._jc_site = site
        try:
            self._jc_fp = hash((
                _describe_static(fun),
                tuple(sorted((k, _describe_static(v))
                             for k, v in kwargs.items()))))
        except Exception:  # noqa: BLE001 -- unhashable description
            self._jc_fp = 0

        def _traced(*a, **k):
            # runs ONLY when jax traces (compile-cache miss)
            st = _tls_state()
            if st["calls"]:
                st["calls"][-1][2] += 1
            _counters["traces"] += 1
            return fun(*a, **k)

        try:
            functools.update_wrapper(_traced, fun)
        except Exception:  # noqa: BLE001 -- lambdas/partials vary
            pass
        self._jc_fn = _REAL["jit"](_traced, **kwargs)
        with _slock:
            _counters["jits"] += 1
            rec = _sites.get(site)
            if rec is None:
                rec = _sites[site] = {"calls": 0, "traces": 0,
                                      "jits": 0, "steady": False,
                                      "sigs": {}}
            rec["jits"] += 1

    def __call__(self, *args, **kwargs):
        if not _ACTIVE:
            return self._jc_fn(*args, **kwargs)
        sig = _abstract_sig(args, kwargs)
        _note_dtype_drift(self._jc_site, (args, kwargs), "jit")
        frame = [self._jc_site, sig, 0]
        st = _tls_state()
        st["calls"].append(frame)
        try:
            return self._jc_fn(*args, **kwargs)
        finally:
            st["calls"].pop()
            _note_call(self._jc_site, self._jc_fp, sig, frame[2])

    def __getattr__(self, name):
        return getattr(self._jc_fn, name)

    def __repr__(self):
        return f"<jitcheck.jit {self._jc_site} inner={self._jc_fn!r}>"


def _note_call(site: str, fp: int, sig: str, fired: int) -> None:
    retrace = late = None
    skey = (fp, sig)
    with _slock:
        rec = _sites.get(site)
        if rec is None:
            rec = _sites[site] = {"calls": 0, "traces": 0, "jits": 0,
                                  "steady": False, "sigs": {}}
        rec["calls"] += 1
        srec = rec["sigs"].get(skey)
        if srec is None:
            if len(rec["sigs"]) >= _SIG_CAP:
                _counters["sigs_dropped"] += 1
                rec["traces"] += fired
                return
            srec = rec["sigs"][skey] = {"traces": 0, "steady": False}
        _counters["calls"] += 1
        if not fired:
            srec["steady"] = True
            rec["steady"] = True
            return
        was_new = srec["traces"] == 0
        rec["traces"] += fired
        srec["traces"] += fired
        if srec["traces"] > _warmup:
            # same abstract signature traced again after warmup: the
            # compile cache was defeated (fresh jit per call, or an
            # unstable signature normalizing to the same abstract key)
            key = (site, sig)
            rep = _retrace_keys.get(key)
            if rep is not None:
                rep["count"] = srec["traces"]
            elif len(_retraces) >= _max_reports:
                _counters["reports_dropped"] += 1
            else:
                steady = [s for (_f, s), r in rec["sigs"].items()
                          if r["steady"]][:3]
                rep = {
                    "site": site, "signature": sig,
                    "count": srec["traces"],
                    # witness pair: the signature(s) the site already
                    # served from cache vs the one that re-traced
                    "witness": {"old": steady or [sig], "new": sig},
                    "thread": threading.current_thread().name,
                    "stack": _fmt_stack(),
                }
                _retrace_keys[key] = rep
                _retraces.append(rep)
            _counters["retraces"] += 1
            retrace = True
        elif was_new and any(
                r["steady"] for (f2, _s2), r in rec["sigs"].items()
                if f2 == fp):
            # a NEW signature at a program variant that already served
            # calls from cache: legitimate when a fresh shape bucket
            # arrives (fleet growth), so report-only
            key = (site, sig)
            if key not in _late_keys:
                _late_keys.add(key)
                if len(_late_traces) < _max_reports:
                    late = {
                        "site": site, "signature": sig,
                        "known_sigs": len(rec["sigs"]) - 1,
                        "thread": threading.current_thread().name,
                    }
                    _late_traces.append(late)
                else:
                    _counters["reports_dropped"] += 1
    if retrace:
        m = _metrics()
        if m is not None:
            m.incr("nomad.jitcheck.retrace")


def _jit_factory(fun=None, **kwargs):
    """Installed over jax.jit while enabled. Only callables constructed
    from repo frames are wrapped; stdlib/jax internals get the real
    jit. Keyword-only usage (jax.jit(static_argnames=...)) returns a
    partial, matching the real API."""
    if fun is None:
        return functools.partial(_jit_factory, **kwargs)
    if not _ACTIVE:
        return _REAL["jit"](fun, **kwargs)
    site = _repo_site()
    if site is None:
        return _REAL["jit"](fun, **kwargs)
    return _JitWrapper(fun, kwargs, site)


# ----------------------------------------------------------------------
# hot-region + host-sync detection


def note_dispatch_begin(label: str = "") -> None:
    """guard.run_dispatch entry (on the dispatch/runner thread): host
    syncs recorded until note_dispatch_end are hot-path syncs."""
    if not _ACTIVE:
        return
    st = _tls_state()
    st["hot"] += 1
    st["label"] = label


def note_dispatch_end() -> None:
    if not _ACTIVE:
        return
    st = _tls_state()
    st["hot"] = max(0, st["hot"] - 1)


class _SanctionedFetch:
    """Marks the designed one-bulk-fetch-per-dispatch sites: a
    device_get inside this block is the fused transport doing its job,
    not a hot-path sync. nomadlint's no-host-sync-hot rule recognizes
    the same marker statically, and its fetch-accounted rule requires
    every site to pass the transfer-ledger tag (``tag``) naming the
    transport, so per-tag sanctioned-fetch counts line up with the
    xferobs fetch decomposition."""

    def __init__(self, tag: str = ""):
        self._tag = tag

    def __enter__(self):
        if _ACTIVE:
            self._entered = True
            st = _tls_state()
            st["sanct"] += 1
            self._prev_tag = st.get("sanct_tag", "")
            st["sanct_tag"] = self._tag
        else:
            self._entered = False
        return self

    def __exit__(self, *exc):
        if self._entered:
            st = _tls_state()
            st["sanct"] = max(0, st["sanct"] - 1)
            st["sanct_tag"] = self._prev_tag
        return False


def sanctioned_fetch(tag: str = "") -> _SanctionedFetch:
    return _SanctionedFetch(tag)


def _note_sync(kind: str) -> None:
    if not _ACTIVE:
        return
    st = _tls_state()
    if st["hot"] <= 0:
        return
    if st["sanct"] > 0:
        _counters["sanctioned_fetches"] += 1
        tag = st.get("sanct_tag", "")
        if tag:
            with _slock:
                _sanct_tags[tag] = _sanct_tags.get(tag, 0) + 1
        return
    site = _repo_site() or "?"
    evals = _span_ids()
    m = _metrics()
    with _slock:
        key = (kind, site)
        rep = _host_sync_keys.get(key)
        if rep is not None:
            rep["count"] += 1
        elif len(_host_syncs) >= _max_reports:
            _counters["reports_dropped"] += 1
        else:
            rep = {"kind": kind, "site": site, "count": 1,
                   "label": st["label"], "evals": evals,
                   "thread": threading.current_thread().name,
                   "stack": _fmt_stack()}
            _host_sync_keys[key] = rep
            _host_syncs.append(rep)
        _counters["host_syncs"] += 1
    if m is not None:
        m.incr("nomad.jitcheck.host_sync")


def _patched_device_get(x):
    _note_sync("device_get")
    return _REAL["device_get"](x)


def _patched_device_put(x, *args, **kwargs):
    if _ACTIVE:
        _note_dtype_drift(_repo_site(), x, "device_put")
    return _REAL["device_put"](x, *args, **kwargs)


def _mk_sync_dunder(name: str):
    orig = _REAL[name]

    def patched(self, *a, **k):
        _note_sync(name)
        return orig(self, *a, **k)

    patched.__name__ = name
    return patched


# ----------------------------------------------------------------------
# fingerprint-cache mutation + frozen-memo invariant


def _digest(arr) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(str((arr.dtype.str, arr.shape)).encode())
    import numpy as np
    h.update(np.ascontiguousarray(arr).data)
    return h.digest()


def note_fingerprint(arr, digest: Optional[bytes] = None) -> None:
    """A host array's content fingerprint was just taken (constcache):
    register it for sampled re-hash; a later mismatch means the source
    was written after fingerprinting."""
    if not _ACTIVE:
        return
    site = _repo_site() or "?"
    if digest is None:
        digest = _digest(arr)
    nbytes = int(getattr(arr, "nbytes", 0))
    with _slock:
        if id(arr) not in _fps:
            _fps_bytes[0] += nbytes
        _fps[id(arr)] = (arr, digest, site)
        while _fps and (len(_fps) > _FPS_CAP
                        or _fps_bytes[0] > _FPS_MAX_BYTES):
            _, (old, _d, _s) = _fps.popitem(last=False)
            _fps_bytes[0] -= int(getattr(old, "nbytes", 0))


def note_frozen(arr) -> None:
    """A host array was stored into a memo/cache: it must be frozen
    (writeable=False) and stay that way."""
    if not _ACTIVE:
        return
    site = _repo_site() or "?"
    writable_now = bool(getattr(arr, "flags", None) is not None
                        and arr.flags.writeable)
    nbytes = int(getattr(arr, "nbytes", 0))
    with _slock:
        if id(arr) not in _frozen:
            _fps_bytes[1] += nbytes
        _frozen[id(arr)] = (arr, site)
        while _frozen and (len(_frozen) > _FPS_CAP
                           or _fps_bytes[1] > _FPS_MAX_BYTES):
            _, (old, _s) = _frozen.popitem(last=False)
            _fps_bytes[1] -= int(getattr(old, "nbytes", 0))
    if writable_now:
        _note_mutation("unfrozen-memo", site,
                       "array stored into a memo without "
                       "writeable=False")


def _note_mutation(kind: str, site: str, detail: str) -> None:
    m = _metrics()
    with _slock:
        key = (kind, site)
        if key in _mutation_keys:
            _counters["mutations"] += 1
            return
        _mutation_keys.add(key)
        if len(_mutations) >= _max_reports:
            _counters["reports_dropped"] += 1
        else:
            _mutations.append({
                "kind": kind, "site": site, "detail": detail,
                "thread": threading.current_thread().name})
        _counters["mutations"] += 1
    if m is not None:
        m.incr("nomad.jitcheck.mutated_cache")


def verify_caches(sample: Optional[int] = None) -> int:
    """Re-hash a rotating sample of registered fingerprint sources and
    re-check the frozen invariant; returns the number of NEW findings.
    Called from state() (every surface read audits) and directly by
    tests."""
    if not _ACTIVE:
        return 0
    n = sample if sample is not None else _rehash_n
    with _slock:
        fps = list(_fps.items())
        frozen = list(_frozen.items())
        cursor = _rehash_cursor[0]
    found = 0
    if fps:
        for i in range(min(n, len(fps))):
            key, (arr, digest, site) = fps[(cursor + i) % len(fps)]
            try:
                fresh = _digest(arr)
            except Exception:  # noqa: BLE001 -- shrunk/retyped arrays
                fresh = b"?"
            if fresh != digest:
                _note_mutation(
                    "content-mutation", site,
                    f"fingerprinted array re-hash mismatch "
                    f"(dtype={arr.dtype}, shape={arr.shape})")
                found += 1
                with _slock:
                    # re-arm with the current content so one mutation
                    # is one finding, not one per state() read
                    if key in _fps:
                        _fps[key] = (arr, fresh, site)
        with _slock:
            _rehash_cursor[0] = (cursor + n) % max(len(_fps), 1)
    for key, (arr, site) in frozen:
        if getattr(arr, "flags", None) is not None \
                and arr.flags.writeable:
            _note_mutation("thawed-memo", site,
                           "memoized array became writeable again")
            found += 1
            with _slock:
                _frozen.pop(key, None)
    return found


# ----------------------------------------------------------------------
# lifecycle


def enabled() -> bool:
    return _ACTIVE


def enable() -> None:
    """Patch jax.jit / device_get / device_put and the jax array host-
    conversion dunders. Jitted callables constructed before enable stay
    raw (documented gap -- nomadlint's no-callsite-jit covers the
    module-level sites statically)."""
    global _ACTIVE, _warmup, _stack_depth, _max_reports, _rehash_n, \
        _x64_flag
    with _slock:
        if _ACTIVE:
            return
        _warmup = max(1, int(os.environ.get(
            "NOMAD_TPU_JITCHECK_WARMUP", "1")))
        _stack_depth = int(os.environ.get(
            "NOMAD_TPU_JITCHECK_STACK", "16"))
        _max_reports = int(os.environ.get(
            "NOMAD_TPU_JITCHECK_MAX", "256"))
        _rehash_n = max(1, int(os.environ.get(
            "NOMAD_TPU_JITCHECK_REHASH", "32")))
    import jax
    from jax._src.array import ArrayImpl
    x64_mode = os.environ.get("NOMAD_TPU_JITCHECK_X64", "auto")
    if x64_mode == "1":
        _x64_flag = True
    elif x64_mode == "0":
        _x64_flag = False
    else:
        # x64 deliberately on (CPU-parity deployments): float64 is not
        # a leak there, it is the configured compute dtype
        _x64_flag = not jax.config.jax_enable_x64
    if not _REAL:
        _REAL["jit"] = jax.jit
        _REAL["device_get"] = jax.device_get
        _REAL["device_put"] = jax.device_put
        _REAL["array_cls"] = ArrayImpl
        _REAL["dunders"] = tuple(
            name for name in ("__array__", "__bool__", "__float__",
                              "__int__", "__index__", "item")
            if getattr(ArrayImpl, name, None) is not None)
        for name in _REAL["dunders"]:
            _REAL[name] = getattr(ArrayImpl, name)
    jax.jit = _jit_factory
    jax.device_get = _patched_device_get
    jax.device_put = _patched_device_put
    for name in _REAL["dunders"]:
        setattr(ArrayImpl, name, _mk_sync_dunder(name))
    _ACTIVE = True


def disable() -> None:
    """Restore the real entry points. Wrappers created while enabled
    keep working (they always delegate) but go inert."""
    global _ACTIVE
    if not _ACTIVE:
        return
    _ACTIVE = False
    import jax
    jax.jit = _REAL["jit"]
    jax.device_get = _REAL["device_get"]
    jax.device_put = _REAL["device_put"]
    cls = _REAL.get("array_cls")
    if cls is not None:
        for name in _REAL["dunders"]:
            setattr(cls, name, _REAL[name])


def maybe_install_from_env() -> None:
    if os.environ.get("NOMAD_TPU_JITCHECK", "0") == "1":
        enable()


# ----------------------------------------------------------------------
# reporting


def state(sites: bool = False) -> dict:
    """Full checker state (capped); rides /v1/agent/self, the operator
    CLI, debug bundles and bench artifacts. ``sites=True`` adds the
    per-site trace table (the CLI's --sites view)."""
    if _ACTIVE:
        verify_caches()
    with _slock:
        out = {
            "enabled": _ACTIVE,
            "warmup": _warmup,
            "jits": _counters["jits"],
            "calls": _counters["calls"],
            "traces": _counters["traces"],
            "site_count": len(_sites),
            "retrace_count": len(_retraces),
            "late_trace_count": len(_late_traces),
            "host_sync_count": len(_host_syncs),
            "sanctioned_fetches": _counters["sanctioned_fetches"],
            "sanctioned_by_tag": dict(_sanct_tags),
            "x64_leak_count": sum(1 for d in _dtype_drift
                                  if d["kind"] == "float64"),
            "weak_scalar_count": sum(1 for d in _dtype_drift
                                     if d["kind"] == "weak-scalar"),
            "mutation_count": len(_mutations),
            "reports_dropped": _counters["reports_dropped"],
            "retraces": [dict(r) for r in _retraces],
            "late_traces": [dict(r) for r in _late_traces],
            "host_syncs": [dict(r) for r in _host_syncs],
            "dtype_drift": [dict(r) for r in _dtype_drift],
            "mutations": [dict(r) for r in _mutations],
        }
        if sites:
            out["sites"] = [
                {"site": s, "jits": r["jits"], "calls": r["calls"],
                 "traces": r["traces"], "sigs": len(r["sigs"]),
                 "steady": r["steady"]}
                for s, r in _sites.items()]
    return out


def _reset_for_tests() -> None:
    with _slock:
        _sites.clear()
        _retraces.clear()
        _retrace_keys.clear()
        _late_traces.clear()
        _late_keys.clear()
        _host_syncs.clear()
        _host_sync_keys.clear()
        _dtype_drift.clear()
        _dtype_keys.clear()
        _mutations.clear()
        _mutation_keys.clear()
        _fps.clear()
        _frozen.clear()
        _fps_bytes[0] = _fps_bytes[1] = 0
        _rehash_cursor[0] = 0
        _sanct_tags.clear()
        for k in _counters:
            _counters[k] = 0
