"""Runtime lock-order sanitizer ("tsan-lite") for the control plane.

The reference Nomad leans on Go's race detector in CI while running
NumCPU scheduler workers against MVCC snapshots; this reproduction has
grown ~60 ``threading.Lock/RLock/Condition`` sites across the barrier,
dispatch pipeline, group-commit applier, delta journal and quality
layers with no equivalent tooling.  Before ROADMAP item 2 multiplies
the cross-thread interleavings (N concurrent scheduler workers over
snapshot isolation), this module gives tests and operators a deadlock
detector that works on the *order graph*, not on luck:

  * every acquire of an instrumented lock records the acquiring
    thread's currently-held set into a global acquisition-order graph;
    a cycle in that graph (A taken while holding B somewhere, B taken
    while holding A elsewhere) is a potential deadlock even if the
    fatal interleaving never fired in this run.  Both witness stacks
    (one per conflicting edge) are retained for the report.
  * locks held across a device dispatch (``guard.run_dispatch``), a
    ``faultinject.fire`` point, or a blocking ``queue.Queue.get`` /
    ``Condition.wait`` longer than ``NOMAD_TPU_LOCKCHECK_WAIT_MS`` are
    reported: those are the "solver wedge turns into a control-plane
    wedge" hazards round 5 hit live.
  * bare ``.acquire()`` calls whose acquiring frame returns (or whose
    thread exits) while the lock is still held are reported as
    escaped-frame acquires -- the runtime complement of nomadlint's
    static ``bare-acquire`` rule.

Kill switch semantics (mirrors the tracing kill switch): the checker is
OFF by default and ``NOMAD_TPU_LOCKCHECK=0``/unset is a true no-op --
``threading.Lock`` et al are untouched and no wrapper classes are
observable anywhere.  ``NOMAD_TPU_LOCKCHECK=1`` at process start (or
``enable()`` at runtime, which is how the conftest sanitizer fixture
runs the chaos/dispatch-pipeline/plan-batch/churn suites under the
checker) patches the ``threading`` factories; only locks constructed
from files under this repo are instrumented, so stdlib/jax internals
keep their raw primitives.

State rides the usual surfaces: ``/v1/agent/self`` ``stats.lockcheck``
block, ``operator lockcheck`` CLI, ``lockcheck.json`` in operator
debug bundles, and ``nomad.lockcheck.*`` counters.

Knobs: ``NOMAD_TPU_LOCKCHECK`` (off; ``1`` installs at import),
``NOMAD_TPU_LOCKCHECK_WAIT_MS`` (100: blocking-wait report threshold),
``NOMAD_TPU_LOCKCHECK_STACK`` (16: witness stack depth),
``NOMAD_TPU_LOCKCHECK_MAX`` (256: retained reports per class).
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

import _thread

# the deterministic-schedule sibling (schedcheck.py): the wrappers
# below double as its lock/condvar interposition points, gated on one
# module-attr read when it is off (same pattern as guard.py's
# lockcheck._ACTIVE gate). schedcheck's module top imports only the
# stdlib, so this import can never cycle.
from . import schedcheck as _schedcheck

# the real factories, captured before any patching can happen
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ACTIVE = False                  # module-global fast gate (one dict read)
_REAL_QUEUE_GET = None           # queue.Queue.get, saved at first enable

# checker-internal state; _slock is a RAW lock and a leaf: nothing is
# ever acquired under it and no user code runs under it
_slock = _REAL_LOCK()
_EDGE_CAP = 8192
_PATH_VISIT_CAP = 10000

_wait_ms = 100.0
_stack_depth = 16
_max_reports = 256

_serial = [0]                    # next lock id (under _slock)
_sites: Dict[int, str] = {}      # lock id -> construction site
_held: Dict[int, list] = {}      # thread id -> [_Held, ...] (own thread
                                 # appends/pops; readers copy)
_adj: Dict[int, Set[int]] = {}   # order graph: lock id -> successors
_edge_wit: Dict[Tuple[int, int], dict] = {}
_cycles: List[dict] = []
_cycle_keys: Set[frozenset] = set()
_held_across: List[dict] = []
_held_across_keys: Set[tuple] = set()
_escaped: List[dict] = []
_escaped_keys: Set[tuple] = set()
_counters = {"locks": 0, "acquires": 0, "edges_dropped": 0,
             "reports_dropped": 0}


class _Held:
    __slots__ = ("lock", "depth", "bare", "frame_id", "code_name",
                 "site", "thread_name")

    def __init__(self, lock, bare, frame):
        self.lock = lock
        self.depth = 1
        self.bare = bare
        self.frame_id = id(frame) if frame is not None else 0
        self.code_name = (frame.f_code.co_name if frame is not None
                          else "?")
        self.site = (f"{_rel(frame.f_code.co_filename)}:{frame.f_lineno}"
                     if frame is not None else "?")
        self.thread_name = threading.current_thread().name


def _rel(path: str) -> str:
    if path.startswith(_REPO_ROOT):
        return path[len(_REPO_ROOT) + 1:]
    return path


def _fmt_stack(frame) -> str:
    try:
        return "".join(traceback.format_stack(frame, limit=_stack_depth))
    except Exception:  # noqa: BLE001 -- diagnostics must never raise
        return "<stack unavailable>"


def _metrics():
    """Telemetry sink, or None mid-teardown -- the sanitizer must
    never take the process down with it."""
    try:
        from .server.telemetry import metrics
        return metrics
    except Exception:  # noqa: BLE001
        return None


# ----------------------------------------------------------------------
# recording


def _held_list() -> list:
    tid = _thread.get_ident()
    lst = _held.get(tid)
    if lst is None:
        lst = _held[tid] = []    # GIL-atomic single-key insert
    return lst


def _record_acquire(w, bare: bool, frame) -> None:
    if not _ACTIVE:
        return
    lst = _held_list()
    for e in reversed(lst):
        if e.lock is w:          # RLock re-entry: no new edges
            e.depth += 1
            return
    _counters["acquires"] += 1
    new_edges = [(e.lock._lc_id, w._lc_id) for e in lst
                 if (e.lock._lc_id, w._lc_id) not in _edge_wit]
    lst.append(_Held(w, bare, frame))
    if not new_edges:
        return
    # witness stack captured OUTSIDE _slock (format_stack allocates)
    stack = _fmt_stack(frame)
    thread_name = threading.current_thread().name
    cycles_found = []
    with _slock:
        for a, b in new_edges:
            if (a, b) in _edge_wit:
                continue
            if len(_edge_wit) >= _EDGE_CAP:
                _counters["edges_dropped"] += 1
                continue
            _edge_wit[(a, b)] = {
                "from": _sites.get(a, "?"), "to": _sites.get(b, "?"),
                "thread": thread_name, "stack": stack,
            }
            _adj.setdefault(a, set()).add(b)
            # path [b, ..., a]: the wrap-around edge a->b (just added)
            # closes the cycle
            path = _find_path(b, a)
            if path is not None:
                cyc = _record_cycle_locked(path)
                if cyc is not None:
                    cycles_found.append(cyc)
    if cycles_found:
        m = _metrics()
        if m is not None:
            m.incr("nomad.lockcheck.cycle", n=len(cycles_found))


def _find_path(src: int, dst: int) -> Optional[List[int]]:
    """DFS src -> dst in the order graph (under _slock). Returns the
    node path [src, ..., dst] or None."""
    if src == dst:
        return [src]
    stack = [(src, [src])]
    seen = {src}
    visits = 0
    while stack:
        node, path = stack.pop()
        for nxt in _adj.get(node, ()):
            visits += 1
            if visits > _PATH_VISIT_CAP:
                return None
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _record_cycle_locked(nodes: List[int]) -> Optional[dict]:
    """nodes is the cycle's node sequence [n0, ..., nk] where the edge
    nk->n0 closes the loop. Dedup by edge set; keep every edge's
    witness (both stacks of an AB/BA inversion)."""
    edges = [(nodes[i], nodes[(i + 1) % len(nodes)])
             for i in range(len(nodes))]
    key = frozenset(edges)
    if key in _cycle_keys:
        return None
    _cycle_keys.add(key)
    if len(_cycles) >= _max_reports:
        _counters["reports_dropped"] += 1
        return None
    cyc = {
        "locks": [_sites.get(n, "?") for n in nodes],
        "edges": [dict(_edge_wit.get((a, b)) or
                       {"from": _sites.get(a, "?"),
                        "to": _sites.get(b, "?"),
                        "thread": "?", "stack": "<unwitnessed>"})
                  for a, b in edges],
        # replayable counterexample: the active schedcheck run's seed
        # + decision step (None outside a controlled schedule)
        "schedule": _schedcheck.witness(),
    }
    _cycles.append(cyc)
    return cyc


def _record_release(w, full: bool = False) -> None:
    if not _ACTIVE:
        return
    lst = _held.get(_thread.get_ident())
    if not lst:
        return
    for i in range(len(lst) - 1, -1, -1):
        if lst[i].lock is w:
            if full or lst[i].depth <= 1:
                del lst[i]
            else:
                lst[i].depth -= 1
            return
    # not found: state was reset mid-critical-section, or the lock is
    # being released by a thread that never recorded the acquire
    # (cross-thread hand-off -- the acquirer's entry stays and the
    # escaped-frame check will surface it)


def _held_other(exclude=None) -> List[dict]:
    """Sites of locks the current thread holds (minus ``exclude``)."""
    lst = _held.get(_thread.get_ident())
    if not lst:
        return []
    return [{"lock": e.lock._lc_site, "acquired_at": e.site}
            for e in list(lst) if e.lock is not exclude]


def _note_held_across(kind: str, others: List[dict],
                      detail: str = "") -> None:
    key = (kind, tuple(o["lock"] for o in others))
    with _slock:
        if key in _held_across_keys:
            return
        _held_across_keys.add(key)
        if len(_held_across) >= _max_reports:
            _counters["reports_dropped"] += 1
            return
        _held_across.append({
            "kind": kind, "detail": detail, "held": others,
            "thread": threading.current_thread().name,
            "stack": _fmt_stack(sys._getframe(2)),
        })
    m = _metrics()
    if m is not None:
        m.incr("nomad.lockcheck.held_across")


# ----------------------------------------------------------------------
# hooks called from the rest of the tree (each is gated on _ACTIVE by
# the caller reading lockcheck._ACTIVE first, and re-checks here)


def note_fire(point: str) -> None:
    """faultinject.fire entry: firing a fault point -- which may hang
    or raise by design -- while holding locks turns an injected solver
    wedge into a control-plane wedge."""
    if not _ACTIVE:
        return
    others = _held_other()
    if others:
        _note_held_across(f"faultinject.fire:{point}", others)


def note_dispatch(label: str) -> None:
    """guard.run_dispatch entry: a device dispatch can burn a full
    watchdog deadline; holding any lock across it starves every other
    thread that needs that lock for the same deadline."""
    if not _ACTIVE:
        return
    others = _held_other()
    if others:
        _note_held_across(f"solver.dispatch:{label}", others)


def _patched_queue_get(self, block=True, timeout=None):
    if _ACTIVE and block:
        others = _held_other()
        if others:
            t0 = time.monotonic()
            try:
                return _REAL_QUEUE_GET(self, block, timeout)
            finally:
                dt_ms = (time.monotonic() - t0) * 1000.0
                if dt_ms >= _wait_ms:
                    _note_held_across("queue.get", others,
                                      f"{dt_ms:.0f}ms")
    return _REAL_QUEUE_GET(self, block, timeout)


# ----------------------------------------------------------------------
# instrumented primitives


class _LockWrapper:
    """Instrumented Lock/RLock. Delegates to a real primitive; records
    acquire/release into the checker when it is active. Implements the
    Condition owner protocol so instrumented condvars keep the held-set
    exact across wait()."""

    def __init__(self, inner, site: str, kind: str):
        self._lc_inner = inner
        self._lc_site = site
        self._lc_kind = kind
        with _slock:
            _serial[0] += 1
            self._lc_id = _serial[0]
            _sites[self._lc_id] = site
            _counters["locks"] += 1

    def acquire(self, blocking=True, timeout=-1):
        if blocking and _schedcheck._ACTIVE:
            _schedcheck.lock_gate(self._lc_inner)
        ok = self._lc_inner.acquire(blocking, timeout)
        if ok:
            _record_acquire(self, True, sys._getframe(1))
        return ok

    def release(self):
        self._lc_inner.release()
        _record_release(self)
        if _schedcheck._ACTIVE:
            _schedcheck.lock_released(self._lc_inner)

    def __enter__(self):
        if _schedcheck._ACTIVE:
            _schedcheck.lock_gate(self._lc_inner)
        # nomadlint: waive=bare-acquire -- this IS the lock: the paired
        # release is __exit__ by context-manager protocol
        self._lc_inner.acquire()
        _record_acquire(self, False, sys._getframe(1))
        return self

    def __exit__(self, *exc):
        _record_release(self)
        self._lc_inner.release()
        if _schedcheck._ACTIVE:
            _schedcheck.lock_released(self._lc_inner)
        return False

    def locked(self):
        return self._lc_inner.locked()

    # -- Condition owner protocol -------------------------------------
    def _release_save(self):
        _record_release(self, full=True)
        if self._lc_kind == "rlock":
            state = self._lc_inner._release_save()
        else:
            self._lc_inner.release()
            state = None
        if _schedcheck._ACTIVE:
            _schedcheck.lock_released(self._lc_inner)
        return state

    def _acquire_restore(self, state):
        if self._lc_kind == "rlock":
            self._lc_inner._acquire_restore(state)
        else:
            # nomadlint: waive=bare-acquire -- Condition owner
            # protocol: wait() re-acquires here, releases via
            # _release_save; the condvar owns the pairing
            self._lc_inner.acquire()
        _record_acquire(self, False, sys._getframe(1))

    def _is_owned(self):
        if self._lc_kind == "rlock":
            return self._lc_inner._is_owned()
        if self._lc_inner.acquire(False):
            self._lc_inner.release()
            return False
        return True

    def _at_fork_reinit(self):
        self._lc_inner._at_fork_reinit()

    def __repr__(self):
        return (f"<lockcheck.{self._lc_kind} {self._lc_site} "
                f"inner={self._lc_inner!r}>")


class _InstrumentedCondition(_REAL_CONDITION):
    """Real Condition over an instrumented lock; times waits so a
    thread parked on a condvar while holding OTHER locks past the
    threshold is reported.  Under an active schedcheck run, wait and
    notify route through the controller instead of the OS: the waiter
    parks virtually (no wall clock burns) and notify makes it runnable
    at the next scheduling decision -- which is what makes condvar
    handoff order a deterministic function of the schedule seed."""

    def wait(self, timeout=None):
        if _schedcheck._ACTIVE and _schedcheck.managed_active():
            state = self._release_save()
            try:
                notified = _schedcheck.cond_wait_gate(
                    id(self), timed=timeout is not None)
            finally:
                inner = getattr(self._lock, "_lc_inner", None)
                if inner is not None:
                    _schedcheck.lock_gate(inner, "cond.reacquire")
                self._acquire_restore(state)
            return notified
        if not _ACTIVE:
            return super().wait(timeout)
        others = _held_other(exclude=self._lock)
        if not others:
            return super().wait(timeout)
        t0 = time.monotonic()
        try:
            return super().wait(timeout)
        finally:
            dt_ms = (time.monotonic() - t0) * 1000.0
            if dt_ms >= _wait_ms:
                _note_held_across("condition.wait", others,
                                  f"{dt_ms:.0f}ms")

    def notify(self, n=1):
        super().notify(n)
        if _schedcheck._ACTIVE:
            _schedcheck.cond_notify(id(self), n)

    def notify_all(self):
        super().notify_all()
        if _schedcheck._ACTIVE:
            _schedcheck.cond_notify(id(self), None)


# ----------------------------------------------------------------------
# factories installed over threading.Lock/RLock/Condition while enabled


def _caller_site(depth: int = 2):
    """Construction call site as 'rel/path.py:line', or None when the
    caller is outside this repo (stdlib/jax locks stay raw)."""
    f = sys._getframe(depth)
    fn = f.f_code.co_filename
    if not fn.startswith(_REPO_ROOT) or fn.startswith(
            os.path.join(_REPO_ROOT, "nomad_tpu", "lockcheck")):
        return None
    return f"{_rel(fn)}:{f.f_lineno}"


def _lock_factory():
    inner = _REAL_LOCK()
    if not _ACTIVE:
        return inner
    site = _caller_site()
    if site is None:
        return inner
    return _LockWrapper(inner, site, "lock")


def _rlock_factory():
    inner = _REAL_RLOCK()
    if not _ACTIVE:
        return inner
    site = _caller_site()
    if site is None:
        return inner
    return _LockWrapper(inner, site, "rlock")


def _condition_factory(lock=None):
    if not _ACTIVE:
        return _REAL_CONDITION(lock)
    site = _caller_site()
    if site is None:
        return _REAL_CONDITION(lock)
    if lock is None:
        lock = _LockWrapper(_REAL_RLOCK(), site, "rlock")
    return _InstrumentedCondition(lock)


# ----------------------------------------------------------------------
# lifecycle


def enabled() -> bool:
    return _ACTIVE


def enable() -> None:
    """Patch the threading factories and start recording. Locks that
    already exist stay raw (documented gap: module-level singletons
    created before enable are invisible to the checker)."""
    global _ACTIVE, _REAL_QUEUE_GET, _wait_ms, _stack_depth, _max_reports
    with _slock:
        if _ACTIVE:
            return
        _wait_ms = float(os.environ.get(
            "NOMAD_TPU_LOCKCHECK_WAIT_MS", "100"))
        _stack_depth = int(os.environ.get(
            "NOMAD_TPU_LOCKCHECK_STACK", "16"))
        _max_reports = int(os.environ.get(
            "NOMAD_TPU_LOCKCHECK_MAX", "256"))
    import queue
    if _REAL_QUEUE_GET is None:
        _REAL_QUEUE_GET = queue.Queue.get
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    queue.Queue.get = _patched_queue_get
    _ACTIVE = True


def disable() -> None:
    """Restore the real factories. Wrappers created while enabled keep
    working (they always delegate to a real primitive) but go inert."""
    global _ACTIVE
    if not _ACTIVE:
        return
    _ACTIVE = False
    import queue
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    if _REAL_QUEUE_GET is not None:
        queue.Queue.get = _REAL_QUEUE_GET


def maybe_install_from_env() -> None:
    if os.environ.get("NOMAD_TPU_LOCKCHECK", "0") == "1":
        enable()


# ----------------------------------------------------------------------
# reporting


def _check_escapes() -> None:
    """A bare .acquire() whose acquiring frame is no longer on its
    thread's stack (or whose thread exited) while the lock is still
    held: the release, if it ever comes, is someone else's problem."""
    frames = sys._current_frames()
    alive = {t.ident for t in threading.enumerate()}
    found = []
    for tid, lst in list(_held.items()):
        for e in list(lst):
            if not e.bare:
                continue
            reason = None
            if tid not in alive:
                reason = "thread-exited"
            else:
                f = frames.get(tid)
                on_stack = False
                while f is not None:
                    if id(f) == e.frame_id and \
                            f.f_code.co_name == e.code_name:
                        on_stack = True
                        break
                    f = f.f_back
                if not on_stack:
                    reason = "frame-exited"
            if reason is None:
                continue
            key = (e.lock._lc_id, e.frame_id)
            with _slock:
                if key in _escaped_keys:
                    continue
                _escaped_keys.add(key)
                if len(_escaped) >= _max_reports:
                    _counters["reports_dropped"] += 1
                    continue
                _escaped.append({
                    "lock": e.lock._lc_site, "acquired_at": e.site,
                    "in_function": e.code_name, "reason": reason,
                    "thread": e.thread_name,
                })
                found.append(key)
    if found:
        m = _metrics()
        if m is not None:
            m.incr("nomad.lockcheck.escaped", n=len(found))


def state() -> dict:
    """Full checker state (capped); rides /v1/agent/self, the operator
    CLI, and debug bundles."""
    if _ACTIVE:
        _check_escapes()
    with _slock:
        return {
            "enabled": _ACTIVE,
            "wait_ms": _wait_ms,
            "locks": _counters["locks"],
            "acquires": _counters["acquires"],
            "edges": len(_edge_wit),
            "edges_dropped": _counters["edges_dropped"],
            "reports_dropped": _counters["reports_dropped"],
            "cycle_count": len(_cycles),
            "cycles": [dict(c) for c in _cycles],
            "held_across": [dict(v) for v in _held_across],
            "escaped": [dict(v) for v in _escaped],
        }


def _reset_for_tests() -> None:
    with _slock:
        _held.clear()
        _adj.clear()
        _edge_wit.clear()
        _cycles.clear()
        _cycle_keys.clear()
        _held_across.clear()
        _held_across_keys.clear()
        _escaped.clear()
        _escaped_keys.clear()
        for k in _counters:
            _counters[k] = 0
