"""ctypes bindings for the native tensorization kernels (native/
pack_kernels.cc), with pure-numpy fallbacks when the library is absent.

The native boundary mirrors where the reference keeps native code
(SURVEY.md section 2.4): performance-critical runtime components, here the
struct->tensor marshalling path of the TPU solver.
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

PORT_WORDS = 2048
MAX_PORTS_PER_ALLOC = 8

# Bumped whenever the C ABI changes shape; load() refuses a stale .so so a
# half-upgraded tree falls back to numpy instead of corrupting memory.
ABI_VERSION = 3

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def native_cp_enabled() -> bool:
    """Kill switch for the native control-plane hot paths (plan verify,
    delta-advanced snapshots, lazy alloc materialization). Default on;
    ``NOMAD_TPU_NATIVE_CP=0`` restores the pre-native Python paths
    bit-for-bit (the parity oracle)."""
    return os.environ.get("NOMAD_TPU_NATIVE_CP", "") != "0"


def _find_library() -> Optional[str]:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for cand in (
            os.path.join(here, "native", "build", "libnomad_tpu_native.so"),
            os.environ.get("NOMAD_TPU_NATIVE_LIB", "")):
        if cand and os.path.exists(cand):
            return cand
    return None


def load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    path = _find_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        if lib.nt_abi_version() != ABI_VERSION:
            return None
        d = ctypes.POINTER(ctypes.c_double)
        i32 = ctypes.POINTER(ctypes.c_int32)
        i64 = ctypes.POINTER(ctypes.c_int64)
        i8 = ctypes.POINTER(ctypes.c_int8)
        u8 = ctypes.POINTER(ctypes.c_uint8)
        u32 = ctypes.POINTER(ctypes.c_uint32)
        u64 = ctypes.POINTER(ctypes.c_uint64)
        lib.nt_pack_usage.argtypes = [
            i32, d, d, d, u8, i32, ctypes.c_int64, ctypes.c_int32,
            i32, i32, d, d, d, i32, u32, ctypes.c_int64]
        lib.nt_count_placed.argtypes = [
            i32, u64, u64, u8, ctypes.c_int64, ctypes.c_uint64,
            ctypes.c_uint64, i32, i32, ctypes.c_int64]
        lib.nt_static_ports_free.argtypes = [
            u32, ctypes.c_int64, i32, ctypes.c_int32, u8]
        lib.nt_verify_fit.argtypes = [d, d, d, d, d, d, d, d, d,
                                      ctypes.c_int64, i32]
        lib.nt_verify_plan.argtypes = [
            d, d, d, u8,                          # table columns
            i64, i32, i8, ctypes.c_int64,         # row deltas
            i32, d, d, d, i8, ctypes.c_int64,     # direct ask entries
            d, d, d,                              # caps
            d, d, d, d, d, d,                     # used/ask accumulators
            ctypes.c_int64, i32]
        lib.nt_solve_eval.argtypes = [
            ctypes.c_int32, d, d, d, d, d, d, i32, u8,
            ctypes.c_uint64, ctypes.c_double, ctypes.c_double,
            ctypes.c_double, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_double, ctypes.c_int32,
            ctypes.c_int32, i32, i32]
        lib.nt_shuffled_order.argtypes = [ctypes.c_uint64, ctypes.c_int32,
                                          i32]
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def available() -> bool:
    return load() is not None


def ensure_built(timeout_s: int = 120) -> bool:
    """Build the native library if absent (g++ one-liner, matching the
    CMake flags). Used by bench.py so the compiled-host baseline exists on
    whatever machine runs the bench."""
    global _load_attempted
    if available():
        return True
    import subprocess
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(here, "native", "pack_kernels.cc")
    out_dir = os.path.join(here, "native", "build")
    out = os.path.join(out_dir, "libnomad_tpu_native.so")
    if not os.path.exists(src):
        return False
    os.makedirs(out_dir, exist_ok=True)
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC",
             "-o", out, src],
            check=True, capture_output=True, timeout=timeout_s)
    except (subprocess.SubprocessError, OSError):
        return False
    _load_attempted = False
    return available()


def shuffled_order(seed: int, n: int) -> Optional[np.ndarray]:
    """The deterministic per-eval Fisher-Yates permutation (identical to
    scheduler/util.py shuffled_order) computed natively; None when the
    library is absent."""
    lib = load()
    if lib is None:
        return None
    out = np.empty(n, dtype=np.int32)
    lib.nt_shuffled_order(seed, n, _ptr(out, ctypes.c_int32))
    return out


def solve_eval(cpu_cap: np.ndarray, mem_cap: np.ndarray, disk_cap: np.ndarray,
               used_cpu: np.ndarray, used_mem: np.ndarray,
               used_disk: np.ndarray, placed_jobtg: np.ndarray,
               eligible: np.ndarray, shuffle_seed: int,
               ask_cpu: float, ask_mem: float, ask_disk: float,
               desired_count: int, limit: int, n_placements: int,
               spread_alg: bool = False, max_skip: int = 3,
               skip_threshold: float = 0.0) -> Optional[np.ndarray]:
    """Run the compiled host-baseline oracle: n_placements sequential
    window-limited binpack selections with usage carry (the reference's
    per-eval inner loop, scheduler/rank.go:205 + stack.go:82-95). Mutates
    used_* and placed_jobtg in place; returns chosen node index per
    placement (-1 = no placement), or None when the library is absent."""
    lib = load()
    if lib is None:
        return None
    n = len(cpu_cap)
    for arr, dt in ((cpu_cap, np.float64), (mem_cap, np.float64),
                    (disk_cap, np.float64), (used_cpu, np.float64),
                    (used_mem, np.float64), (used_disk, np.float64),
                    (placed_jobtg, np.int32), (eligible, np.uint8)):
        if arr.dtype != dt or not arr.flags["C_CONTIGUOUS"]:
            raise ValueError("solve_eval requires contiguous typed arrays")
    order = np.empty(n, dtype=np.int32)
    out_choice = np.empty(n_placements, dtype=np.int32)
    lib.nt_solve_eval(
        n, _ptr(cpu_cap, ctypes.c_double), _ptr(mem_cap, ctypes.c_double),
        _ptr(disk_cap, ctypes.c_double), _ptr(used_cpu, ctypes.c_double),
        _ptr(used_mem, ctypes.c_double), _ptr(used_disk, ctypes.c_double),
        _ptr(placed_jobtg, ctypes.c_int32), _ptr(eligible, ctypes.c_uint8),
        shuffle_seed, float(ask_cpu), float(ask_mem), float(ask_disk),
        desired_count, limit, max_skip, skip_threshold, n_placements,
        1 if spread_alg else 0, _ptr(order, ctypes.c_int32),
        _ptr(out_choice, ctypes.c_int32))
    return out_choice


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def pack_usage(node_slot: np.ndarray, cpu: np.ndarray, mem: np.ndarray,
               disk: np.ndarray, live: np.ndarray,
               ports: Optional[np.ndarray],
               dyn_lo: np.ndarray, dyn_hi: np.ndarray, n_pad: int,
               port_words_seed: Optional[np.ndarray] = None
               ) -> Tuple[np.ndarray, ...]:
    """Fold the alloc table into node-axis usage tensors. All row arrays are
    length n_rows; ports is (n_rows, MAX_PORTS_PER_ALLOC) int32 (-1 empty)
    or None to skip port folding entirely.
    Returns (used_cpu, used_mem, used_disk, dyn_used, port_words);
    port_words is None when no port state exists."""
    n_rows = len(node_slot)
    used_cpu = np.zeros(n_pad, dtype=np.float64)
    used_mem = np.zeros(n_pad, dtype=np.float64)
    used_disk = np.zeros(n_pad, dtype=np.float64)
    dyn_used = np.zeros(n_pad, dtype=np.int32)
    # The bitmap is 80MB at 10K nodes; only materialize when port state
    # exists (seed present or any row carries ports).
    has_ports = (ports is not None and n_rows
                 and bool((ports[:, 0] >= 0).any()))
    if port_words_seed is None and not has_ports:
        port_words = None
    else:
        port_words = (port_words_seed.copy() if port_words_seed is not None
                      else np.zeros((n_pad, PORT_WORDS), dtype=np.uint32))
    max_ports = MAX_PORTS_PER_ALLOC if ports is not None else 0
    lib = load()
    if lib is not None and n_rows:
        node_slot = np.ascontiguousarray(node_slot, dtype=np.int32)
        cpu = np.ascontiguousarray(cpu, dtype=np.float64)
        mem = np.ascontiguousarray(mem, dtype=np.float64)
        disk = np.ascontiguousarray(disk, dtype=np.float64)
        live = np.ascontiguousarray(live, dtype=np.uint8)
        if ports is not None:
            ports = np.ascontiguousarray(ports, dtype=np.int32)
        dyn_lo = np.ascontiguousarray(dyn_lo, dtype=np.int32)
        dyn_hi = np.ascontiguousarray(dyn_hi, dtype=np.int32)
        lib.nt_pack_usage(
            _ptr(node_slot, ctypes.c_int32), _ptr(cpu, ctypes.c_double),
            _ptr(mem, ctypes.c_double), _ptr(disk, ctypes.c_double),
            _ptr(live, ctypes.c_uint8),
            (_ptr(ports, ctypes.c_int32) if ports is not None else None),
            n_rows, max_ports,
            _ptr(dyn_lo, ctypes.c_int32), _ptr(dyn_hi, ctypes.c_int32),
            _ptr(used_cpu, ctypes.c_double), _ptr(used_mem, ctypes.c_double),
            _ptr(used_disk, ctypes.c_double), _ptr(dyn_used, ctypes.c_int32),
            (_ptr(port_words, ctypes.c_uint32)
             if port_words is not None else None), n_pad)
        return used_cpu, used_mem, used_disk, dyn_used, port_words

    # numpy fallback
    mask = (live != 0) & (node_slot >= 0) & (node_slot < n_pad)
    slots = node_slot[mask]
    np.add.at(used_cpu, slots, cpu[mask])
    np.add.at(used_mem, slots, mem[mask])
    np.add.at(used_disk, slots, disk[mask])
    if port_words is not None and ports is not None:
        for i in np.nonzero(mask)[0]:
            slot = node_slot[i]
            for p in ports[i]:
                if p < 0:
                    break
                if p >= 65536:
                    continue
                word, bit = p >> 5, np.uint32(1 << (p & 31))
                if not port_words[slot, word] & bit:
                    port_words[slot, word] |= bit
                    if dyn_lo[slot] <= p <= dyn_hi[slot]:
                        dyn_used[slot] += 1
    return used_cpu, used_mem, used_disk, dyn_used, port_words


def count_placed(node_slot: np.ndarray, job_hash: np.ndarray,
                 jobtg_hash: np.ndarray, live: np.ndarray,
                 want_job: int, want_jobtg: int, n_pad: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
    placed = np.zeros(n_pad, dtype=np.int32)
    placed_job = np.zeros(n_pad, dtype=np.int32)
    n_rows = len(node_slot)
    lib = load()
    if lib is not None and n_rows:
        node_slot = np.ascontiguousarray(node_slot, dtype=np.int32)
        job_hash = np.ascontiguousarray(job_hash, dtype=np.uint64)
        jobtg_hash = np.ascontiguousarray(jobtg_hash, dtype=np.uint64)
        live = np.ascontiguousarray(live, dtype=np.uint8)
        lib.nt_count_placed(
            _ptr(node_slot, ctypes.c_int32), _ptr(job_hash, ctypes.c_uint64),
            _ptr(jobtg_hash, ctypes.c_uint64), _ptr(live, ctypes.c_uint8),
            n_rows, want_job, want_jobtg,
            _ptr(placed, ctypes.c_int32), _ptr(placed_job, ctypes.c_int32),
            n_pad)
        return placed, placed_job
    mask = (live != 0) & (node_slot >= 0) & (node_slot < n_pad) & \
        (job_hash == want_job)
    np.add.at(placed_job, node_slot[mask], 1)
    mask_tg = mask & (jobtg_hash == want_jobtg)
    np.add.at(placed, node_slot[mask_tg], 1)
    return placed, placed_job


def static_ports_free(port_words: np.ndarray,
                      check_ports: np.ndarray) -> np.ndarray:
    n_pad = port_words.shape[0]
    out = np.ones(n_pad, dtype=np.uint8)
    n_ports = len(check_ports)
    if n_ports == 0:
        return out.astype(bool)
    lib = load()
    if lib is not None:
        pw = np.ascontiguousarray(port_words, dtype=np.uint32)
        cp = np.ascontiguousarray(check_ports, dtype=np.int32)
        lib.nt_static_ports_free(
            _ptr(pw, ctypes.c_uint32), n_pad,
            _ptr(cp, ctypes.c_int32), n_ports, _ptr(out, ctypes.c_uint8))
        return out.astype(bool)
    for p in check_ports:
        if p < 0 or p >= 65536:
            continue
        word, bit = int(p) >> 5, np.uint32(1 << (int(p) & 31))
        out &= ((port_words[:, word] & bit) == 0).astype(np.uint8)
    return out.astype(bool)


def verify_fit(cpu_cap, mem_cap, disk_cap, used_cpu, used_mem, used_disk,
               ask_cpu, ask_mem, ask_disk) -> np.ndarray:
    """Batch node-axis fit verification. Returns failing dim per node
    (0 ok, 1 cpu, 2 memory, 3 disk)."""
    n = len(cpu_cap)
    out = np.zeros(n, dtype=np.int32)
    lib = load()
    if lib is not None and n:
        args = [np.ascontiguousarray(a, dtype=np.float64) for a in
                (cpu_cap, mem_cap, disk_cap, used_cpu, used_mem, used_disk,
                 ask_cpu, ask_mem, ask_disk)]
        lib.nt_verify_fit(*[_ptr(a, ctypes.c_double) for a in args],
                          n, _ptr(out, ctypes.c_int32))
        return out
    out = np.where(used_cpu + ask_cpu > cpu_cap, 1,
                   np.where(used_mem + ask_mem > mem_cap, 2,
                            np.where(used_disk + ask_disk > disk_cap, 3, 0)))
    return out.astype(np.int32)


def verify_plan(tbl_cpu, tbl_mem, tbl_disk, tbl_live_strict,
                d_row, d_pos, d_sign, a_pos, a_cpu, a_mem, a_disk,
                a_into_used, cpu_cap, mem_cap, disk_cap,
                used_cpu, used_mem, used_disk) -> np.ndarray:
    """Whole-group plan verification: apply a plan group's row-backed
    deltas (``used[d_pos] += d_sign * tbl[d_row]`` where the row is still
    live_strict) and direct value entries (into used for in-flight overlay
    adds, into ask for this group's placements), then compare
    ``used + ask`` against caps per node. Entries apply strictly in order,
    so float accumulation matches the Python oracle's traversal order.
    Mutates used_* in place; returns failing dim per node (0 ok, 1 cpu,
    2 memory, 3 disk). The GIL is released for the whole call when the
    library is loaded; the fallback applies the same entries in the same
    order in Python, bitwise-identical."""
    n = len(cpu_cap)
    n_delta, n_ask = len(d_row), len(a_pos)
    out = np.zeros(n, dtype=np.int32)
    ask_c = np.zeros(n, dtype=np.float64)
    ask_m = np.zeros(n, dtype=np.float64)
    ask_d = np.zeros(n, dtype=np.float64)
    lib = load()
    if lib is not None and n:
        tbl = [np.ascontiguousarray(a, dtype=np.float64)
               for a in (tbl_cpu, tbl_mem, tbl_disk)]
        ls = np.ascontiguousarray(tbl_live_strict, dtype=np.uint8)
        d_row = np.ascontiguousarray(d_row, dtype=np.int64)
        d_pos = np.ascontiguousarray(d_pos, dtype=np.int32)
        d_sign = np.ascontiguousarray(d_sign, dtype=np.int8)
        a_pos = np.ascontiguousarray(a_pos, dtype=np.int32)
        a_c, a_m, a_d = [np.ascontiguousarray(a, dtype=np.float64)
                         for a in (a_cpu, a_mem, a_disk)]
        a_iu = np.ascontiguousarray(a_into_used, dtype=np.int8)
        caps = [np.ascontiguousarray(a, dtype=np.float64)
                for a in (cpu_cap, mem_cap, disk_cap)]
        lib.nt_verify_plan(
            *[_ptr(a, ctypes.c_double) for a in tbl],
            _ptr(ls, ctypes.c_uint8),
            _ptr(d_row, ctypes.c_int64), _ptr(d_pos, ctypes.c_int32),
            _ptr(d_sign, ctypes.c_int8), n_delta,
            _ptr(a_pos, ctypes.c_int32),
            _ptr(a_c, ctypes.c_double), _ptr(a_m, ctypes.c_double),
            _ptr(a_d, ctypes.c_double), _ptr(a_iu, ctypes.c_int8), n_ask,
            *[_ptr(a, ctypes.c_double) for a in caps],
            _ptr(used_cpu, ctypes.c_double), _ptr(used_mem, ctypes.c_double),
            _ptr(used_disk, ctypes.c_double),
            _ptr(ask_c, ctypes.c_double), _ptr(ask_m, ctypes.c_double),
            _ptr(ask_d, ctypes.c_double), n, _ptr(out, ctypes.c_int32))
        return out

    # numpy fallback: entries apply one at a time in order, so the float
    # accumulation order is identical to the C loop (bitwise parity)
    for e in range(n_delta):
        row = int(d_row[e])
        if not tbl_live_strict[row]:
            continue
        k, s = int(d_pos[e]), float(d_sign[e])
        used_cpu[k] += s * tbl_cpu[row]
        used_mem[k] += s * tbl_mem[row]
        used_disk[k] += s * tbl_disk[row]
    for e in range(n_ask):
        k = int(a_pos[e])
        if a_into_used[e]:
            used_cpu[k] += a_cpu[e]
            used_mem[k] += a_mem[e]
            used_disk[k] += a_disk[e]
        else:
            ask_c[k] += a_cpu[e]
            ask_m[k] += a_mem[e]
            ask_d[k] += a_disk[e]
    return verify_fit(cpu_cap, mem_cap, disk_cap, used_cpu, used_mem,
                      used_disk, ask_c, ask_m, ask_d)
