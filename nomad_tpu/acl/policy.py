"""ACL policy language: named rule documents granting capabilities.

Semantic parity with the reference's policy model (reference: acl/policy.go
-- Policy/NamespacePolicy/capability expansion; parsed from HCL). A policy
document is HCL:

    namespace "default" { policy = "write" }
    namespace "ops-*"   { capabilities = ["list-jobs", "read-job"] }
    node     { policy = "read" }
    agent    { policy = "write" }
    operator { policy = "read" }
    quota    { policy = "read" }
    plugin   { policy = "list" }
    host_volume "prod-*" { policy = "mount-readonly" }

Short policy levels expand to capability sets exactly like the reference's
expandNamespacePolicy (acl/policy.go).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..jobspec.hcl import Block, HclError, parse_hcl

# policy levels (reference: acl/policy.go PolicyDeny..PolicyScale)
POLICY_DENY = "deny"
POLICY_READ = "read"
POLICY_WRITE = "write"
POLICY_LIST = "list"
POLICY_SCALE = "scale"

# namespace capabilities (reference: acl/policy.go NamespaceCapability*)
CAP_DENY = "deny"
CAP_LIST_JOBS = "list-jobs"
CAP_PARSE_JOB = "parse-job"
CAP_READ_JOB = "read-job"
CAP_SUBMIT_JOB = "submit-job"
CAP_DISPATCH_JOB = "dispatch-job"
CAP_READ_LOGS = "read-logs"
CAP_READ_FS = "read-fs"
CAP_ALLOC_EXEC = "alloc-exec"
CAP_ALLOC_LIFECYCLE = "alloc-lifecycle"
CAP_ALLOC_NODE_EXEC = "alloc-node-exec"
CAP_CSI_REGISTER_PLUGIN = "csi-register-plugin"
CAP_CSI_WRITE_VOLUME = "csi-write-volume"
CAP_CSI_READ_VOLUME = "csi-read-volume"
CAP_CSI_LIST_VOLUME = "csi-list-volume"
CAP_CSI_MOUNT_VOLUME = "csi-mount-volume"
CAP_LIST_SCALING_POLICIES = "list-scaling-policies"
CAP_READ_SCALING_POLICY = "read-scaling-policy"
CAP_READ_JOB_SCALING = "read-job-scaling"
CAP_SCALE_JOB = "scale-job"
CAP_VARIABLES_READ = "variables-read"
CAP_VARIABLES_WRITE = "variables-write"
CAP_VARIABLES_LIST = "variables-list"
CAP_VARIABLES_DESTROY = "variables-destroy"

_READ_CAPS = [
    CAP_LIST_JOBS, CAP_PARSE_JOB, CAP_READ_JOB, CAP_CSI_LIST_VOLUME,
    CAP_CSI_READ_VOLUME, CAP_READ_JOB_SCALING, CAP_LIST_SCALING_POLICIES,
    CAP_READ_SCALING_POLICY, CAP_VARIABLES_READ, CAP_VARIABLES_LIST,
]
_WRITE_CAPS = _READ_CAPS + [
    CAP_SUBMIT_JOB, CAP_DISPATCH_JOB, CAP_READ_LOGS, CAP_READ_FS,
    CAP_ALLOC_EXEC, CAP_ALLOC_LIFECYCLE, CAP_CSI_WRITE_VOLUME,
    CAP_CSI_MOUNT_VOLUME, CAP_SCALE_JOB, CAP_VARIABLES_WRITE,
    CAP_VARIABLES_DESTROY,
]
_SCALE_CAPS = [CAP_LIST_SCALING_POLICIES, CAP_READ_SCALING_POLICY,
               CAP_READ_JOB_SCALING, CAP_SCALE_JOB]


def expand_namespace_policy(level: str) -> List[str]:
    """(reference: acl/policy.go expandNamespacePolicy)"""
    if level == POLICY_DENY:
        return [CAP_DENY]
    if level == POLICY_READ:
        return list(_READ_CAPS)
    if level == POLICY_WRITE:
        return list(_WRITE_CAPS)
    if level == POLICY_SCALE:
        return list(_SCALE_CAPS)
    raise ValueError(f"invalid namespace policy level: {level!r}")


@dataclass
class NamespaceRule:
    name: str                      # may contain glob '*'
    policy: str = ""
    capabilities: List[str] = field(default_factory=list)
    variables: List["VariablePathRule"] = field(default_factory=list)

    def all_capabilities(self) -> List[str]:
        caps: List[str] = []
        if self.policy:
            caps.extend(expand_namespace_policy(self.policy))
        caps.extend(self.capabilities)
        return caps


def expand_variables_capabilities(caps: List[str]) -> List[str]:
    """Expand the shorthand levels exactly like the reference
    (acl/policy.go expandVariablesCapabilities: write -> list+read+write+
    destroy, read -> list+read; deny is sticky)."""
    if "deny" in caps:
        return ["deny"]
    out: List[str] = []
    for cap in caps:
        if cap == "write":
            out.extend(("list", "read", "write", "destroy"))
        elif cap == "read":
            out.extend(("list", "read"))
        else:
            out.append(cap)
    # stable dedup
    seen: set = set()
    return [c for c in out if not (c in seen or seen.add(c))]


@dataclass
class VariablePathRule:
    """`variables { path "nomad/jobs/*" { capabilities = [...] } }`"""
    path: str
    capabilities: List[str] = field(default_factory=list)


@dataclass
class HostVolumeRule:
    name: str
    policy: str = ""
    capabilities: List[str] = field(default_factory=list)


@dataclass
class Policy:
    """A parsed, named policy document (reference: acl/policy.go Policy)."""
    name: str = ""
    description: str = ""
    raw: str = ""
    namespaces: List[NamespaceRule] = field(default_factory=list)
    host_volumes: List[HostVolumeRule] = field(default_factory=list)
    node: str = ""
    agent: str = ""
    operator: str = ""
    quota: str = ""
    plugin: str = ""


_COARSE_LEVELS = {POLICY_DENY, POLICY_READ, POLICY_WRITE}
_PLUGIN_LEVELS = {POLICY_DENY, POLICY_LIST, POLICY_READ}


def parse_policy(name: str, src: str) -> Policy:
    """Parse an HCL policy document (reference: acl/policy.go Parse)."""
    root = parse_hcl(src)
    pol = Policy(name=name, raw=src)
    for item in root.body:
        if not isinstance(item, Block):
            continue
        if item.type == "namespace":
            attrs = item.attrs()
            rule = NamespaceRule(
                name=item.label(default="default"),
                policy=attrs.get("policy", ""),
                capabilities=list(attrs.get("capabilities", []) or []))
            if rule.policy:
                expand_namespace_policy(rule.policy)  # validate
            for sub in item.blocks("variables"):
                for pb in sub.blocks("path"):
                    rule.variables.append(VariablePathRule(
                        path=pb.label(default="*"),
                        capabilities=expand_variables_capabilities(
                            list(pb.attrs().get("capabilities", []) or []))))
            pol.namespaces.append(rule)
        elif item.type == "host_volume":
            attrs = item.attrs()
            pol.host_volumes.append(HostVolumeRule(
                name=item.label(default="*"),
                policy=attrs.get("policy", ""),
                capabilities=list(attrs.get("capabilities", []) or [])))
        elif item.type in ("node", "agent", "operator", "quota", "plugin"):
            level = item.attrs().get("policy", "")
            allowed = (_PLUGIN_LEVELS if item.type == "plugin"
                       else _COARSE_LEVELS)
            if level and level not in allowed:
                raise HclError(
                    f"invalid {item.type} policy level {level!r}", item.line)
            setattr(pol, item.type, level)
    return pol
