"""ACL subsystem: policy language, compiled capability checks, resolution.

(reference: /root/reference/acl/ + nomad/auth/; storage structs live in
nomad_tpu/structs/acl.py, tables in the state store.)
"""
from .acl import ACL, ANONYMOUS_ACL, MANAGEMENT_ACL  # noqa: F401
from .policy import (  # noqa: F401
    CAP_ALLOC_EXEC, CAP_ALLOC_LIFECYCLE, CAP_CSI_LIST_VOLUME,
    CAP_CSI_MOUNT_VOLUME, CAP_CSI_READ_VOLUME, CAP_CSI_REGISTER_PLUGIN,
    CAP_CSI_WRITE_VOLUME, CAP_DISPATCH_JOB, CAP_LIST_JOBS,
    CAP_LIST_SCALING_POLICIES, CAP_PARSE_JOB, CAP_READ_FS, CAP_READ_JOB,
    CAP_READ_JOB_SCALING, CAP_READ_LOGS, CAP_READ_SCALING_POLICY,
    CAP_SCALE_JOB, CAP_SUBMIT_JOB, CAP_VARIABLES_DESTROY, CAP_VARIABLES_LIST,
    CAP_VARIABLES_READ, CAP_VARIABLES_WRITE,
    POLICY_DENY, POLICY_LIST, POLICY_READ, POLICY_SCALE, POLICY_WRITE,
    Policy, expand_namespace_policy, parse_policy,
)


class Resolver:
    """Resolves request secrets to compiled ACLs with a cache keyed on the
    ACL table indexes (reference: nomad/auth/auth.go + acl cache in
    nomad/acl.go ResolveToken)."""

    def __init__(self, state):
        import threading
        self.state = state
        self._lock = threading.Lock()
        self._cache = {}
        self._cache_key = (-1, -1)

    def resolve_secret(self, secret_id):
        """-> (ACL, token) or (None, None) for an unknown/expired secret."""
        from ..structs import ACL_TOKEN_TYPE_MANAGEMENT

        # snapshot the generation BEFORE reading token/policies, and only
        # publish a compiled ACL under the generation it was built from --
        # otherwise a concurrent policy write could cache a stale compile
        # under a fresh key and serve revoked capabilities indefinitely
        key = (self.state.table_index("acl_tokens"),
               self.state.table_index("acl_policies"),
               self.state.table_index("acl_roles"))
        with self._lock:
            if key != self._cache_key:
                self._cache = {}
                self._cache_key = key
        token = self.state.acl_token_by_secret(secret_id)
        if token is None or token.is_expired():
            return None, None
        if token.type == ACL_TOKEN_TYPE_MANAGEMENT:
            return MANAGEMENT_ACL, token
        cache_id = token.accessor_id
        with self._lock:
            if key == self._cache_key and cache_id in self._cache:
                return self._cache[cache_id], token
        # direct policy links, plus policies reached through role links
        # (reference: ACLToken.Roles -> ACLRole.Policies union)
        names = list(token.policies)
        for role_name in getattr(token, "roles", []) or []:
            role = self.state.acl_role_by_name(role_name)
            if role is not None:
                names.extend(role.policies)
        policies = []
        seen = set()
        for name in names:
            if name in seen:
                continue
            seen.add(name)
            stored = self.state.acl_policy_by_name(name)
            if stored is not None:
                policies.append(parse_policy(stored.name, stored.rules))
        compiled = ACL(policies=policies)
        with self._lock:
            if key == self._cache_key:
                self._cache[cache_id] = compiled
        return compiled, token
