"""Compiled ACLs: merge policies into capability sets and answer
authorization questions.

Semantic parity with the reference's compiler (reference: acl/acl.go:106
NewACL -- merges policies; deny wins; namespace rules matched by exact
name first, then longest glob). Instead of the reference's radix tree we
keep a dict of exact rules plus an ordered glob list -- clusters have
few policies, correctness over micro-optimisation.
"""
from __future__ import annotations

from fnmatch import fnmatchcase
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .policy import (
    CAP_DENY, POLICY_DENY, POLICY_LIST, POLICY_READ, POLICY_WRITE,
    Policy, VariablePathRule,
)


def _merge_coarse(cur: str, new: str) -> str:
    """deny > write > list > read > '' (reference: acl.go maxPrivilege)."""
    order = {POLICY_DENY: 4, POLICY_WRITE: 3, POLICY_LIST: 2,
             POLICY_READ: 1, "": 0}
    return new if order.get(new, 0) > order.get(cur, 0) else cur


class ACL:
    """An immutable, compiled ACL (reference: acl/acl.go ACL)."""

    def __init__(self, management: bool = False,
                 policies: Iterable[Policy] = ()):
        self.management = management
        # namespace -> capability set (CAP_DENY sticky)
        self._ns_exact: Dict[str, Set[str]] = {}
        self._ns_glob: Dict[str, Set[str]] = {}
        self._ns_variables: Dict[str, List[VariablePathRule]] = {}
        self._hv_exact: Dict[str, Set[str]] = {}
        self._hv_glob: Dict[str, Set[str]] = {}
        self.node = ""
        self.agent = ""
        self.operator = ""
        self.quota = ""
        self.plugin = ""
        for pol in policies:
            self._merge(pol)

    def _merge(self, pol: Policy) -> None:
        for rule in pol.namespaces:
            table = (self._ns_glob if "*" in rule.name else self._ns_exact)
            caps = table.setdefault(rule.name, set())
            for cap in rule.all_capabilities():
                caps.add(cap)
            if rule.variables:
                self._ns_variables.setdefault(
                    rule.name, []).extend(rule.variables)
        for hv in pol.host_volumes:
            table = (self._hv_glob if "*" in hv.name else self._hv_exact)
            caps = table.setdefault(hv.name, set())
            if hv.policy == POLICY_READ:
                caps.add("mount-readonly")
            elif hv.policy == POLICY_WRITE:
                caps.update(("mount-readonly", "mount-readwrite"))
            elif hv.policy == POLICY_DENY:
                caps.add(CAP_DENY)
            caps.update(hv.capabilities)
        self.node = _merge_coarse(self.node, pol.node)
        self.agent = _merge_coarse(self.agent, pol.agent)
        self.operator = _merge_coarse(self.operator, pol.operator)
        self.quota = _merge_coarse(self.quota, pol.quota)
        self.plugin = _merge_coarse(self.plugin, pol.plugin)

    # -- namespace capabilities ----------------------------------------
    def _ns_caps(self, ns: str) -> Optional[Set[str]]:
        """Exact match wins; else the longest (most specific) glob match
        (reference: acl.go AllowNamespaceOperation -> findClosestMatching)."""
        if ns in self._ns_exact:
            return self._ns_exact[ns]
        best: Optional[Tuple[int, str]] = None
        for pattern in self._ns_glob:
            if fnmatchcase(ns, pattern):
                key = (len(pattern.replace("*", "")), pattern)
                if best is None or key > best:
                    best = key
        return self._ns_glob[best[1]] if best else None

    def allow_namespace_op(self, ns: str, cap: str) -> bool:
        if self.management:
            return True
        caps = self._ns_caps(ns)
        if caps is None or CAP_DENY in caps:
            return False
        return cap in caps

    def allow_any_namespace(self, cap: str) -> bool:
        """True when ANY namespace rule grants the capability -- used by
        list endpoints with ?namespace=* (reference: acl.go
        AllowNsOpFunc over the wildcard namespace)."""
        if self.management:
            return True
        for caps in list(self._ns_exact.values()) + \
                list(self._ns_glob.values()):
            if cap in caps and CAP_DENY not in caps:
                return True
        return False

    def allow_namespace(self, ns: str) -> bool:
        """Any capability at all in the namespace (reference:
        acl.go AllowNamespace)."""
        if self.management:
            return True
        caps = self._ns_caps(ns)
        return bool(caps) and CAP_DENY not in caps

    # -- variables path capabilities -----------------------------------
    def allow_variable_op(self, ns: str, path: str, cap: str) -> bool:
        """Variables are gated per path glob inside the namespace rule;
        fall back to the namespace-level variables-* capabilities
        (reference: acl.go AllowVariableOperation)."""
        if self.management:
            return True
        rules: List[VariablePathRule] = []
        if ns in self._ns_variables:
            rules = self._ns_variables[ns]
        else:
            best: Optional[Tuple[int, str]] = None
            for pattern in self._ns_variables:
                if "*" in pattern and fnmatchcase(ns, pattern):
                    key = (len(pattern.replace("*", "")), pattern)
                    if best is None or key > best:
                        best = key
            if best:
                rules = self._ns_variables[best[1]]
        best_rule: Optional[Tuple[int, VariablePathRule]] = None
        for rule in rules:
            if fnmatchcase(path, rule.path):
                key = len(rule.path.replace("*", ""))
                if best_rule is None or key > best_rule[0]:
                    best_rule = (key, rule)
        if best_rule is not None:
            # capabilities are pre-expanded at parse time
            # (policy.py expand_variables_capabilities), so membership is
            # the whole check; deny is sticky
            caps = best_rule[1].capabilities
            if "deny" in caps:
                return False
            return cap in caps
        # fall back to namespace-wide variables capabilities
        return self.allow_namespace_op(ns, f"variables-{cap}")

    # -- host volumes --------------------------------------------------
    def allow_host_volume_op(self, name: str, cap: str) -> bool:
        if self.management:
            return True
        caps = self._hv_exact.get(name)
        if caps is None:
            best: Optional[Tuple[int, str]] = None
            for pattern in self._hv_glob:
                if fnmatchcase(name, pattern):
                    key = (len(pattern.replace("*", "")), pattern)
                    if best is None or key > best:
                        best = key
            caps = self._hv_glob[best[1]] if best else None
        if caps is None or CAP_DENY in caps:
            return False
        return cap in caps

    # -- coarse domains ------------------------------------------------
    def _coarse(self, level: str, need: str) -> bool:
        if self.management:
            return True
        if level == POLICY_DENY:
            return False
        if need == POLICY_READ:
            return level in (POLICY_READ, POLICY_WRITE)
        if need == POLICY_LIST:
            return level in (POLICY_LIST, POLICY_READ, POLICY_WRITE)
        return level == POLICY_WRITE

    def allow_node_read(self) -> bool:
        return self._coarse(self.node, POLICY_READ)

    def allow_node_write(self) -> bool:
        return self._coarse(self.node, POLICY_WRITE)

    def allow_agent_read(self) -> bool:
        return self._coarse(self.agent, POLICY_READ)

    def allow_agent_write(self) -> bool:
        return self._coarse(self.agent, POLICY_WRITE)

    def allow_operator_read(self) -> bool:
        return self._coarse(self.operator, POLICY_READ)

    def allow_operator_write(self) -> bool:
        return self._coarse(self.operator, POLICY_WRITE)

    def allow_quota_read(self) -> bool:
        return self._coarse(self.quota, POLICY_READ)

    def allow_quota_write(self) -> bool:
        return self._coarse(self.quota, POLICY_WRITE)

    def allow_plugin_read(self) -> bool:
        return self._coarse(self.plugin, POLICY_READ)

    def allow_plugin_list(self) -> bool:
        return self._coarse(self.plugin, POLICY_LIST)

    def is_management(self) -> bool:
        return self.management


MANAGEMENT_ACL = ACL(management=True)
# An anonymous request with ACLs enabled and no token: deny-all compiled ACL
ANONYMOUS_ACL = ACL(management=False)
