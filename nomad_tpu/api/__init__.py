"""HTTP API + dev agent (reference: /root/reference/command/agent/)."""
from .http import HttpServer, job_from_json, to_jsonable  # noqa: F401
