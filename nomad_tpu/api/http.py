"""HTTP API: the /v1/* surface (reference:
/root/reference/command/agent/http.go:382 registerHandlers + per-resource
endpoint files). JSON in/out; blocking queries via ?index=N&wait=Ns exactly
like the reference's blocking-query contract (nomad/rpc.go:852).
"""
from __future__ import annotations

import dataclasses
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..structs import (
    Constraint, EphemeralDisk, Job, NetworkResource, Port, ReschedulePolicy,
    Resources, RestartPolicy, SchedulerConfiguration, Service, Spread,
    SpreadTarget, Task, TaskGroup, UpdateStrategy, Affinity,
    ParameterizedJobConfig, PeriodicConfig,
)


def _thread_stacks():
    """Every thread's current stack (the pprof 'goroutine' analog,
    reference: command/agent/pprof/pprof.go)."""
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append({
            "thread": names.get(ident, str(ident)),
            "frames": [f"{f.filename}:{f.lineno} {f.name}"
                       for f in traceback.extract_stack(frame)],
        })
    return out


def _sample_profile(seconds: float, hz: int):
    """Statistical CPU profile: sample every thread's stack at `hz` for
    `seconds`, aggregate by innermost frames (the pprof 'profile'
    analog). Pure-Python sampling, no signals -- safe under threads."""
    import sys
    import time as _t
    from collections import Counter

    counts: Counter = Counter()
    interval = 1.0 / max(hz, 1)
    deadline = _t.monotonic() + seconds
    n = 0
    while _t.monotonic() < deadline:
        for frame in sys._current_frames().values():
            key_parts = []
            f = frame
            depth = 0
            while f is not None and depth < 3:
                key_parts.append(f"{f.f_code.co_filename.rsplit('/', 1)[-1]}"
                                 f":{f.f_lineno} {f.f_code.co_name}")
                f = f.f_back
                depth += 1
            counts[" < ".join(key_parts)] += 1
        n += 1
        _t.sleep(interval)
    top = counts.most_common(50)
    return {"samples": n, "hz": hz, "seconds": seconds,
            "top": [{"stack": k, "count": v} for k, v in top]}


def to_jsonable(obj):
    hydrate = getattr(obj, "__nomad_hydrate__", None)
    if hydrate is not None:
        # lazy struct stub (structs.alloc.LazyAllocMetric): an API read
        # is a first struct access -- render the hydrated record
        obj = hydrate()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: to_jsonable(v)
                for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, dict):
        return {str(k): to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_jsonable(v) for v in obj]
    if isinstance(obj, bytes):
        return obj.decode("utf-8", "replace")
    return obj


def job_from_json(data: dict) -> Job:
    """Parse the JSON jobspec (the reference's api.Job JSON shape,
    snake_cased; jobspec2 HCL parsing maps to the same structure)."""
    def build(cls, src, **overrides):
        fields = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in (src or {}).items() if k in fields}
        kwargs.update(overrides)
        return cls(**kwargs)

    tgs = []
    for tg_src in data.get("task_groups", []):
        tasks = []
        for t_src in tg_src.get("tasks", []):
            res_src = t_src.get("resources", {})
            networks = [
                build(NetworkResource, n,
                      reserved_ports=[build(Port, p) for p in
                                      n.get("reserved_ports", [])],
                      dynamic_ports=[build(Port, p) for p in
                                     n.get("dynamic_ports", [])])
                for n in res_src.get("networks", [])]
            resources = build(Resources, res_src, networks=networks,
                              devices=[])
            tasks.append(build(
                Task, t_src, resources=resources,
                constraints=[build(Constraint, c)
                             for c in t_src.get("constraints", [])],
                affinities=[build(Affinity, a)
                            for a in t_src.get("affinities", [])],
                services=[build(Service, s)
                          for s in t_src.get("services", [])]))
        networks = [
            build(NetworkResource, n,
                  reserved_ports=[build(Port, p)
                                  for p in n.get("reserved_ports", [])],
                  dynamic_ports=[build(Port, p)
                                 for p in n.get("dynamic_ports", [])])
            for n in tg_src.get("networks", [])]
        tg = build(
            TaskGroup, tg_src, tasks=tasks, networks=networks,
            services=[build(Service, s)
                      for s in tg_src.get("services", [])],
            constraints=[build(Constraint, c)
                         for c in tg_src.get("constraints", [])],
            affinities=[build(Affinity, a)
                        for a in tg_src.get("affinities", [])],
            spreads=[build(Spread, s,
                           spread_target=[build(SpreadTarget, t)
                                          for t in s.get("spread_target", [])])
                     for s in tg_src.get("spreads", [])],
            update=(build(UpdateStrategy, tg_src["update"])
                    if tg_src.get("update") else None),
            restart_policy=build(RestartPolicy,
                                 tg_src.get("restart_policy", {})),
            reschedule_policy=(build(ReschedulePolicy,
                                     tg_src["reschedule_policy"])
                               if tg_src.get("reschedule_policy") else None),
            ephemeral_disk=build(EphemeralDisk,
                                 tg_src.get("ephemeral_disk", {})),
            volumes={}, scaling=tg_src.get("scaling"), migrate=None)
        tgs.append(tg)
    job = Job(
        id=data.get("id", ""),
        name=data.get("name", data.get("id", "")),
        namespace=data.get("namespace", "default"),
        type=data.get("type", "service"),
        priority=int(data.get("priority", 50)),
        all_at_once=bool(data.get("all_at_once", False)),
        datacenters=data.get("datacenters", ["*"]),
        node_pool=data.get("node_pool", "default"),
        constraints=[Constraint(**{k: v for k, v in c.items()
                                   if k in ("l_target", "r_target", "operand")})
                     for c in data.get("constraints", [])],
        affinities=[Affinity(**{k: v for k, v in a.items()
                                if k in ("l_target", "r_target", "operand",
                                         "weight")})
                    for a in data.get("affinities", [])],
        spreads=[],
        task_groups=tgs,
        meta=data.get("meta", {}),
    )
    if data.get("update"):
        fields = {f.name for f in dataclasses.fields(UpdateStrategy)}
        job.update = UpdateStrategy(**{k: v for k, v in data["update"].items()
                                       if k in fields})
    if data.get("periodic"):
        fields = {f.name for f in dataclasses.fields(PeriodicConfig)}
        job.periodic = PeriodicConfig(
            **{k: v for k, v in data["periodic"].items() if k in fields})
    if data.get("parameterized"):
        fields = {f.name for f in dataclasses.fields(ParameterizedJobConfig)}
        job.parameterized = ParameterizedJobConfig(
            **{k: v for k, v in data["parameterized"].items()
               if k in fields})
    return job


class ApiHandler(BaseHTTPRequestHandler):
    server_version = "nomad-tpu/0.1"
    protocol_version = "HTTP/1.1"

    # quiet logs
    def log_message(self, fmt, *args):
        pass

    @property
    def nomad(self):
        return self.server.nomad_server

    def _maybe_forward(self) -> bool:
        """Cross-region forwarding: ?region=X for a foreign region relays
        the whole request to a server of that region and streams the
        response back (reference: nomad/rpc.go forwardRegion). Returns
        True when the request was handled here."""
        q = parse_qs(urlparse(self.path).query)
        region = q.get("region", [None])[0]
        if not region or region == self.nomad.region:
            return False
        addr = self.nomad.forward_address(region)
        if addr is None:
            self._error(404, f"unknown region {region!r}")
            return True
        # unbounded streams can't be relayed through the buffering
        # forwarder -- clients must connect to that region directly
        parsed = urlparse(self.path)
        if (parsed.path == "/v1/event/stream"
                and q.get("poll", ["false"])[0] != "true") or \
                parsed.path == "/v1/agent/monitor" or \
                (parsed.path.startswith("/v1/client/fs/logs/")
                 and q.get("follow", ["false"])[0] == "true"):
            self._error(
                400, f"{parsed.path} cannot be forwarded; connect to "
                     f"region {region!r} at {addr} directly")
            return True
        import urllib.error
        import urllib.request
        length = int(self.headers.get("Content-Length", 0) or 0)
        body = self.rfile.read(length) if length else None
        req = urllib.request.Request(
            f"{addr}{self.path}", method=self.command, data=body,
            headers={k: v for k, v in self.headers.items()
                     if k.lower() in ("content-type", "x-nomad-token")})
        try:
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                data = resp.read()
                self.send_response(resp.status)
                ctype = resp.headers.get("Content-Type",
                                         "application/json")
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
        except urllib.error.HTTPError as e:
            data = e.read()
            self.send_response(e.code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        except OSError as e:
            self._error(502, f"region {region!r} unreachable: {e}")
        return True

    def _client_for_csi_plugin(self, plugin_id: str):
        """A client serving this controller plugin: in-process first,
        then any node advertising it healthy + a client listener."""
        for c in getattr(self.server, "local_clients", []):
            mgr = getattr(c, "csi_manager", None)
            if mgr is not None and plugin_id in mgr.plugins:
                return c
        for node in self.nomad.state.nodes():
            health = (node.csi_node_plugins or {}).get(plugin_id, {})
            addr = (node.attributes or {}).get("nomad.client_http", "")
            if health.get("healthy") and addr:
                from ..client.http import RemoteClientProxy
                return RemoteClientProxy(addr)
        return None

    def _client_for_alloc(self, alloc_id: str):
        """-> (client, alloc) serving the alloc's fs, or (None, alloc).
        Falls back to the node's advertised client-agent listener
        (reference: server->client RPC forwarding, nomad/client_rpc.go)
        when the alloc's node is not served in-process."""
        alloc = self.nomad.state.alloc_by_id(alloc_id)
        if alloc is None:
            return None, None
        for c in getattr(self.server, "local_clients", []):
            if c.node.id == alloc.node_id:
                return c, alloc
        node = self.nomad.state.node_by_id(alloc.node_id)
        addr = (node.attributes or {}).get("nomad.client_http", "") \
            if node is not None else ""
        if addr:
            from ..client.http import RemoteClientProxy
            return RemoteClientProxy(addr), alloc
        return None, alloc

    # ------------------------------------------------------------------
    def _send(self, code: int, payload, index: Optional[int] = None) -> None:
        body = json.dumps(to_jsonable(payload)).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if index is not None:
            self.send_header("X-Nomad-Index", str(index))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, msg: str) -> None:
        self._send(code, {"error": msg})

    def _body(self):
        length = int(self.headers.get("Content-Length", 0) or 0)
        if not length:
            return {}
        return json.loads(self.rfile.read(length) or b"{}")

    # -- ACL enforcement (reference: command/agent/http.go wrap() pulls the
    #    token; each RPC endpoint checks capabilities) ----------------------
    def _acl(self):
        secret = self.headers.get("X-Nomad-Token", "")
        if not secret:
            q = parse_qs(urlparse(self.path).query)
            if "token" in q:
                secret = q["token"][0]
        compiled, _token = self.nomad.resolve_token(secret or None)
        return compiled

    def _check(self, allowed: bool) -> bool:
        """False (and a 403 already sent) when the request is denied."""
        if allowed:
            return True
        self._error(403, "Permission denied")
        return False

    def _blocking(self, query, tables=()) -> int:
        """Apply ?index/?wait blocking semantics; returns current index."""
        q = parse_qs(query)
        if "index" in q:
            min_index = int(q["index"][0])
            wait = 5.0
            if "wait" in q:
                wait = float(q["wait"][0].rstrip("s"))
            # cap like the reference's MaxBlockingRPCQueryTime so a client
            # can't pin a handler thread arbitrarily long
            wait = min(wait, 300.0)
            return self.nomad.state.block_until(min_index, timeout=wait,
                                                tables=tables)
        return self.nomad.state.latest_index()

    # ------------------------------------------------------------------
    # -- web UI (reference: /root/reference/ui/ Ember app served by the
    #    agent; here a no-build vanilla-JS SPA in nomad_tpu/ui/) ----------
    _UI_TYPES = {".html": "text/html; charset=utf-8",
                 ".js": "application/javascript; charset=utf-8",
                 ".css": "text/css; charset=utf-8",
                 ".svg": "image/svg+xml"}

    def _serve_ui(self, parts) -> None:
        import os
        ui_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "ui")
        name = parts[1] if len(parts) > 1 else "index.html"
        # flat directory, no traversal
        name = os.path.basename(name)
        path = os.path.join(ui_dir, name)
        if not os.path.isfile(path):
            # all client routing lives under '#', so only the bare /ui
            # (or /) ever legitimately wants index.html -- a missing
            # asset must 404, not masquerade as HTML
            if len(parts) > 1 and name != "index.html":
                self._error(404, f"no such ui asset: {name}")
                return
            path = os.path.join(ui_dir, "index.html")
            name = "index.html"
        ext = os.path.splitext(name)[1]
        try:
            with open(path, "rb") as f:
                body = f.read()
        except OSError:
            self._error(404, "ui not bundled")
            return
        try:
            self.send_response(200)
            self.send_header(
                "Content-Type",
                self._UI_TYPES.get(ext, "application/octet-stream"))
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass                # browser aborted mid-transfer; routine

    def do_GET(self):  # noqa: N802
        if self._maybe_forward():
            return
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        if not parts or parts[0] == "ui":
            return self._serve_ui(parts)
        state = self.nomad.state
        try:
            # the node alloc watch blocks on the allocs table only, so
            # unrelated writes don't wake every polling node
            tables = (("allocs",) if parts[:2] == ["v1", "node"]
                      and len(parts) == 4 and parts[3] == "allocations"
                      else ())
            q = parse_qs(url.query)
            ns = q.get("namespace", ["default"])[0]
            acl = self._acl()
            from ..acl import CAP_LIST_JOBS, CAP_READ_JOB
            # authorize BEFORE the blocking wait so a denied request can't
            # pin a server thread for the full ?wait duration; namespaced
            # single resources are re-checked against the RESOURCE's
            # namespace after fetch (reference: endpoints resolve the
            # object, then check caps in its namespace)
            if parts[:2] == ["v1", "acl"]:
                # management pre-gate (except token/self) so denied ACL
                # reads can't sit in the blocking wait
                if parts != ["v1", "acl", "token", "self"] and \
                        not self._check(acl.is_management()):
                    return
                index = self._blocking(url.query, tables)
                return self._acl_get(parts, acl, index)
            if parts[1:2] == ["operator"]:
                if not self._check(acl.allow_operator_read()):
                    return
            elif parts[:2] in (["v1", "nodes"], ["v1", "node"]):
                if not self._check(acl.allow_node_read()):
                    return
            elif parts[:2] == ["v1", "job"]:
                # job reads are namespaced lookups: query-ns == resource-ns
                if not self._check(acl.allow_namespace_op(ns, CAP_READ_JOB)):
                    return
            elif parts[:2] in (["v1", "jobs"], ["v1", "evaluations"],
                               ["v1", "allocations"], ["v1", "deployments"]):
                # list endpoints: deny outright when the token has no access
                # in the request namespace (unless asking for ns=*); matched
                # results are additionally filtered per-item below
                cap = (CAP_LIST_JOBS if parts[1] == "jobs" else CAP_READ_JOB)
                allowed = (acl.allow_any_namespace(cap) if ns == "*"
                           else acl.allow_namespace_op(ns, cap))
                if not self._check(allowed):
                    return
            elif parts[:2] in (["v1", "evaluation"], ["v1", "allocation"]):
                # cheap pre-gate before the blocking wait; the exact
                # resource-namespace check still runs after fetch
                if not self._check(acl.allow_any_namespace(CAP_READ_JOB)):
                    return
            elif parts[:2] in (["v1", "services"], ["v1", "service"]):
                # pre-gate before the blocking wait (like the list
                # endpoints above); exact per-object checks run after
                allowed = (acl.allow_any_namespace(CAP_READ_JOB)
                           if ns == "*" else
                           acl.allow_namespace_op(ns, CAP_READ_JOB))
                if not self._check(allowed):
                    return
            elif parts[:2] == ["v1", "scaling"]:
                from ..acl import CAP_LIST_SCALING_POLICIES
                allowed = (acl.allow_any_namespace(CAP_LIST_SCALING_POLICIES)
                           if ns == "*" else acl.allow_namespace_op(
                               ns, CAP_LIST_SCALING_POLICIES))
                if not self._check(allowed):
                    return
            elif parts == ["v1", "event", "stream"]:
                if not self._check(acl.allow_any_namespace(CAP_READ_JOB)):
                    return
                if q.get("poll", ["false"])[0] != "true":
                    # live stream: ?index is the replay point, NOT a
                    # blocking-query parameter -- dispatch immediately
                    return self._stream_events(
                        q, int(q.get("index", ["0"])[0]))
            elif parts[:2] == ["v1", "agent"] and parts[2:3] != ["health"]:
                if not self._check(acl.allow_agent_read()):
                    return
            elif parts == ["v1", "metrics"]:
                if not self._check(acl.allow_agent_read()):
                    return
            index = self._blocking(url.query, tables)
            if parts[:2] == ["v1", "jobs"] and len(parts) == 2:
                # ?prefix= filtering like every reference list endpoint
                prefix = q.get("prefix", [""])[0]
                self._send(200, [self._job_stub(j) for j in state.jobs()
                                 if j.id.startswith(prefix)
                                 and acl.allow_namespace_op(
                                     j.namespace, CAP_LIST_JOBS)], index)
            elif parts[:2] == ["v1", "job"] and len(parts) == 3:
                job = state.job_by_id(ns, parts[2])
                if job is None:
                    return self._error(404, "job not found")
                self._send(200, job, index)
            elif parts[:2] == ["v1", "job"] and len(parts) == 4 and \
                    parts[3] == "allocations":
                self._send(200, state.allocs_by_job(ns, parts[2]), index)
            elif parts[:2] == ["v1", "job"] and len(parts) == 4 and \
                    parts[3] == "evaluations":
                self._send(200, state.evals_by_job(ns, parts[2]), index)
            elif parts[:2] == ["v1", "job"] and len(parts) == 4 and \
                    parts[3] == "summary":
                # (reference: structs.JobSummary, maintained by the state
                # store; equivalent here computed on read from allocs +
                # the latest eval's queued counts)
                job = state.job_by_id(ns, parts[2])
                if job is None:
                    return self._error(404, "job not found")
                summary = {tg.name: {
                    "queued": 0, "starting": 0, "running": 0,
                    "complete": 0, "failed": 0, "lost": 0, "unknown": 0,
                } for tg in job.task_groups}
                for a in state.allocs_by_job(ns, parts[2]):
                    row = summary.get(a.task_group)
                    if row is None:
                        continue
                    cs = a.client_status or "pending"
                    key = {"pending": "starting", "running": "running",
                           "complete": "complete", "failed": "failed",
                           "lost": "lost", "unknown": "unknown"}.get(
                               cs, "unknown")
                    if a.server_terminal_status() and key in (
                            "starting", "running"):
                        continue
                    row[key] += 1
                evs = sorted(state.evals_by_job(ns, parts[2]),
                             key=lambda e: e.modify_index, reverse=True)
                if evs and evs[0].queued_allocations:
                    for tg_name, n_q in evs[0].queued_allocations.items():
                        if tg_name in summary:
                            summary[tg_name]["queued"] = int(n_q)
                self._send(200, {"job_id": parts[2], "namespace": ns,
                                 "summary": summary}, index)
            elif parts[:2] == ["v1", "job"] and len(parts) == 4 and \
                    parts[3] == "deployment":
                self._send(200, state.latest_deployment_by_job(ns, parts[2]),
                           index)
            elif parts[:2] == ["v1", "job"] and len(parts) == 4 and \
                    parts[3] == "versions":
                versions = self.nomad.job_versions(ns, parts[2])
                if not versions:
                    return self._error(404, "job not found")
                self._send(200, {"versions": versions}, index)
            elif parts[:2] == ["v1", "job"] and len(parts) == 4 and \
                    parts[3] == "scale":
                status = self.nomad.job_scale_status(ns, parts[2])
                if status is None:
                    return self._error(404, "job not found")
                self._send(200, status, index)
            elif parts == ["v1", "scaling", "policies"]:
                job_filter = q.get("job", [None])[0]
                pols = state.scaling_policies(None if ns == "*" else ns)
                if job_filter:
                    pols = [p for p in pols if p.job_id == job_filter]
                self._send(200, pols, index)
            elif parts[:3] == ["v1", "scaling", "policy"] and len(parts) == 4:
                pol = state.scaling_policy_by_id(parts[3])
                if pol is None:
                    return self._error(404, "policy not found")
                # re-check against the POLICY's namespace (ids are
                # guessable; the pre-gate only saw the query namespace)
                from ..acl import CAP_READ_SCALING_POLICY
                if not self._check(acl.allow_namespace_op(
                        pol.namespace, CAP_READ_SCALING_POLICY)):
                    return
                self._send(200, pol, index)
            elif parts[:2] == ["v1", "evaluations"]:
                prefix = q.get("prefix", [""])[0]
                self._send(200, [e for e in state.evals()
                                 if e.id.startswith(prefix)
                                 and acl.allow_namespace_op(
                                     e.namespace, CAP_READ_JOB)], index)
            elif parts[:2] == ["v1", "evaluation"] and len(parts) == 3:
                ev = state.eval_by_id(parts[2])
                if ev is None:
                    return self._error(404, "eval not found")
                if not self._check(acl.allow_namespace_op(ev.namespace,
                                                          CAP_READ_JOB)):
                    return
                self._send(200, ev, index)
            elif parts[:2] == ["v1", "evaluation"] and len(parts) == 4 \
                    and parts[3] == "allocations":
                # (reference: eval_endpoint.go Allocations)
                ev = state.eval_by_id(parts[2])
                if ev is None:
                    return self._error(404, "eval not found")
                if not self._check(acl.allow_namespace_op(ev.namespace,
                                                          CAP_READ_JOB)):
                    return
                self._send(200, [a for a in state.allocs()
                                 if a.eval_id == parts[2]], index)
            elif parts[:2] == ["v1", "allocations"]:
                prefix = q.get("prefix", [""])[0]
                if prefix:
                    return self._send(
                        200, [a for a in state.allocs()
                              if a.id.startswith(prefix)
                              and acl.allow_namespace_op(
                                  a.namespace, CAP_READ_JOB)], index)
                self._send(200, [a for a in state.allocs()
                                 if acl.allow_namespace_op(
                                     a.namespace, CAP_READ_JOB)], index)
            elif parts[:2] == ["v1", "allocation"] and len(parts) == 3:
                a = state.alloc_by_id(parts[2])
                if a is None:
                    return self._error(404, "alloc not found")
                if not self._check(acl.allow_namespace_op(a.namespace,
                                                          CAP_READ_JOB)):
                    return
                self._send(200, a, index)
            elif parts[:2] == ["v1", "nodes"]:
                self._send(200, [self._node_stub(n) for n in state.nodes()],
                           index)
            elif parts[:2] == ["v1", "node"] and len(parts) == 3 and \
                    parts[2] not in ("pools", "pool"):
                n = state.node_by_id(parts[2])
                if n is None:
                    return self._error(404, "node not found")
                self._send(200, n, index)
            elif parts[:2] == ["v1", "deployments"]:
                self._send(200, [d for d in state.deployments()
                                 if acl.allow_namespace_op(
                                     d.namespace, CAP_READ_JOB)], index)
            elif parts[:3] == ["v1", "client", "fs"] and len(parts) == 5:
                # /v1/client/fs/{ls|cat|readat|stat}/:alloc (reference:
                # command/agent/fs_endpoint.go over client forwarding)
                from ..acl import CAP_READ_FS
                op, alloc_id = parts[3], parts[4]
                client, alloc = self._client_for_alloc(alloc_id)
                if alloc is None:
                    return self._error(404, "alloc not found")
                if not self._check(acl.allow_namespace_op(
                        alloc.namespace, CAP_READ_FS)):
                    return
                if client is None:
                    return self._error(
                        501, "alloc's node is not served by this agent")
                path = q.get("path", ["/"])[0]
                try:
                    if op == "ls":
                        return self._send(200, client.fs_list(alloc_id,
                                                              path))
                    if op == "stat":
                        return self._send(200, client.fs_stat(alloc_id,
                                                              path))
                    if op in ("cat", "readat"):
                        # same explicit verdict the follow path gives
                        # (ADVICE low #2): a garbled query param is a
                        # client error, never a 500 / raw int() message
                        try:
                            offset = int(q.get("offset", ["0"])[0])
                        except ValueError:
                            return self._error(
                                400, "offset must be numeric")
                        try:
                            limit = int(q.get("limit",
                                              [str(1 << 20)])[0])
                        except ValueError:
                            return self._error(
                                400, "limit must be numeric")
                        data = client.fs_read(alloc_id, path, offset,
                                              limit)
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/octet-stream")
                        self.send_header("Content-Length", str(len(data)))
                        self.end_headers()
                        self.wfile.write(data)
                        return
                    return self._error(404, f"unknown fs op {op}")
                except KeyError as e:
                    return self._error(404, str(e))
                except PermissionError as e:
                    return self._error(403, str(e))
                except (OSError, ValueError) as e:
                    return self._error(400, str(e))
            elif parts[:3] == ["v1", "client", "allocation"] and \
                    len(parts) == 5 and parts[4] == "stats":
                # live task resource usage (reference: client
                # Allocations.Stats via server->client forwarding)
                from ..acl import CAP_READ_JOB
                client, alloc = self._client_for_alloc(parts[3])
                if alloc is None:
                    return self._error(404, "alloc not found")
                if not self._check(acl.allow_namespace_op(
                        alloc.namespace, CAP_READ_JOB)):
                    return
                if client is None:
                    return self._error(
                        501, "alloc's node is not served by this agent")
                try:
                    return self._send(200, client.alloc_stats(parts[3]))
                except KeyError as e:
                    return self._error(404, str(e))
            elif parts[:3] == ["v1", "client", "fs"] and len(parts) == 6 \
                    and parts[3] == "logs":
                from ..acl import CAP_READ_LOGS
                alloc_id, task = parts[4], parts[5]
                client, alloc = self._client_for_alloc(alloc_id)
                if alloc is None:
                    return self._error(404, "alloc not found")
                if not self._check(acl.allow_namespace_op(
                        alloc.namespace, CAP_READ_LOGS)):
                    return
                if client is None:
                    return self._error(
                        501, "alloc's node is not served by this agent")
                log_type = q.get("type", ["stdout"])[0]
                if q.get("follow", ["false"])[0] == "true":
                    try:
                        offset = int(q.get("offset", ["0"])[0])
                    except ValueError:
                        return self._error(400, "offset must be numeric")
                    return self._stream_log_follow(
                        client, alloc_id, task, log_type, offset)
                # non-follow path: same numeric validation as the
                # follow path above (ADVICE low #2)
                try:
                    offset = int(q.get("offset", ["0"])[0])
                except ValueError:
                    return self._error(400, "offset must be numeric")
                try:
                    limit = int(q.get("limit", [str(1 << 20)])[0])
                except ValueError:
                    return self._error(400, "limit must be numeric")
                try:
                    data = client.fs_logs(
                        alloc_id, task, log_type, offset, limit)
                except KeyError as e:
                    return self._error(404, str(e))
                except (OSError, ValueError, PermissionError) as e:
                    return self._error(400, str(e))
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            elif parts == ["v1", "client", "stats"]:
                if not self._check(acl.allow_node_read()):
                    return
                node_id = q.get("node_id", [""])[0]
                for c in getattr(self.server, "local_clients", []):
                    if not node_id or c.node.id == node_id:
                        return self._send(200, c.client_stats())
                if node_id:
                    node = self.nomad.state.node_by_id(node_id)
                    addr = (node.attributes or {}).get(
                        "nomad.client_http", "") if node else ""
                    if addr:
                        from ..client.http import RemoteClientProxy
                        try:
                            return self._send(
                                200,
                                RemoteClientProxy(addr).client_stats())
                        except OSError as e:
                            return self._error(502, str(e))
                return self._error(
                    501, "no matching client served by this agent")
            elif parts == ["v1", "services"]:
                if not self._check(acl.allow_any_namespace(CAP_READ_JOB)
                                   if ns == "*" else
                                   acl.allow_namespace_op(ns, CAP_READ_JOB)):
                    return
                names = self.nomad.service_names(None if ns == "*" else ns)
                self._send(200, [n for n in names
                                 if acl.allow_namespace_op(
                                     n["namespace"], CAP_READ_JOB)], index)
            elif parts[:2] == ["v1", "service"] and len(parts) == 3:
                if ns == "*":
                    if not self._check(
                            acl.allow_any_namespace(CAP_READ_JOB)):
                        return
                    regs = [r for r in state.service_registrations(None)
                            if r.service_name == parts[2]
                            and acl.allow_namespace_op(r.namespace,
                                                       CAP_READ_JOB)]
                    return self._send(200, regs, index)
                if not self._check(acl.allow_namespace_op(ns,
                                                          CAP_READ_JOB)):
                    return
                self._send(200, state.services_by_name(ns, parts[2]), index)
            elif parts == ["v1", "volumes"]:
                from ..acl import CAP_CSI_LIST_VOLUME
                allowed = (acl.allow_any_namespace(CAP_CSI_LIST_VOLUME)
                           if ns == "*" else acl.allow_namespace_op(
                               ns, CAP_CSI_LIST_VOLUME))
                if not self._check(allowed):
                    return
                vols = state.csi_volumes(None if ns == "*" else ns)
                self._send(200, [self._volume_stub(v) for v in vols
                                 if acl.allow_namespace_op(
                                     v.namespace, CAP_CSI_LIST_VOLUME)],
                           index)
            elif parts[:3] == ["v1", "volume", "csi"] and len(parts) == 4:
                from ..acl import CAP_CSI_READ_VOLUME
                if not self._check(acl.allow_namespace_op(
                        ns, CAP_CSI_READ_VOLUME)):
                    return
                v = state.csi_volume_by_id(ns, parts[3])
                if v is None:
                    return self._error(404, "volume not found")
                self._send(200, v, index)
            elif parts == ["v1", "plugins"]:
                self._send(200, state.csi_plugins(), index)
            elif parts[:3] == ["v1", "plugin", "csi"] and len(parts) == 4:
                p = state.csi_plugin_by_id(parts[3])
                if p is None:
                    return self._error(404, "plugin not found")
                self._send(200, p, index)
            elif parts == ["v1", "namespaces"]:
                self._send(200, [n for n in state.namespaces()
                                 if acl.allow_namespace(n.name)], index)
            elif parts[:2] == ["v1", "namespace"] and len(parts) == 3:
                # ACL first: a 403-vs-404 difference would leak existence
                if not self._check(acl.allow_namespace(parts[2])):
                    return
                n = state.namespace_by_name(parts[2])
                if n is None:
                    return self._error(404, "namespace not found")
                self._send(200, n, index)
            elif parts == ["v1", "node", "pools"]:
                if not self._check(acl.allow_node_read()):
                    return
                self._send(200, state.node_pools(), index)
            elif parts[:3] == ["v1", "node", "pool"] and len(parts) == 4:
                if not self._check(acl.allow_node_read()):
                    return
                p = state.node_pool_by_name(parts[3])
                if p is None:
                    return self._error(404, "node pool not found")
                self._send(200, p, index)
            elif parts[:3] == ["v1", "node", "pool"] and len(parts) == 5 \
                    and parts[4] == "nodes":
                if not self._check(acl.allow_node_read()):
                    return
                self._send(200, [self._node_stub(n) for n in state.nodes()
                                 if n.node_pool == parts[3]], index)
            elif parts == ["v1", "operator", "scheduler", "configuration"]:
                self._send(200, state.scheduler_config(), index)
            elif parts == ["v1", "operator", "keyring", "keys"]:
                # metadata only -- key material never leaves the server
                # (reference: operator_endpoint.go KeyringList)
                self._send(200, [{"key_id": k.key_id, "state": k.state,
                                  "create_time": k.create_time}
                                 for k in state.root_keys()], index)
            elif parts[:2] == ["v1", "vars"]:
                prefix = q.get("prefix", [""])[0]
                metas = self.nomad.var_list(
                    None if ns == "*" else ns, prefix)
                self._send(200, [m for m in metas
                                 if acl.allow_variable_op(
                                     m.namespace, m.path, "list")], index)
            elif parts[:2] == ["v1", "var"] and len(parts) >= 3:
                path = "/".join(parts[2:])
                if not self._check(acl.allow_variable_op(ns, path, "read")):
                    return
                dec = self.nomad.var_get(ns, path)
                if dec is None:
                    return self._error(404, "variable not found")
                self._send(200, dec, index)
            elif parts == ["v1", "regions"]:
                self._send(200, self.nomad.regions())
            elif parts == ["v1", "status", "peers"]:
                raft = getattr(self.nomad, "raft", None)
                if raft is None:
                    self._send(200, [])
                else:
                    self._send(200, [f"{a[0]}:{a[1]}"
                                     for _, a in raft.configuration()])
            elif parts == ["v1", "status", "leader"]:
                raft = getattr(self.nomad, "raft", None)
                if raft is None:
                    self._send(200, "local")
                else:
                    lid, addr = raft.leader()
                    self._send(200, f"{addr[0]}:{addr[1]}" if addr else lid)
            elif parts == ["v1", "operator", "autopilot", "health"]:
                # (reference: operator_autopilot.go ServerHealth)
                raft = getattr(self.nomad, "raft", None)
                serf = getattr(self.nomad, "serf", None)
                if raft is None:
                    return self._send(200, {"healthy": True,
                                            "servers": []})
                alive = ({m.name: m.status for m in serf.members()}
                         if serf is not None else {})
                lid, _ = raft.leader()
                servers = [{
                    "id": name, "address": f"{a[0]}:{a[1]}",
                    "leader": name == lid, "voter": True,
                    "serf_status": alive.get(name, "unknown"),
                    "healthy": alive.get(name, "alive") == "alive",
                } for name, a in raft.configuration()]
                self._send(200, {
                    "healthy": all(s["healthy"] for s in servers),
                    "failure_tolerance":
                        max(0, sum(1 for s in servers if s["healthy"])
                            - (len(servers) // 2 + 1)),
                    "servers": servers})
            elif parts == ["v1", "operator", "raft", "configuration"]:
                # (reference: operator_endpoint.go RaftGetConfiguration)
                raft = getattr(self.nomad, "raft", None)
                if raft is None:
                    self._send(200, {"servers": []})
                else:
                    lid, _ = raft.leader()
                    self._send(200, {"servers": [
                        {"id": name, "address": f"{a[0]}:{a[1]}",
                         "leader": name == lid, "voter": True}
                        for name, a in raft.configuration()]})
            elif parts == ["v1", "operator", "faults"]:
                # armed fault-injection points (chaos/ops; pre-gated
                # operator:read by the blanket /v1/operator GET check)
                from ..faultinject import faults as _faults
                self._send(200, _faults.snapshot())
            elif parts == ["v1", "operator", "quality"]:
                # scheduler quality scoreboard + shadow-audit state +
                # pipeline saturation attribution (server/quality.py;
                # operator:read via the blanket /v1/operator GET check)
                from ..server.quality import observatory
                self._send(200, observatory.report())
            elif parts == ["v1", "agent", "self"]:
                # (reference: agent_endpoint.go AgentSelfRequest; the
                # solver_guard block is TPU-native: a degraded backend
                # must be visible to operators, VERDICT r4 weak #5)
                from ..solver import guard as solver_guard
                from ..solver import xferobs as _xferobs
                from .. import jitcheck as _jitcheck
                from .. import lockcheck as _lockcheck
                from .. import schedcheck as _schedcheck
                from .. import shardcheck as _shardcheck
                from .. import statecheck as _statecheck
                cfg = self.nomad.state.scheduler_config()
                raft = getattr(self.nomad, "raft", None)
                self._send(200, {
                    "config": {
                        "region": self.nomad.region,
                        "version": "nomad-tpu",
                        "server": {"enabled": True,
                                   "raft": raft is not None},
                        "scheduler_algorithm":
                            cfg.scheduler_algorithm if cfg else "",
                    },
                    "stats": {
                        "nomad": {
                            "leader": str(raft.is_leader()).lower()
                            if raft is not None else "true",
                        },
                        "solver_guard": solver_guard.state(),
                        # transfer & device-residency observatory
                        # (solver/xferobs.py): per-dispatch payload
                        # ledger by tree group, const-cache residency
                        # map, live tunnel-model fit;
                        # {"enabled": False} under the kill switch
                        "xferobs": _xferobs.state(),
                        # flap damping: per-node flap scores + active
                        # quarantines (ISSUE 6), exposed like the
                        # breaker state so a quarantined fleet is
                        # diagnosable from the agent endpoint
                        "node_flaps":
                            self.nomad.flaps.state()
                            if hasattr(self.nomad, "flaps") else {},
                        # supervised worker pool (ISSUE 16): per-slot
                        # liveness/progress, death/wedge/restart
                        # counters; enabled=False under
                        # NOMAD_TPU_WORKER_SUPERVISE=0
                        "worker_pool":
                            self.nomad.supervisor.state()
                            if hasattr(self.nomad, "supervisor")
                            else {},
                        # poison-eval dead letters (ISSUE 16): evals
                        # that exhausted their delivery limit
                        # NOMAD_TPU_POISON_AFTER times; released via
                        # POST /v1/operator/quarantine
                        "eval_quarantine":
                            self.nomad.broker.quarantine_state()
                            if hasattr(self.nomad, "broker") else {},
                        # lock-order sanitizer report (lockcheck.py):
                        # cycles/held-across/escaped-frame findings,
                        # {"enabled": False, ...} when the checker is
                        # off (the default)
                        "lockcheck": _lockcheck.state(),
                        # device-dispatch discipline report
                        # (jitcheck.py): steady-state retraces,
                        # hot-path host syncs, dtype drift and
                        # fingerprint-cache mutations; enabled=False
                        # when off (the default)
                        "jitcheck": _jitcheck.state(sites=True),
                        # MVCC snapshot-isolation sanitizer report
                        # (statecheck.py): torn reads, aliasing
                        # writes, delta-journal gaps, write-skew
                        # witnesses and stale version-keyed memos;
                        # enabled=False when off (the default)
                        "statecheck": _statecheck.state(),
                        # deterministic schedule explorer report
                        # (schedcheck.py): run/seed/policy state,
                        # decision counters, manifested-deadlock and
                        # replay-divergence counterexamples;
                        # enabled=False when off (the default)
                        "schedcheck": _schedcheck.state(),
                        # sharding-discipline sanitizer report
                        # (shardcheck.py): spec drift vs the
                        # parallel/mesh.py registry, implicit
                        # transfers into mesh callables, collective-
                        # budget excess and per-shard byte parity;
                        # enabled=False when off (the default)
                        "shardcheck": _shardcheck.state(
                            programs=True),
                    },
                    "member": {"name": getattr(self.nomad, "name",
                                               "local"),
                               "status": "alive"},
                })
            elif parts[:3] == ["v1", "agent", "trace"] and \
                    len(parts) in (3, 4):
                # eval-scoped span flight recorder (server/tracing.py):
                # list retained traces (?degraded=1&slowest=N), export
                # them as chrome://tracing JSON (?format=chrome), or
                # fetch one trace by eval id. agent:read (blanket
                # /v1/agent gate above).
                from ..server.tracing import tracer
                if len(parts) == 4:
                    tr = tracer.get(parts[3])
                    if tr is None:
                        return self._error(
                            404, f"no trace retained for eval "
                                 f"{parts[3]!r}")
                    return self._send(200, tr)
                if q.get("format", [""])[0] == "chrome":
                    return self._send(200, tracer.chrome_trace())
                try:
                    slowest = int(q.get("slowest", ["0"])[0])
                    limit = int(q.get("limit", ["50"])[0])
                except ValueError:
                    return self._error(400,
                                       "slowest/limit must be numeric")
                degraded = q.get("degraded", ["0"])[0] in ("1", "true")
                self._send(200, {
                    "traces": tracer.list_traces(
                        degraded=degraded, slowest=slowest, limit=limit),
                    "stats": tracer.stats()})
            elif parts == ["v1", "agent", "members"]:
                serf = getattr(self.nomad, "serf", None)
                if serf is None:
                    self._send(200, {"members": [
                        {"name": "local", "status": "alive"}]})
                else:
                    self._send(200, {"members": [
                        m.to_wire() for m in serf.members()]})
            elif parts == ["v1", "agent", "health"]:
                self._send(200, {"server": {"ok": True}})
            elif parts == ["v1", "agent", "monitor"]:
                # live log stream with level filter (reference:
                # command/agent/agent_endpoint.go AgentMonitor +
                # monitor/monitor.go). agent:read, like the reference.
                if not self._check(acl.allow_agent_read()):
                    return
                self._stream_monitor(q)
                return
            elif parts == ["v1", "agent", "pprof", "goroutine"]:
                # thread-stack dump (reference: command/agent/pprof/ --
                # gated on agent:write like the reference's enableDebug)
                if not self._check(acl.allow_agent_write()):
                    return
                self._send(200, {"stacks": _thread_stacks()})
            elif parts == ["v1", "agent", "pprof", "profile"]:
                if not self._check(acl.allow_agent_write()):
                    return
                try:
                    seconds = min(float(q.get("seconds", ["1"])[0]), 10.0)
                    hz = min(int(q.get("hz", ["100"])[0]), 250)
                except ValueError:
                    return self._error(400, "seconds/hz must be numeric")
                self._send(200, _sample_profile(seconds, hz))
            elif parts[:2] == ["v1", "node"] and len(parts) == 4 and \
                    parts[3] == "allocations":
                from ..structs import codec
                allocs = state.allocs_by_node(parts[2])
                self._send(200, {"allocs": [codec.encode(a)
                                            for a in allocs],
                                 "index": index}, index)
            elif parts == ["v1", "event", "stream"]:
                # polling mode (stream mode dispatched before _blocking)
                since = int(q.get("index", ["0"])[0])
                self._send(200, self.nomad.events_since(since), index)
            elif parts == ["v1", "operator", "snapshot"]:
                # the archive contains ACL token secrets + root keys:
                # management only (reference: operator_endpoint.go
                # SnapshotSave requires IsManagement)
                if not self._check(acl.is_management()):
                    return
                data = self.nomad.snapshot_save()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            elif parts == ["v1", "metrics"]:
                if q.get("format", [""])[0] == "prometheus":
                    self._send_prometheus()
                else:
                    self._send(200, self._metrics())
            else:
                self._error(404, f"unknown path {url.path}")
        except BrokenPipeError:
            pass
        except Exception as e:  # pragma: no cover
            self._error(500, f"{type(e).__name__}: {e}")

    def do_PUT(self):  # noqa: N802
        self.do_POST()

    def do_POST(self):  # noqa: N802
        if self._maybe_forward():
            return
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        try:
            q = parse_qs(url.query)
            ns = q.get("namespace", ["default"])[0]
            acl = self._acl()
            from ..acl import CAP_PARSE_JOB, CAP_SUBMIT_JOB
            if parts[:2] == ["v1", "acl"]:
                return self._acl_post(parts, acl)
            if parts == ["v1", "jobs", "parse"]:
                if not self._check(acl.allow_namespace_op(ns,
                                                          CAP_PARSE_JOB)):
                    return
            elif parts[1:2] == ["node"]:
                # register/heartbeat/allocs-update are the client-agent
                # paths (node secret in the reference); drain/eligibility
                # are operator actions -- all require node:write
                if not self._check(acl.allow_node_write()):
                    return
            elif parts[1:2] in (["operator"], ["system"], ["regions"]):
                if not self._check(acl.allow_operator_write()):
                    return
            if parts[:2] == ["v1", "search"]:
                # (reference: command/agent/search_endpoint.go; context
                # filtering per token caps as filteredSearchContexts)
                body = self._body()
                allowed = self._allowed_search_contexts(acl, ns)
                from ..acl import CAP_READ_JOB as _READ
                ns_allowed = (None if acl.is_management()
                              else (lambda n: acl.allow_namespace_op(
                                  n, _READ)))
                if parts == ["v1", "search"]:
                    reply = self.nomad.search(
                        body.get("prefix", ""),
                        body.get("context", "all") or "all",
                        ns, allowed_contexts=allowed,
                        ns_allowed=ns_allowed)
                elif parts == ["v1", "search", "fuzzy"]:
                    reply = self.nomad.fuzzy_search(
                        body.get("text", ""),
                        body.get("context", "all") or "all",
                        ns, allowed_contexts=allowed,
                        ns_allowed=ns_allowed)
                else:
                    return self._error(404, "unknown search path")
                return self._send(200, reply)
            if parts == ["v1", "jobs", "parse"]:
                # (reference: /v1/jobs/parse -- HCL -> api.Job JSON)
                from ..jobspec import parse as parse_jobspec
                body = self._body()
                job = parse_jobspec(body.get("job_hcl", ""),
                                    body.get("variables") or {})
                self._send(200, job)
            elif parts == ["v1", "jobs"]:
                body = self._body()
                job = self._job_from_body(body)
                if not job.id:
                    return self._error(400, "job id required")
                # authorize against the JOB's namespace, not the query arg
                # (reference: Job.Register checks submit-job in job.Namespace)
                if not self._check(acl.allow_namespace_op(job.namespace,
                                                          CAP_SUBMIT_JOB)):
                    return
                try:
                    ev = self.nomad.register_job(job)
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"eval_id": ev.id if ev else "",
                                 "job_modify_index": job.job_modify_index})
            elif parts[:2] == ["v1", "job"] and len(parts) == 4 and \
                    parts[3] == "revert":
                if not self._check(acl.allow_namespace_op(ns,
                                                          CAP_SUBMIT_JOB)):
                    return
                body = self._body()
                try:
                    ev = self.nomad.revert_job(
                        ns, parts[2], int(body.get("job_version", 0)),
                        body.get("enforce_prior_version"))
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"eval_id": ev.id if ev else ""})
            elif parts[:2] == ["v1", "job"] and len(parts) == 4 and \
                    parts[3] == "stable":
                if not self._check(acl.allow_namespace_op(ns,
                                                          CAP_SUBMIT_JOB)):
                    return
                body = self._body()
                try:
                    self.nomad.set_job_stability(
                        ns, parts[2], int(body.get("job_version", 0)),
                        bool(body.get("stable", True)))
                except (TypeError, ValueError) as e:
                    return self._error(400, str(e))
                self._send(200, {"updated": True})
            elif parts[:2] == ["v1", "job"] and len(parts) == 4 and \
                    parts[3] == "dispatch":
                from ..acl import CAP_DISPATCH_JOB
                if not self._check(acl.allow_namespace_op(ns,
                                                          CAP_DISPATCH_JOB)):
                    return
                import base64
                body = self._body()
                try:
                    payload = base64.b64decode(body.get("payload", "") or "")
                    child, ev = self.nomad.dispatch_job(
                        ns, parts[2], payload, body.get("meta") or {},
                        body.get("idempotency_token", ""))
                except ValueError as e:   # includes binascii.Error
                    return self._error(400, str(e))
                self._send(200, {"dispatched_job_id": child.id,
                                 "eval_id": ev.id if ev else ""})
            elif parts[:2] == ["v1", "job"] and len(parts) == 4 and \
                    parts[3] == "scale":
                from ..acl import CAP_SCALE_JOB
                if not self._check(acl.allow_namespace_op(ns,
                                                          CAP_SCALE_JOB)):
                    return
                body = self._body()
                target = body.get("target") or {}
                group = target.get("Group", target.get("group", ""))
                try:
                    ev = self.nomad.scale_job(
                        ns, parts[2], group,
                        count=(int(body["count"])
                               if body.get("count") is not None else None),
                        message=body.get("message", ""),
                        error=bool(body.get("error", False)),
                        meta=body.get("meta"))
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"eval_id": ev.id if ev else ""})
            elif parts[:2] == ["v1", "job"] and len(parts) == 4 and \
                    parts[3] == "plan":
                body = self._body()
                job = self._job_from_body(body)
                if not self._check(acl.allow_namespace_op(job.namespace,
                                                          CAP_SUBMIT_JOB)):
                    return
                try:
                    self._send(200, self.nomad.plan_job(job))
                except ValueError as e:
                    return self._error(400, str(e))
            elif parts == ["v1", "node", "register"]:
                from ..structs import Node, codec
                node = codec.decode(Node, self._body().get("node", {}))
                self.nomad.register_node(node)
                self._send(200, {"node_id": node.id,
                                 "heartbeat_ttl":
                                     self.nomad.heartbeat_ttl})
            elif parts[:3] == ["v1", "deployment", "pause"] and \
                    len(parts) == 4:
                # (reference: deployment_endpoint.go Pause)
                from ..acl import CAP_SUBMIT_JOB
                d = self.nomad.state.deployment_by_id(parts[3])
                if d is None:
                    return self._error(404, "unknown deployment")
                if not self._check(acl.allow_namespace_op(
                        d.namespace, CAP_SUBMIT_JOB)):
                    return
                try:
                    self.nomad.pause_deployment(
                        parts[3], bool(self._body().get("pause", True)))
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"paused": True})
            elif parts[:3] == ["v1", "deployment", "fail"] and \
                    len(parts) == 4:
                # (reference: deployment_endpoint.go Fail)
                from ..acl import CAP_SUBMIT_JOB
                d = self.nomad.state.deployment_by_id(parts[3])
                if d is None:
                    return self._error(404, "unknown deployment")
                if not self._check(acl.allow_namespace_op(
                        d.namespace, CAP_SUBMIT_JOB)):
                    return
                try:
                    self.nomad.fail_deployment(parts[3])
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"failed": True})
            elif parts[:3] == ["v1", "deployment", "promote"] and \
                    len(parts) == 4:
                # (reference: deployment_endpoint.go Promote)
                from ..acl import CAP_SUBMIT_JOB
                d = self.nomad.state.deployment_by_id(parts[3])
                if d is None:
                    return self._error(404, "unknown deployment")
                if not self._check(acl.allow_namespace_op(
                        d.namespace, CAP_SUBMIT_JOB)):
                    return
                body = self._body()
                groups = body.get("groups")
                try:
                    self.nomad.promote_deployment(parts[3], groups)
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"promoted": True})
            elif parts == ["v1", "agent", "jax-profile"]:
                # JAX profiler hooks (SURVEY 5.1): capture a device trace
                # for the solver's dispatches. Mutating + writes to a
                # caller-named path: agent:write only.
                if not self._check(acl.allow_agent_write()):
                    return
                body = self._body()
                action = str(body.get("action", ""))
                trace_dir = str(body.get("dir", "")) or "/tmp/jax-trace"
                try:
                    import jax
                    if action == "start":
                        jax.profiler.start_trace(trace_dir)
                        self._send(200, {"tracing": True,
                                         "dir": trace_dir})
                    elif action == "stop":
                        jax.profiler.stop_trace()
                        self._send(200, {"tracing": False,
                                         "dir": trace_dir})
                    else:
                        self._error(400, "action must be start|stop")
                except RuntimeError as e:
                    self._error(400, str(e))
            elif parts == ["v1", "node", "identity-sign"]:
                # client-agent path (node:write pre-gated above): mint a
                # workload identity JWT for a task the node runs
                token = self.nomad.sign_workload_identity(
                    dict(self._body().get("claims", {})))
                self._send(200, {"token": token})
            elif parts == ["v1", "workload", "variable"]:
                # authorization IS the workload identity JWT itself
                body = self._body()
                try:
                    items = self.nomad.workload_variable(
                        str(body.get("identity", "")),
                        str(body.get("path", "")))
                except PermissionError as e:
                    return self._error(403, str(e))
                if items is None:
                    return self._error(404, "variable not found")
                self._send(200, {"items": items})
            elif parts[:2] == ["v1", "node"] and len(parts) == 4 and \
                    parts[3] == "heartbeat":
                ttl = self.nomad.heartbeat(parts[2])
                if not ttl:
                    # unknown node: force the client to re-register
                    # (reference: heartbeats to unknown nodes error so the
                    # client retries registration)
                    return self._error(404, "node not found")
                self._send(200, {"heartbeat_ttl": ttl})
            elif parts == ["v1", "node", "services-register"]:
                # client-agent path (pre-gated by allow_node_write above)
                from ..structs import ServiceRegistration, codec
                from typing import List as _L
                regs = codec.decode(_L[ServiceRegistration],
                                    self._body().get("services", []))
                self.nomad.upsert_services(regs)
                self._send(200, {"registered": len(regs)})
            elif parts == ["v1", "node", "allocs-update"]:
                from ..structs import Allocation, codec
                from typing import List as _L
                allocs = codec.decode(_L[Allocation],
                                      self._body().get("allocs", []))
                self.nomad.update_allocs_from_client(allocs)
                self._send(200, {"updated": len(allocs)})
            elif parts == ["v1", "namespace"] or (
                    parts[:2] == ["v1", "namespace"] and len(parts) == 3):
                # upsert (reference: namespace_endpoint.go UpsertNamespaces;
                # mutating namespaces is a management operation)
                if not self._check(acl.is_management()):
                    return
                from ..structs import (Namespace,
                                       NamespaceNodePoolConfiguration)
                body = self._body()
                npc_src = body.get("node_pool_configuration") or {}
                namespace = Namespace(
                    name=body.get("name", parts[2] if len(parts) == 3
                                  else ""),
                    description=body.get("description", ""),
                    quota=body.get("quota", ""),
                    meta=body.get("meta") or {},
                    node_pool_configuration=NamespaceNodePoolConfiguration(
                        default=npc_src.get("default", ""),
                        allowed=npc_src.get("allowed") or [],
                        denied=npc_src.get("denied") or []))
                try:
                    self.nomad.upsert_namespace(namespace)
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"updated": True})
            elif parts == ["v1", "node", "pools"] or (
                    parts[:3] == ["v1", "node", "pool"] and len(parts) == 4):
                from ..structs import NodePool
                body = self._body()
                pool = NodePool(
                    name=body.get("name", parts[3] if len(parts) == 4
                                  else ""),
                    description=body.get("description", ""),
                    meta=body.get("meta") or {},
                    scheduler_algorithm=body.get("scheduler_algorithm", ""))
                try:
                    self.nomad.upsert_node_pool(pool)
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"updated": True})
            elif parts[:3] == ["v1", "volume", "csi"] and \
                    len(parts) == 5 and parts[4] == "create":
                # dynamic provisioning (reference: csi_endpoint.go Create
                # -> controller CreateVolume on a plugin-running client)
                from ..acl import CAP_CSI_WRITE_VOLUME
                if not self._check(acl.allow_namespace_op(
                        ns, CAP_CSI_WRITE_VOLUME)):
                    return
                from ..structs import CSIVolume
                body = self._body()
                plugin_id = str(body.get("plugin_id", ""))
                if not plugin_id:
                    return self._error(400, "plugin_id required")
                client = self._client_for_csi_plugin(plugin_id)
                if client is None:
                    return self._error(
                        400, f"no healthy client runs plugin "
                             f"{plugin_id!r}")
                try:
                    created = client.csi_create_volume(
                        plugin_id, parts[3],
                        body.get("parameters") or {})
                except KeyError as e:
                    return self._error(404, str(e))
                except Exception as e:  # noqa: BLE001 -- plugin errors
                    return self._error(400, str(e))
                vol = CSIVolume(
                    id=parts[3], namespace=ns,
                    name=body.get("name", parts[3]),
                    external_id=str(created.get("volume_id", parts[3])),
                    plugin_id=plugin_id,
                    access_mode=body.get("access_mode",
                                         "single-node-writer"),
                    attachment_mode=body.get("attachment_mode",
                                             "file-system"),
                    parameters=body.get("parameters") or {})
                self.nomad.register_csi_volume(vol)
                self._send(200, {"created": True, "volume": created})
            elif parts[:3] == ["v1", "volume", "csi"] and \
                    len(parts) == 5 and parts[4] == "delete":
                # (reference: csi_endpoint.go Delete -> DeleteVolume)
                from ..acl import CAP_CSI_WRITE_VOLUME
                if not self._check(acl.allow_namespace_op(
                        ns, CAP_CSI_WRITE_VOLUME)):
                    return
                v = self.nomad.state.csi_volume_by_id(ns, parts[3])
                if v is None:
                    return self._error(404, "volume not found")
                client = self._client_for_csi_plugin(v.plugin_id)
                if client is not None:
                    try:
                        client.csi_delete_volume(v.plugin_id, parts[3])
                    except Exception as e:  # noqa: BLE001
                        return self._error(400, str(e))
                try:
                    self.nomad.deregister_csi_volume(ns, parts[3], False)
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"deleted": True})
            elif parts[:3] == ["v1", "volume", "csi"] and len(parts) == 4:
                from ..acl import CAP_CSI_WRITE_VOLUME
                if not self._check(acl.allow_namespace_op(
                        ns, CAP_CSI_WRITE_VOLUME)):
                    return
                from ..structs import CSITopology, CSIVolume
                body = self._body()
                try:
                    vol = CSIVolume(
                        id=parts[3], namespace=ns,
                        name=body.get("name", parts[3]),
                        external_id=body.get("external_id", ""),
                        plugin_id=body.get("plugin_id", ""),
                        access_mode=body.get("access_mode",
                                             "single-node-writer"),
                        attachment_mode=body.get("attachment_mode",
                                                 "file-system"),
                        capacity_min_mb=int(body.get("capacity_min_mb", 0)),
                        capacity_max_mb=int(body.get("capacity_max_mb", 0)),
                        parameters=body.get("parameters") or {},
                        topologies=[
                            CSITopology(segments=t.get("segments", {}))
                            for t in body.get("topologies", [])])
                    self.nomad.register_csi_volume(vol)
                except (TypeError, ValueError) as e:
                    return self._error(400, str(e))
                self._send(200, {"registered": True})
            elif parts == ["v1", "operator", "raft", "remove-peer"]:
                # (reference: operator_endpoint.go RaftRemovePeer via
                # `nomad operator raft remove-peer`); forwards to the
                # leader on clustered followers like every other write
                name = str(self._body().get("id", ""))
                if not name:
                    return self._error(400, "id required")
                try:
                    self.nomad.remove_raft_peer(name)
                except ValueError as e:
                    return self._error(400, str(e))
                except Exception as e:  # noqa: BLE001 -- not leader etc.
                    return self._error(500, str(e))
                self._send(200, {"removed": name})
            elif parts[:3] == ["v1", "client", "allocation"] and \
                    len(parts) == 5 and parts[4] == "signal":
                # (reference: alloc_endpoint.go Signal)
                from ..acl import CAP_ALLOC_LIFECYCLE
                client, alloc = self._client_for_alloc(parts[3])
                if alloc is None:
                    return self._error(404, "alloc not found")
                if not self._check(acl.allow_namespace_op(
                        alloc.namespace, CAP_ALLOC_LIFECYCLE)):
                    return
                if client is None:
                    return self._error(
                        501, "alloc's node is not served by this agent")
                body = self._body()
                try:
                    out = client.alloc_signal(
                        parts[3], str(body.get("task", "")),
                        str(body.get("signal", "SIGUSR1")))
                except KeyError as e:
                    return self._error(404, str(e))
                except Exception as e:  # noqa: BLE001 -- driver errors
                    return self._error(400, str(e))
                self._send(200, out)
            elif parts[:3] == ["v1", "client", "allocation"] and \
                    len(parts) == 5 and parts[4] == "restart":
                # (reference: alloc_endpoint.go Restart)
                from ..acl import CAP_ALLOC_LIFECYCLE
                client, alloc = self._client_for_alloc(parts[3])
                if alloc is None:
                    return self._error(404, "alloc not found")
                if not self._check(acl.allow_namespace_op(
                        alloc.namespace, CAP_ALLOC_LIFECYCLE)):
                    return
                if client is None:
                    return self._error(
                        501, "alloc's node is not served by this agent")
                try:
                    out = client.alloc_restart(
                        parts[3], str(self._body().get("task", "")))
                except KeyError as e:
                    return self._error(404, str(e))
                except Exception as e:  # noqa: BLE001 -- forwarding loss
                    return self._error(400, str(e))
                self._send(200, out)
            elif parts[:3] == ["v1", "client", "allocation"] and \
                    len(parts) == 5 and parts[4] == "exec":
                # one-shot exec in a task's context (reference:
                # `nomad alloc exec`, non-interactive form)
                from ..acl import CAP_ALLOC_EXEC
                client, alloc = self._client_for_alloc(parts[3])
                if alloc is None:
                    return self._error(404, "alloc not found")
                if not self._check(acl.allow_namespace_op(
                        alloc.namespace, CAP_ALLOC_EXEC)):
                    return
                if client is None:
                    return self._error(
                        501, "alloc's node is not served by this agent")
                body = self._body()
                cmd = body.get("cmd") or []
                if not isinstance(cmd, list) or not cmd:
                    return self._error(400, "cmd must be a non-empty list")
                try:
                    exec_timeout = float(body.get("timeout", 10.0))
                except (TypeError, ValueError):
                    return self._error(400, "timeout must be a number")
                if not (0 < exec_timeout <= 300):
                    return self._error(
                        400, "timeout must be in (0, 300] seconds")
                try:
                    out = client.alloc_exec(
                        parts[3], str(body.get("task", "")),
                        [str(c) for c in cmd], timeout=exec_timeout)
                except KeyError as e:
                    return self._error(404, str(e))
                except Exception as e:  # noqa: BLE001 -- driver errors
                    return self._error(400, str(e))
                self._send(200, out)
            elif parts[:2] == ["v1", "allocation"] and len(parts) == 4 \
                    and parts[3] == "stop":
                # (reference: alloc_endpoint.go Stop)
                from ..acl import CAP_ALLOC_LIFECYCLE
                alloc = self.nomad.state.alloc_by_id(parts[2])
                if alloc is None:
                    return self._error(404, "alloc not found")
                if not self._check(acl.allow_namespace_op(
                        alloc.namespace, CAP_ALLOC_LIFECYCLE)):
                    return
                eval_id = self.nomad.stop_alloc(parts[2])
                self._send(200, {"eval_id": eval_id})
            elif parts[:2] == ["v1", "node"] and len(parts) == 4 and \
                    parts[3] == "evaluate":
                # (reference: node_endpoint.go Evaluate -- force evals
                # for every job with allocs on the node)
                node = self.nomad.state.node_by_id(parts[2])
                if node is None:
                    return self._error(404, "node not found")
                self.nomad._create_node_evals(parts[2])
                self._send(200, {"evaluated": parts[2]})
            elif parts[:2] == ["v1", "node"] and len(parts) == 4 and \
                    parts[3] == "purge":
                # (reference: node_endpoint.go Deregister via
                # `nomad node purge`); node:write pre-gated above
                try:
                    self.nomad.deregister_node(parts[2])
                except ValueError as e:
                    return self._error(404, str(e))
                self._send(200, {"purged": parts[2]})
            elif parts[:2] == ["v1", "job"] and len(parts) == 5 and \
                    parts[3] == "periodic" and parts[4] == "force":
                # (reference: periodic_endpoint.go Force)
                from ..acl import CAP_SUBMIT_JOB
                if not self._check(acl.allow_namespace_op(
                        ns, CAP_SUBMIT_JOB)):
                    return
                try:
                    child = self.nomad.periodic_force(ns, parts[2])
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"dispatched_job_id": child})
            elif parts == ["v1", "regions", "join"]:
                # federation join (operator; pre-gated operator_write)
                body = self._body()
                if not body.get("region") or not body.get("address"):
                    return self._error(400, "region and address required")
                self.nomad.join_federation(body["region"], body["address"])
                self._send(200, {"joined": body["region"]})
            elif parts == ["v1", "system", "gc"]:
                self._send(200, self.nomad.run_gc_once())
            elif parts == ["v1", "operator", "snapshot"]:
                # restoring installs arbitrary ACL state: management only
                if not self._check(acl.is_management()):
                    return
                length = int(self.headers.get("Content-Length", 0) or 0)
                raw = self.rfile.read(length) if length else b""
                try:
                    meta = self.nomad.snapshot_restore(raw)
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"restored": True, "index": meta["index"]})
            elif parts == ["v1", "operator", "keyring", "rotate"]:
                key = self.nomad.encrypter.rotate()
                self._send(200, {"key_id": key.key_id})
            elif parts == ["v1", "operator", "solver", "reprobe"]:
                # operator-triggered accelerator guard recovery check
                # (solver/guard.py reprobe: late-thread flag read + a
                # killable subprocess probe -- a wedged init can't hang
                # this handler). Gated operator:write by the blanket
                # /v1/operator POST check above, like other operator
                # mutations.
                from ..solver import guard as solver_guard
                try:
                    timeout = float(
                        q.get("timeout", ["0"])[0]) or None
                except ValueError:
                    timeout = None
                self._send(200, solver_guard.reprobe(timeout))
            elif parts == ["v1", "operator", "faults"]:
                # arm/disarm fault-injection points (chaos testing; the
                # blanket /v1/operator POST gate above requires
                # operator:write). Body: {"point", "action", "delay_s",
                # "count"} to arm; {"point", "disarm": true} or
                # {"disarm_all": true} to clear.
                from ..faultinject import faults as _faults
                body = self._body()
                try:
                    if body.get("disarm_all"):
                        _faults.disarm_all()
                    elif body.get("disarm"):
                        if not body.get("point"):
                            return self._error(400, "point required")
                        _faults.disarm(body["point"])
                    else:
                        _faults.arm(
                            body.get("point", ""),
                            body.get("action", "error"),
                            delay_s=float(body.get("delay_s", 0.0)),
                            count=body.get("count"))
                except (ValueError, TypeError) as e:
                    return self._error(400, str(e))
                self._send(200, _faults.snapshot())
            elif parts == ["v1", "operator", "quarantine"]:
                # release poison-eval dead letters (ISSUE 16; the
                # blanket /v1/operator POST gate above requires
                # operator:write). Body: {"eval_id": "..."} for one,
                # {"release_all": true} for the whole set.
                body = self._body()
                if body.get("release_all"):
                    released = self.nomad.broker.release_quarantined()
                elif body.get("eval_id"):
                    released = self.nomad.broker.release_quarantined(
                        body["eval_id"])
                else:
                    return self._error(
                        400, "eval_id or release_all required")
                self._send(200, {
                    "released": released,
                    "quarantine":
                        self.nomad.broker.quarantine_state()})
            elif parts[:2] == ["v1", "var"] and len(parts) >= 3:
                path = "/".join(parts[2:])
                if not self._check(acl.allow_variable_op(ns, path, "write")):
                    return
                body = self._body()
                cas = (int(q["cas"][0]) if "cas" in q else None)
                ok, result = self.nomad.var_put(
                    ns, path, body.get("items", body.get("Items", {})),
                    cas_index=cas)
                if not ok:
                    return self._send(409, {"error": "cas conflict",
                                            "conflict": result})
                self._send(200, result)
            elif parts == ["v1", "operator", "scheduler", "configuration"]:
                body = self._body()
                cfg = SchedulerConfiguration(
                    scheduler_algorithm=body.get("scheduler_algorithm",
                                                 "binpack"),
                    memory_oversubscription_enabled=body.get(
                        "memory_oversubscription_enabled", False),
                    pause_eval_broker=bool(body.get("pause_eval_broker",
                                                    False)))
                self.nomad.apply_scheduler_config(cfg)
                self._send(200, {"updated": True})
            elif parts[:2] == ["v1", "node"] and len(parts) == 4 and \
                    parts[3] == "drain":
                from ..structs import DrainStrategy
                body = self._body()
                strategy = None
                if body.get("drain_spec") is not None:
                    strategy = DrainStrategy(
                        deadline_s=body["drain_spec"].get("deadline_s", 3600))
                self.nomad.drain_node(parts[2], strategy)
                self._send(200, {"updated": True})
            elif parts[:2] == ["v1", "node"] and len(parts) == 4 and \
                    parts[3] == "eligibility":
                body = self._body()
                self.nomad.state.update_node_eligibility(
                    parts[2], body.get("eligibility", "eligible"))
                self._send(200, {"updated": True})
            else:
                self._error(404, f"unknown path {url.path}")
        except Exception as e:
            self._error(500, f"{type(e).__name__}: {e}")

    def do_DELETE(self):  # noqa: N802
        if self._maybe_forward():
            return
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        try:
            q = parse_qs(url.query)
            ns = q.get("namespace", ["default"])[0]
            purge = q.get("purge", ["false"])[0] == "true"
            acl = self._acl()
            from ..acl import CAP_SUBMIT_JOB
            if parts[:2] == ["v1", "job"] and len(parts) == 3:
                if not self._check(acl.allow_namespace_op(ns,
                                                          CAP_SUBMIT_JOB)):
                    return
                ev = self.nomad.deregister_job(ns, parts[2], purge=purge)
                if ev is None:
                    return self._error(404, "job not found")
                self._send(200, {"eval_id": ev.id})
            elif parts[:3] == ["v1", "acl", "policy"] and len(parts) == 4:
                if not self._check(acl.is_management()):
                    return
                self.nomad.state.delete_acl_policies([parts[3]])
                self._send(200, {"deleted": True})
            elif parts[:3] == ["v1", "acl", "role"] and len(parts) == 4:
                if not self._check(acl.is_management()):
                    return
                self.nomad.state.delete_acl_roles([parts[3]])
                self._send(200, {"deleted": True})
            elif parts[:3] == ["v1", "acl", "token"] and len(parts) == 4:
                if not self._check(acl.is_management()):
                    return
                self.nomad.state.delete_acl_tokens([parts[3]])
                self._send(200, {"deleted": True})
            elif parts[:2] == ["v1", "service"] and len(parts) == 4:
                from ..acl import CAP_SUBMIT_JOB as _SUBMIT
                # resolve the registration, then authorize against ITS
                # namespace (ids are guessable -- query-ns is not enough)
                reg = next(
                    (r for r in self.nomad.state.service_registrations(None)
                     if r.id == parts[3]), None)
                if reg is None or reg.service_name != parts[2]:
                    if not self._check(acl.allow_namespace_op(ns, _SUBMIT)):
                        return
                    return self._error(404, "registration not found")
                if not self._check(acl.allow_namespace_op(reg.namespace,
                                                          _SUBMIT)):
                    return
                self.nomad.state.delete_service_registrations([parts[3]])
                self._send(200, {"deleted": True})
            elif parts[:3] == ["v1", "volume", "csi"] and len(parts) == 4:
                from ..acl import CAP_CSI_WRITE_VOLUME
                if not self._check(acl.allow_namespace_op(
                        ns, CAP_CSI_WRITE_VOLUME)):
                    return
                force = q.get("force", ["false"])[0] == "true"
                try:
                    self.nomad.deregister_csi_volume(ns, parts[3], force)
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"deregistered": True})
            elif parts[:2] == ["v1", "namespace"] and len(parts) == 3:
                if not self._check(acl.is_management()):
                    return
                try:
                    self.nomad.delete_namespace(parts[2])
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"deleted": True})
            elif parts[:3] == ["v1", "node", "pool"] and len(parts) == 4:
                if not self._check(acl.allow_node_write()):
                    return
                try:
                    self.nomad.delete_node_pool(parts[3])
                except ValueError as e:
                    return self._error(400, str(e))
                self._send(200, {"deleted": True})
            elif parts[:2] == ["v1", "var"] and len(parts) >= 3:
                path = "/".join(parts[2:])
                if not self._check(acl.allow_variable_op(ns, path,
                                                         "destroy")):
                    return
                cas = (int(q["cas"][0]) if "cas" in q else None)
                if not self.nomad.var_delete(ns, path, cas_index=cas):
                    return self._send(409, {"error": "cas conflict"})
                self._send(200, {"deleted": True})
            else:
                self._error(404, f"unknown path {url.path}")
        except Exception as e:
            self._error(500, f"{type(e).__name__}: {e}")

    # ------------------------------------------------------------------
    # ACL endpoints (reference: nomad/acl_endpoint.go + command/agent/
    # acl_endpoint.go)
    def _token_stub(self, t) -> dict:
        return {"accessor_id": t.accessor_id, "name": t.name,
                "type": t.type, "policies": t.policies,
                "global": t.global_token, "create_time": t.create_time,
                "modify_index": t.modify_index}

    def _acl_get(self, parts, acl, index) -> None:
        state = self.nomad.state
        if parts == ["v1", "acl", "policies"]:
            if not self._check(acl.is_management()):
                return
            self._send(200, [{"name": p.name, "description": p.description,
                              "modify_index": p.modify_index}
                             for p in state.acl_policies()], index)
        elif parts[:3] == ["v1", "acl", "policy"] and len(parts) == 4:
            if not self._check(acl.is_management()):
                return
            p = state.acl_policy_by_name(parts[3])
            if p is None:
                return self._error(404, "policy not found")
            self._send(200, p, index)
        elif parts == ["v1", "acl", "roles"]:
            if not self._check(acl.is_management()):
                return
            self._send(200, state.acl_roles(), index)
        elif parts[:3] == ["v1", "acl", "role"] and len(parts) == 4:
            if not self._check(acl.is_management()):
                return
            r = state.acl_role_by_name(parts[3])
            if r is None:
                return self._error(404, "role not found")
            self._send(200, r, index)
        elif parts == ["v1", "acl", "tokens"]:
            if not self._check(acl.is_management()):
                return
            self._send(200, [self._token_stub(t)
                             for t in state.acl_tokens()], index)
        elif parts == ["v1", "acl", "token", "self"]:
            secret = self.headers.get("X-Nomad-Token", "")
            if not secret:
                q = parse_qs(urlparse(self.path).query)
                secret = q.get("token", [""])[0]
            # resolve through the server so expired tokens are rejected
            _compiled, token = self.nomad.resolve_token(secret or None)
            if token is None:
                return self._error(404, "token not found")
            self._send(200, token, index)
        elif parts[:3] == ["v1", "acl", "token"] and len(parts) == 4:
            if not self._check(acl.is_management()):
                return
            t = state.acl_token_by_accessor(parts[3])
            if t is None:
                return self._error(404, "token not found")
            self._send(200, t, index)
        else:
            self._error(404, "unknown acl path")

    def _acl_post(self, parts, acl) -> None:
        from ..acl import parse_policy
        from ..structs import ACLPolicy, ACLToken
        state = self.nomad.state
        if parts == ["v1", "acl", "bootstrap"]:
            token = self.nomad.bootstrap_acl()
            if token is None:
                return self._error(400, "ACL already bootstrapped")
            self._send(200, token)
        elif parts[:3] == ["v1", "acl", "policy"] and len(parts) == 4:
            if not self._check(acl.is_management()):
                return
            body = self._body()
            rules = body.get("rules", "")
            try:
                parse_policy(parts[3], rules)   # validate before storing
            except Exception as e:
                return self._error(400, f"invalid policy: {e}")
            state.upsert_acl_policies([ACLPolicy(
                name=parts[3], description=body.get("description", ""),
                rules=rules)])
            self._send(200, {"updated": True})
        elif parts == ["v1", "acl", "token"]:
            if not self._check(acl.is_management()):
                return
            body = self._body()
            token = ACLToken.new(
                name=body.get("name", ""),
                type=body.get("type", "client"),
                policies=body.get("policies", []),
                roles=body.get("roles", []),
                ttl_s=body.get("ttl_s"))
            state.upsert_acl_tokens([token])
            self._send(200, token)
        elif parts[:3] == ["v1", "acl", "role"] and len(parts) == 4:
            # (reference: acl_endpoint.go UpsertRoles, Nomad 1.4+)
            if not self._check(acl.is_management()):
                return
            from ..structs import ACLRole
            body = self._body()
            policies = [str(p) for p in body.get("policies", [])]
            for p in policies:
                if state.acl_policy_by_name(p) is None:
                    return self._error(
                        400, f"role links unknown policy {p!r}")
            state.upsert_acl_roles([ACLRole(
                name=parts[3],
                description=body.get("description", ""),
                policies=policies)])
            self._send(200, {"updated": True})
        else:
            self._error(404, "unknown acl path")

    def _write_chunk(self, payload: bytes) -> None:
        """One HTTP/1.1 chunked-transfer frame (shared by the monitor,
        event, and log-follow streams)."""
        self.wfile.write(f"{len(payload):x}\r\n".encode())
        self.wfile.write(payload + b"\r\n")
        self.wfile.flush()

    def _stream_log_follow(self, client, alloc_id: str, task: str,
                           log_type: str, offset: int) -> None:
        """Chunked raw-byte log follow (reference: fs_endpoint.go logs
        with follow=true): emits the requested window, then polls the
        rotated frames for growth. Raw bytes -- no heartbeat frames
        (they would corrupt the content); the stream ends when the
        alloc reaches a terminal state and the tail is drained, or the
        reader disconnects."""
        try:
            total0 = client.fs_logs_total(alloc_id, task, log_type)
        except KeyError as e:
            return self._error(404, str(e))
        except (OSError, ValueError, PermissionError) as e:
            return self._error(400, str(e))
        cursor = max(0, total0 + offset) if offset < 0 else \
            min(max(0, offset), total0)
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            chunk = self._write_chunk

            idle_terminal = 0
            while True:
                try:
                    data = client.fs_logs(alloc_id, task, log_type,
                                          offset=cursor, limit=1 << 20)
                except (KeyError, ValueError):
                    # alloc GC'd / runner torn down mid-stream: end the
                    # chunked body cleanly -- raising here would let
                    # do_GET write a 500 header block INTO the stream
                    break
                if data:
                    chunk(data)
                    cursor += len(data)
                    idle_terminal = 0
                    continue
                alloc = self.nomad.state.alloc_by_id(alloc_id)
                if alloc is None or alloc.terminal_status():
                    # one extra idle pass so a final write between the
                    # read and the state check still drains
                    idle_terminal += 1
                    if idle_terminal >= 2:
                        break
                time.sleep(0.5)
        except (BrokenPipeError, ConnectionResetError, OSError):
            return
        try:
            self.wfile.write(b"0\r\n\r\n")
        except OSError:
            pass

    def _stream_monitor(self, q) -> None:
        """Chunked NDJSON log stream (reference: AgentMonitor --
        ?log_level=trace|debug|info|warn|error, ?plain=true for raw
        lines). Replays the recent ring first so an operator attaching
        after an incident still sees it, then follows live; heartbeat
        frame every 10s; client disconnect detaches the sink."""
        from ..server.logbroker import broker
        level = q.get("log_level", ["info"])[0]
        plain = q.get("plain", ["false"])[0] == "true"
        # one locked step: a record logged around attach time shows up
        # exactly once (replay xor live), never twice
        sink, recent = broker.attach_with_recent(min_level=level)
        try:
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain" if plain
                             else "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            chunk = self._write_chunk

            def frame(rec: dict) -> bytes:
                if plain:
                    ts = time.strftime("%H:%M:%S",
                                       time.localtime(rec["ts"]))
                    return (f"{ts} [{rec['level'].upper():5s}] "
                            f"{rec['name']}: {rec['msg']}\n").encode()
                return json.dumps(rec).encode() + b"\n"

            for rec in recent:
                chunk(frame(rec))
            last_beat = time.time()
            while True:
                rec = sink.next(timeout=0.5)
                if rec is not None:
                    chunk(frame(rec))
                elif time.time() - last_beat >= 10.0:
                    chunk(b"\n" if plain else b"{}\n")
                    last_beat = time.time()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            broker.detach(sink)
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass

    def _stream_events(self, q, since: int) -> None:
        """Chunked NDJSON event stream with topic filters (reference:
        command/agent/event_endpoint.go + nomad/stream/ndjson.go).
        ?topic=Topic:Key repeatable; heartbeat {} every 10s."""
        topics: dict = {}
        for t in q.get("topic", []):
            name, _, key = t.partition(":")
            topics.setdefault(name or "*", []).append(key or "*")
        sub = self.nomad.subscribe_events(topics or None, since)
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            chunk = self._write_chunk

            last_beat = time.time()
            while True:
                event = sub.next(timeout=0.5)
                if event is not None:
                    chunk(json.dumps(to_jsonable(event)).encode() + b"\n")
                elif time.time() - last_beat >= 10.0:
                    chunk(b"{}\n")           # heartbeat frame
                    last_beat = time.time()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            sub.closed = True
            self.nomad.unsubscribe_events(sub)
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass

    def _allowed_search_contexts(self, acl, ns: str):
        """Token-capability filter over searchable contexts (reference:
        nomad/search_endpoint.go filteredSearchContexts / sufficientSearchPerms).
        Management tokens see everything (None = unfiltered)."""
        if acl.is_management():
            return None
        from ..acl import (CAP_LIST_JOBS, CAP_LIST_SCALING_POLICIES,
                           CAP_READ_JOB)
        from ..server.search import (
            CONTEXT_ALLOCS, CONTEXT_DEPLOYMENTS, CONTEXT_EVALS,
            CONTEXT_JOBS, CONTEXT_NAMESPACES, CONTEXT_NODE_POOLS,
            CONTEXT_NODES, CONTEXT_PLUGINS, CONTEXT_SCALING_POLICIES,
            CONTEXT_VARIABLES, CONTEXT_VOLUMES)
        allowed = []
        job_cap = (acl.allow_any_namespace(CAP_READ_JOB) if ns == "*"
                   else acl.allow_namespace_op(ns, CAP_READ_JOB))
        list_cap = (acl.allow_any_namespace(CAP_LIST_JOBS) if ns == "*"
                    else acl.allow_namespace_op(ns, CAP_LIST_JOBS))
        if job_cap or list_cap:
            allowed += [CONTEXT_JOBS, CONTEXT_EVALS, CONTEXT_ALLOCS,
                        CONTEXT_DEPLOYMENTS, CONTEXT_VOLUMES,
                        CONTEXT_PLUGINS]
            allowed += [CONTEXT_NAMESPACES]
        if acl.allow_node_read():
            allowed += [CONTEXT_NODES, CONTEXT_NODE_POOLS]
        if (acl.allow_any_namespace(CAP_LIST_SCALING_POLICIES) if ns == "*"
                else acl.allow_namespace_op(ns, CAP_LIST_SCALING_POLICIES)):
            allowed += [CONTEXT_SCALING_POLICIES]
        if acl.allow_variable_op(ns if ns != "*" else "default", "", "list"):
            allowed += [CONTEXT_VARIABLES]
        return allowed

    def _job_from_body(self, body: dict):
        """Accept either JSON jobspec or inline HCL
        (reference: job endpoints accept api.Job; parse is separate)."""
        if "job_hcl" in body:
            from ..jobspec import parse as parse_jobspec
            return parse_jobspec(body["job_hcl"],
                                 body.get("variables") or {})
        return job_from_json(body.get("job", body))

    # ------------------------------------------------------------------
    def _job_stub(self, j) -> dict:
        return {"id": j.id, "name": j.name, "namespace": j.namespace,
                "type": j.type, "priority": j.priority, "status": j.status,
                "version": j.version, "stop": j.stop}

    def _volume_stub(self, v) -> dict:
        return {"id": v.id, "namespace": v.namespace, "name": v.name,
                "plugin_id": v.plugin_id, "access_mode": v.access_mode,
                "schedulable": v.schedulable,
                "read_claims": len(v.read_claims),
                "write_claims": len(v.write_claims)}

    def _node_stub(self, n) -> dict:
        return {"id": n.id, "name": n.name, "datacenter": n.datacenter,
                "status": n.status, "node_class": n.node_class,
                "scheduling_eligibility": n.scheduling_eligibility,
                "drain": n.drain}

    def _send_prometheus(self) -> None:
        body = prometheus_text(self._metrics()).encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _metrics(self) -> dict:
        from ..server.quality import observatory
        from ..server.telemetry import metrics
        s = self.nomad
        # sampling the quality gauges BEFORE the registry snapshot so
        # the fresh fragmentation/packing values ride this response's
        # own gauge series (and statsd/prometheus scrapes of it)
        quality = observatory.report()
        tel = metrics.snapshot()
        counters = tel["counters"]
        tpu = counters.get("nomad.scheduler.placements_tpu", 0)
        host_fb = counters.get("nomad.scheduler.placements_host_fallback", 0)
        return {
            "broker": s.broker.stats(),
            "blocked_evals": s.blocked_evals.stats(),
            "plans_applied": s.planner.plans_applied,
            "plans_rejected": s.planner.plans_rejected,
            "state_index": s.state.latest_index(),
            "samples": tel["samples"],
            "gauges": tel["gauges"],
            "counters": counters,
            # solver coverage: fraction of tpu-algorithm placements that
            # actually ran on the dense path (VERDICT r1 weak #4)
            "tpu_placement_ratio": (tpu / (tpu + host_fb)
                                    if (tpu + host_fb) else None),
            # quality scoreboard + saturation attribution (ISSUE 7):
            # the full report rides /v1/operator/quality; this block is
            # the headline slice dashboards poll alongside the series
            "quality": _quality_metrics_block(quality),
        }


def _quality_metrics_block(q: dict) -> dict:
    """The headline slice of the quality report for /v1/metrics
    (dashboards poll this next to the series; the full report lives at
    /v1/operator/quality)."""
    if not q.get("enabled"):
        return {"enabled": False}
    p = q.get("placement") or {}
    a = q.get("audit") or {}
    sat = q.get("saturation") or {}
    out = {"enabled": True, "attached": q.get("attached", False)}
    if p.get("attached"):
        out["fragmentation_index"] = p["fragmentation_index"]
        out["packing_efficiency"] = p["packing_efficiency"]
        out["live_allocs"] = p["fleet"]["live_allocs"]
    out["score_drift_max"] = a.get("score_drift_max", 0.0)
    out["decision_mismatch_total"] = a.get("decision_mismatch_total", 0)
    out["audit_alert"] = a.get("alert")
    out["bottleneck"] = sat.get("bottleneck")
    return out


def prometheus_text(m: dict) -> str:
    """Prometheus text exposition of a /v1/metrics dict (reference:
    go-metrics prometheus sink fanout, command/agent/command.go:1164-
    1253).  Timer/gauge series render every key in telemetry's
    TIMER_/GAUGE_SUMMARY_KEYS -- the same snapshot the JSON surface
    serves, parity-tested in tests/test_telemetry.py (the old
    hand-listed keys silently dropped p99 and advertised a
    never-produced `last_ms`)."""
    from ..server.telemetry import GAUGE_SUMMARY_KEYS, TIMER_SUMMARY_KEYS

    def norm(name: str) -> str:
        return "".join(ch if ch.isalnum() or ch == "_" else "_"
                       for ch in name)

    lines = []
    for name, value in sorted(m.get("counters", {}).items()):
        p = norm(name)
        lines.append(f"# TYPE {p} counter")
        lines.append(f"{p} {value}")
    for name, s in sorted(m.get("samples", {}).items()):
        p = norm(name)
        # derived series are NOT a prometheus summary (that family
        # only allows _sum/_count/quantile) -- expose each as a gauge
        for k in TIMER_SUMMARY_KEYS:
            if k in s:
                lines.append(f"# TYPE {p}_{k} gauge")
                lines.append(f"{p}_{k} {s[k]}")
    for name, s in sorted(m.get("gauges", {}).items()):
        p = norm(name)
        for k in GAUGE_SUMMARY_KEYS:
            if k in s:
                lines.append(f"# TYPE {p}_{k} gauge")
                lines.append(f"{p}_{k} {s[k]}")
    for k in ("plans_applied", "plans_rejected", "state_index"):
        if k not in m:
            continue
        p = norm(f"nomad.{k}")
        lines.append(f"# TYPE {p} gauge")
        lines.append(f"{p} {m[k]}")
    if m.get("tpu_placement_ratio") is not None:
        lines.append("# TYPE nomad_scheduler_tpu_placement_ratio gauge")
        lines.append("nomad_scheduler_tpu_placement_ratio "
                     f"{m['tpu_placement_ratio']}")
    return "\n".join(lines) + "\n"


class HttpServer:
    """(reference: command/agent/http.go:179). `clients` are in-process
    client agents whose allocdirs back the /v1/client/fs endpoints (the
    reference reaches them via server->client RPC forwarding)."""

    def __init__(self, nomad_server, host: str = "127.0.0.1",
                 port: int = 4646, clients=None, tls=None):
        self.httpd = ThreadingHTTPServer((host, port), ApiHandler)
        self.httpd.nomad_server = nomad_server
        self.httpd.local_clients = list(clients or [])
        self.tls = tls
        if tls is not None and tls.enable_http:
            # (reference: command/agent/http.go TLS listener wrap)
            from ..tlsutil import server_context
            self.httpd.socket = server_context(tls).wrap_socket(
                self.httpd.socket, server_side=True)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def add_client(self, client) -> None:
        self.httpd.local_clients.append(client)

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="http-api")
        self._thread.start()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        # close the listener too: without this the port stays bound and
        # new connections queue in the backlog forever instead of being
        # refused (clients' failover depends on a fast refusal)
        self.httpd.server_close()
