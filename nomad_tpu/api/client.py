"""Python API client for the /v1/* HTTP surface.

Semantic parity with /root/reference/api/ (the separate Go client module:
api.go Client + one file per resource -- jobs.go, allocations.go, nodes.go,
evaluations.go, operator.go, event_stream.go). Also provides
`HttpServerConn`, the client-agent transport over this API -- making node
agents deployable on separate hosts from the servers, like the reference's
client->server RPC.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from ..structs import Allocation, Node, codec


class ApiError(Exception):
    def __init__(self, status: int, msg: str):
        super().__init__(f"HTTP {status}: {msg}")
        self.status = status


class ApiClient:
    """(reference: api/api.go Client)"""

    def __init__(self, address: str = "http://127.0.0.1:4646",
                 namespace: str = "default", token: str = "",
                 timeout: float = 10.0, region: str = "",
                 ca_cert: str = "", client_cert: str = "",
                 client_key: str = ""):
        import os as _os
        self.address = address.rstrip("/")
        self.namespace = namespace
        self.token = token
        self.timeout = timeout
        self.region = region
        # TLS to an https agent (reference: api/api.go TLSConfig +
        # NOMAD_CACERT/NOMAD_CLIENT_CERT/NOMAD_CLIENT_KEY env)
        ca_cert = ca_cert or _os.environ.get("NOMAD_CACERT", "")
        client_cert = client_cert or _os.environ.get("NOMAD_CLIENT_CERT", "")
        client_key = client_key or _os.environ.get("NOMAD_CLIENT_KEY", "")
        self._ssl_ctx = None
        if self.address.startswith("https"):
            from ..tlsutil import TLSConfig, client_context
            self._ssl_ctx = client_context(TLSConfig(
                ca_file=ca_cert, cert_file=client_cert,
                key_file=client_key))

    # -- low-level -----------------------------------------------------
    def _headers(self) -> Dict[str, str]:
        """Auth headers for callers that open raw streams (monitor,
        debug capture) outside request_raw."""
        return {"X-Nomad-Token": self.token} if self.token else {}

    def _url(self, path: str, params: Optional[Dict[str, Any]] = None) -> str:
        params = dict(params or {})
        params.setdefault("namespace", self.namespace)
        if self.region:
            params.setdefault("region", self.region)
        qs = urllib.parse.urlencode(params)
        return f"{self.address}{path}?{qs}"

    # -- regions (reference: api/regions.go) ---------------------------
    def list_regions(self) -> List[str]:
        return self.get("/v1/regions")

    def join_region(self, region: str, address: str) -> dict:
        return self.post("/v1/regions/join",
                         {"region": region, "address": address})

    def _do(self, req: urllib.request.Request,
            timeout: Optional[float] = None) -> bytes:
        """Shared urlopen + HTTPError->ApiError translation."""
        try:
            with urllib.request.urlopen(req, context=self._ssl_ctx, timeout=timeout or self.timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("error", str(e))
            except Exception:   # noqa: BLE001
                detail = str(e)
            raise ApiError(e.code, detail) from e

    def request(self, method: str, path: str,
                body: Optional[dict] = None,
                params: Optional[Dict[str, Any]] = None,
                timeout: Optional[float] = None) -> Any:
        req = urllib.request.Request(
            self._url(path, params), method=method,
            data=None if body is None else json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     **({"X-Nomad-Token": self.token}
                        if self.token else {})})
        return json.loads(self._do(req, timeout) or b"null")

    def get(self, path: str, **params) -> Any:
        return self.request("GET", path, params=params)

    def post(self, path: str, body: Optional[dict] = None, **params) -> Any:
        return self.request("POST", path, body=body, params=params)

    def delete(self, path: str, **params) -> Any:
        return self.request("DELETE", path, params=params)

    # -- jobs (reference: api/jobs.go) ---------------------------------
    def jobs(self) -> List[dict]:
        return self.get("/v1/jobs")

    def job(self, job_id: str) -> dict:
        return self.get(f"/v1/job/{job_id}")

    def register_job(self, job: dict) -> dict:
        return self.post("/v1/jobs", {"job": job})

    def register_job_hcl(self, hcl: str,
                         variables: Optional[dict] = None) -> dict:
        return self.post("/v1/jobs", {"job_hcl": hcl,
                                      "variables": variables or {}})

    def parse_job(self, hcl: str, variables: Optional[dict] = None) -> dict:
        return self.post("/v1/jobs/parse", {"job_hcl": hcl,
                                            "variables": variables or {}})

    def plan_job(self, job_id: str, job: Optional[dict] = None,
                 hcl: Optional[str] = None,
                 variables: Optional[dict] = None) -> dict:
        body: Dict[str, Any] = {}
        if hcl is not None:
            body["job_hcl"] = hcl
            body["variables"] = variables or {}
        else:
            body["job"] = job or {}
        return self.post(f"/v1/job/{job_id}/plan", body)

    def deregister_job(self, job_id: str, purge: bool = False) -> dict:
        return self.delete(f"/v1/job/{job_id}",
                           purge="true" if purge else "false")

    def job_allocations(self, job_id: str) -> List[dict]:
        return self.get(f"/v1/job/{job_id}/allocations")

    def job_evaluations(self, job_id: str) -> List[dict]:
        return self.get(f"/v1/job/{job_id}/evaluations")

    def job_deployment(self, job_id: str) -> Optional[dict]:
        return self.get(f"/v1/job/{job_id}/deployment")

    def job_versions(self, job_id: str) -> dict:
        return self.get(f"/v1/job/{job_id}/versions")

    def revert_job(self, job_id: str, version: int,
                   enforce_prior_version: Optional[int] = None) -> dict:
        return self.post(f"/v1/job/{job_id}/revert",
                         {"job_version": version,
                          "enforce_prior_version": enforce_prior_version})

    def stabilize_job(self, job_id: str, version: int,
                      stable: bool = True) -> dict:
        return self.post(f"/v1/job/{job_id}/stable",
                         {"job_version": version, "stable": stable})

    def dispatch_job(self, job_id: str, payload: bytes = b"",
                     meta: Optional[dict] = None,
                     idempotency_token: str = "") -> dict:
        import base64
        return self.post(f"/v1/job/{job_id}/dispatch", {
            "payload": base64.b64encode(payload).decode(),
            "meta": meta or {}, "idempotency_token": idempotency_token})

    def scale_job(self, job_id: str, group: str, count: int,
                  message: str = "") -> dict:
        return self.post(f"/v1/job/{job_id}/scale", {
            "count": count, "target": {"Group": group}, "message": message})

    def job_scale_status(self, job_id: str) -> dict:
        return self.get(f"/v1/job/{job_id}/scale")

    def scaling_policies(self, job: Optional[str] = None) -> List[dict]:
        params = {"job": job} if job else {}
        return self.get("/v1/scaling/policies", **params)

    def scaling_policy(self, policy_id: str) -> dict:
        return self.get(f"/v1/scaling/policy/{policy_id}")

    # -- namespaces + node pools (reference: api/namespace.go,
    #    api/node_pools.go) --------------------------------------------
    def namespaces(self) -> List[dict]:
        return self.get("/v1/namespaces")

    def get_namespace(self, name: str) -> dict:
        # (named get_* because .namespace is the client's query namespace)
        return self.get(f"/v1/namespace/{name}")

    def upsert_namespace(self, name: str, **fields) -> dict:
        return self.post(f"/v1/namespace/{name}",
                         {"name": name, **fields})

    def delete_namespace(self, name: str) -> dict:
        return self.delete(f"/v1/namespace/{name}")

    def node_pools(self) -> List[dict]:
        return self.get("/v1/node/pools")

    def node_pool(self, name: str) -> dict:
        return self.get(f"/v1/node/pool/{name}")

    def node_pool_nodes(self, name: str) -> List[dict]:
        return self.get(f"/v1/node/pool/{name}/nodes")

    def upsert_node_pool(self, name: str, **fields) -> dict:
        return self.post(f"/v1/node/pool/{name}", {"name": name, **fields})

    def delete_node_pool(self, name: str) -> dict:
        return self.delete(f"/v1/node/pool/{name}")

    # -- client fs/logs/stats (reference: api/fs.go, api/nodes.go) -----
    def fs_list(self, alloc_id: str, path: str = "/") -> List[dict]:
        return self.request("GET", f"/v1/client/fs/ls/{alloc_id}",
                            params={"path": path})

    def fs_stat(self, alloc_id: str, path: str) -> dict:
        return self.request("GET", f"/v1/client/fs/stat/{alloc_id}",
                            params={"path": path})

    def fs_cat(self, alloc_id: str, path: str) -> bytes:
        # _url applies namespace + region so forwarding works like the
        # JSON methods
        url = self._url(f"/v1/client/fs/cat/{alloc_id}", {"path": path})
        return self.request_raw("GET", url[len(self.address):])

    def alloc_logs(self, alloc_id: str, task: str,
                   log_type: str = "stdout", offset: int = 0,
                   limit: Optional[int] = None) -> bytes:
        params = {"type": log_type, "offset": str(offset)}
        if limit is not None:
            params["limit"] = str(limit)
        url = self._url(f"/v1/client/fs/logs/{alloc_id}/{task}", params)
        return self.request_raw("GET", url[len(self.address):])

    def client_stats(self, node_id: str = "") -> dict:
        return self.get("/v1/client/stats", node_id=node_id)

    # -- native service discovery (reference: api/services.go) ---------
    def services(self) -> List[dict]:
        return self.get("/v1/services")

    def service(self, name: str) -> List[dict]:
        return self.get(f"/v1/service/{name}")

    def delete_service_registration(self, name: str, reg_id: str) -> dict:
        return self.delete(f"/v1/service/{name}/{reg_id}")

    # -- CSI volumes + plugins (reference: api/csi.go) -----------------
    def csi_volumes(self) -> List[dict]:
        return self.get("/v1/volumes")

    def csi_volume(self, vol_id: str) -> dict:
        return self.get(f"/v1/volume/csi/{vol_id}")

    def register_csi_volume(self, vol_id: str, plugin_id: str,
                            **fields) -> dict:
        return self.post(f"/v1/volume/csi/{vol_id}",
                         {"plugin_id": plugin_id, **fields})

    def deregister_csi_volume(self, vol_id: str,
                              force: bool = False) -> dict:
        return self.delete(f"/v1/volume/csi/{vol_id}",
                           force="true" if force else "false")

    def csi_plugins(self) -> List[dict]:
        return self.get("/v1/plugins")

    def csi_plugin(self, plugin_id: str) -> dict:
        return self.get(f"/v1/plugin/csi/{plugin_id}")

    # -- search (reference: api/search.go) -----------------------------
    def search(self, prefix: str, context: str = "all") -> dict:
        return self.post("/v1/search",
                         {"prefix": prefix, "context": context})

    def fuzzy_search(self, text: str, context: str = "all") -> dict:
        return self.post("/v1/search/fuzzy",
                         {"text": text, "context": context})

    # -- nodes (reference: api/nodes.go) -------------------------------
    def nodes(self) -> List[dict]:
        return self.get("/v1/nodes")

    def node(self, node_id: str) -> dict:
        return self.get(f"/v1/node/{node_id}")

    def drain_node(self, node_id: str, enable: bool = True,
                   deadline_s: float = 3600.0) -> dict:
        spec = {"deadline_s": deadline_s} if enable else None
        return self.post(f"/v1/node/{node_id}/drain",
                         {"drain_spec": spec})

    def node_eligibility(self, node_id: str, eligible: bool) -> dict:
        return self.post(f"/v1/node/{node_id}/eligibility",
                         {"eligibility":
                          "eligible" if eligible else "ineligible"})

    # -- allocs / evals / deployments ----------------------------------
    def allocations(self) -> List[dict]:
        return self.get("/v1/allocations")

    def allocation(self, alloc_id: str) -> dict:
        return self.get(f"/v1/allocation/{alloc_id}")

    def evaluations(self) -> List[dict]:
        return self.get("/v1/evaluations")

    def evaluation(self, eval_id: str) -> dict:
        return self.get(f"/v1/evaluation/{eval_id}")

    def deployments(self) -> List[dict]:
        return self.get("/v1/deployments")

    # -- operator / system (reference: api/operator.go) ----------------
    def scheduler_config(self) -> dict:
        return self.get("/v1/operator/scheduler/configuration")

    def set_scheduler_config(self, **cfg) -> dict:
        return self.post("/v1/operator/scheduler/configuration", cfg)

    def members(self) -> dict:
        return self.get("/v1/agent/members")

    def leader(self) -> str:
        return self.get("/v1/status/leader")

    def system_gc(self) -> dict:
        return self.post("/v1/system/gc")

    def metrics(self) -> dict:
        return self.get("/v1/metrics")

    def event_stream(self, topics: Optional[List[str]] = None,
                     index: int = 0):
        """Generator over the live NDJSON event stream
        (reference: api/event_stream.go). topics: ["Topic:Key", ...]."""
        params = [("namespace", self.namespace), ("index", str(index))]
        params += [("topic", t) for t in (topics or [])]
        qs = urllib.parse.urlencode(params)
        req = urllib.request.Request(
            f"{self.address}/v1/event/stream?{qs}",
            headers={**({"X-Nomad-Token": self.token}
                        if self.token else {})})
        resp = urllib.request.urlopen(req, context=self._ssl_ctx)
        try:
            for line in resp:
                line = line.strip()
                if not line or line == b"{}":
                    continue           # heartbeat
                yield json.loads(line)
        finally:
            resp.close()

    def request_raw(self, method: str, path: str,
                    data: Optional[bytes] = None,
                    content_type: str = "application/octet-stream"
                    ) -> bytes:
        """Binary-body variant of request() with the same header and
        error-translation behavior."""
        req = urllib.request.Request(
            f"{self.address}{path}", method=method, data=data,
            headers={**({"Content-Type": content_type}
                        if data is not None else {}),
                     **({"X-Nomad-Token": self.token}
                        if self.token else {})})
        return self._do(req)

    def snapshot_save(self) -> bytes:
        """(reference: api/operator.go SnapshotSave)"""
        return self.request_raw("GET", "/v1/operator/snapshot")

    def snapshot_restore(self, data: bytes) -> dict:
        return json.loads(
            self.request_raw("POST", "/v1/operator/snapshot", data)
            or b"null")

    def events(self, index: int = 0) -> List[dict]:
        return self.get("/v1/event/stream", index=index, poll="true")


class FailoverServerConn:
    """Servers manager: one ServerConn over MANY server addresses with
    rotate-on-failure (reference: client/servers/manager.go -- the client
    keeps a ring of known servers, retries the next one when an RPC
    fails, and sticks with whichever worked). Wraps one HttpServerConn
    per address; any method failing with a transport-level error rotates
    through the remaining ring before giving up."""

    # errors that mean "this server is unreachable/unhealthy", not "the
    # request is bad": rotate instead of failing the caller
    def __init__(self, addresses, timeout: float = 10.0, token: str = ""):
        if not addresses:
            raise ValueError("at least one server address required")
        self._conns = [HttpServerConn(a, timeout=timeout, token=token)
                       for a in addresses]
        self._cur = 0
        import threading
        self._lock = threading.Lock()

    def _rotate_call(self, method: str, *args, **kwargs):
        import urllib.error
        with self._lock:
            start = self._cur
            n = len(self._conns)
        last_err: Exception = RuntimeError("no servers")
        for k in range(n):
            idx = (start + k) % n
            conn = self._conns[idx]
            try:
                out = getattr(conn, method)(*args, **kwargs)
            except (ConnectionError, OSError, urllib.error.URLError) as e:
                last_err = e
                continue
            except ApiError as e:
                if e.status >= 500:
                    last_err = e
                    continue
                raise
            if k:
                with self._lock:
                    self._cur = idx
            return out
        raise last_err

    def __getattr__(self, name: str):
        # delegate every ServerConn method through the rotation wrapper
        if name.startswith("_"):
            raise AttributeError(name)
        probe = getattr(self._conns[0], name)
        if not callable(probe):
            return probe

        def call(*args, **kwargs):
            return self._rotate_call(name, *args, **kwargs)
        return call


class HttpServerConn:
    """Client-agent transport over the HTTP API (the remote deployment
    shape; reference: client->server msgpack RPC, nomad/client_rpc.go).
    Implements the ServerConn interface from nomad_tpu.client.client."""

    def __init__(self, address: str = "http://127.0.0.1:4646",
                 timeout: float = 10.0, token: str = ""):
        import os
        # node endpoints need node:write when ACLs are on; agents take
        # their token from config or NOMAD_TOKEN like the reference client
        self.api = ApiClient(address, timeout=timeout,
                             token=token or os.environ.get("NOMAD_TOKEN",
                                                           ""))

    def register_node(self, node: Node) -> None:
        self.api.post("/v1/node/register", {"node": codec.encode(node)})

    def heartbeat(self, node_id: str) -> float:
        try:
            reply = self.api.post(f"/v1/node/{node_id}/heartbeat")
        except ApiError as e:
            if e.status == 404:     # unknown node: caller must re-register
                return 0.0
            raise
        return float(reply.get("heartbeat_ttl", 0.0))

    def pull_allocs(self, node_id: str, min_index: int,
                    timeout: float) -> tuple:
        reply = self.api.request(
            "GET", f"/v1/node/{node_id}/allocations",
            params={"index": min_index, "wait": f"{timeout}s"},
            timeout=timeout + 5.0)
        allocs = codec.decode(List[Allocation], reply.get("allocs", []))
        return allocs, int(reply.get("index", min_index))

    def update_allocs(self, updates: List[Allocation]) -> None:
        self.api.post("/v1/node/allocs-update",
                      {"allocs": [codec.encode(a) for a in updates]})

    def sign_identity(self, claims: dict):
        reply = self.api.post("/v1/node/identity-sign", {"claims": claims})
        return reply.get("token")

    def workload_variable(self, jwt: str, path: str):
        try:
            reply = self.api.post("/v1/workload/variable",
                                  {"identity": jwt, "path": path})
        except ApiError as e:
            if e.status == 404:
                return None
            if e.status == 403:
                raise PermissionError(str(e)) from e
            raise
        return reply.get("items")

    def csi_volume(self, namespace: str, vol_id: str):
        from ..structs.csi import CSIVolume
        try:
            raw = self.api.get(f"/v1/volume/csi/{vol_id}",
                               namespace=namespace)
        except ApiError as e:
            if e.status == 404:
                return None
            raise
        return codec.decode(CSIVolume, raw)

    def register_services(self, regs) -> None:
        self.api.post("/v1/node/services-register",
                      {"services": [codec.encode(r) for r in regs]})

    def get_alloc(self, alloc_id: str) -> Optional[Allocation]:
        try:
            data = self.api.get(f"/v1/allocation/{alloc_id}")
        except ApiError as e:
            if e.status == 404:
                return None
            raise
        return codec.decode(Allocation, data)
