"""Dev agent: single-process server + simulated fleet + HTTP API
(reference analog: `nomad agent -dev`, command/agent/command.go:775).

Run: python -m nomad_tpu.api.devagent [--nodes N] [--port P] [--tpu]
"""
from __future__ import annotations

import argparse
import signal
import sys
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="nomad-tpu dev agent")
    parser.add_argument("--nodes", type=int, default=3,
                        help="simulated client nodes")
    parser.add_argument("--port", type=int, default=4646)
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--tpu", action="store_true",
                        help="enable the tpu-binpack scheduler algorithm")
    parser.add_argument("--acl", action="store_true",
                        help="enable ACL enforcement (bootstrap via "
                             "POST /v1/acl/bootstrap)")
    parser.add_argument("--region", default="global")
    parser.add_argument("--join", action="append", default=[],
                        metavar="REGION=ADDR",
                        help="federate with another region's agent")
    parser.add_argument("--wan", action="store_true",
                        help="start the WAN gossip pool (regions then "
                             "discover each other via --wan-join)")
    parser.add_argument("--wan-join", action="append", default=[],
                        metavar="HOST:PORT",
                        help="join an existing WAN gossip member")
    parser.add_argument("--real-clients", action="store_true",
                        help="run full client agents with allocdirs "
                             "(enables /v1/client/fs endpoints)")
    parser.add_argument("--data-dir", default="",
                        help="client data dir (with --real-clients; "
                             "default: a temp dir)")
    parser.add_argument("--config", default="",
                        help="HCL agent config file (reference: "
                             "command/agent/config_parse.go); CLI flags "
                             "override file values")
    parser.add_argument("--eval-batching", action="store_true",
                        dest="eval_batching",
                        help="coalesce evals into fused solver dispatches")
    parser.add_argument("--batch-width", type=int, default=0,
                        dest="batch_width")
    parser.add_argument("--datacenter", default="dc1")
    # config file supplies DEFAULTS; explicitly-passed flags win
    pre, _ = parser.parse_known_args(argv)
    tls_cfg = None
    file_cfg = None
    if pre.config:
        from .config import load_agent_config
        file_cfg = load_agent_config(pre.config)
        parser.set_defaults(
            region=file_cfg.region,
            datacenter=file_cfg.datacenter,
            port=file_cfg.http_port,
            workers=file_cfg.server.workers,
            acl=file_cfg.server.acl_enabled,
            eval_batching=file_cfg.server.eval_batching,
            batch_width=file_cfg.server.batch_width,
            nodes=(file_cfg.client.simulated_nodes
                   if file_cfg.client.enabled else 0),
            real_clients=file_cfg.client.real_clients,
            data_dir=file_cfg.client.data_dir,
            tpu=(file_cfg.server.scheduler_algorithm
                 in ("tpu-binpack", "tpu-spread")))
        if file_cfg.tls.any:
            tls_cfg = file_cfg.tls
    args = parser.parse_args(argv)

    from .. import mock
    from ..client import SimClient
    from ..server import Server
    from ..structs import SchedulerConfiguration, SCHED_ALG_TPU_BINPACK
    from .http import HttpServer

    server = Server(num_workers=args.workers, acl_enabled=args.acl,
                    region=args.region,
                    eval_batching=args.eval_batching,
                    batch_width=args.batch_width or None)
    for spec in args.join:
        region, _, addr = spec.partition("=")
        if region and addr:
            server.join_federation(region, addr)
    if args.tpu:
        server.state.set_scheduler_config(SchedulerConfiguration(
            scheduler_algorithm=SCHED_ALG_TPU_BINPACK))
    server.start()

    scheme = ("https" if tls_cfg is not None and tls_cfg.enable_http
              else "http")
    # HTTP first: with --port 0 the bound port is only known afterwards,
    # and real clients advertise it to workloads (attr.nomad.api_addr)
    http = HttpServer(server, port=args.port, tls=tls_cfg)
    http.start()
    clients = []
    if args.real_clients:
        import os
        import tempfile
        from ..client.client import Client, LocalServerConn
        base = args.data_dir or tempfile.mkdtemp(prefix="nomad-tpu-dev-")
        for i in range(args.nodes):
            c = Client(LocalServerConn(server),
                       os.path.join(base, f"client{i}"),
                       name=f"dev-client-{i}",
                       api_addr=f"{scheme}://127.0.0.1:{http.port}",
                       serve_http=True)
            c.start()
            clients.append(c)
            http.add_client(c)
    else:
        for _ in range(args.nodes):
            c = SimClient(server, mock.node(datacenter=args.datacenter))
            c.start()
            clients.append(c)
    statsd = None
    if file_cfg is not None and file_cfg.telemetry.statsd_address:
        from ..server.telemetry import StatsdSink, metrics as _metrics
        statsd = StatsdSink(file_cfg.telemetry.statsd_address, _metrics,
                            interval_s=file_cfg.telemetry.interval_s)
        statsd.start()
        print(f"==> statsd sink: {file_cfg.telemetry.statsd_address}")
    if args.wan or args.wan_join:
        wan = server.enable_wan(f"{scheme}://127.0.0.1:{http.port}",
                                name=args.region)
        for spec in args.wan_join:
            host, _, port = spec.rpartition(":")
            if not port.isdigit():
                parser.error(f"--wan-join needs HOST:PORT, got {spec!r}")
            server.wan_join((host or "127.0.0.1", int(port)))
        print(f"==> WAN gossip: {wan.addr[0]}:{wan.addr[1]}")
    print(f"==> nomad-tpu dev agent: {scheme}://127.0.0.1:{http.port} "
          f"({args.nodes} simulated nodes, "
          f"algorithm={server.state.scheduler_config().scheduler_algorithm})")

    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        if statsd is not None:
            statsd.shutdown()
        http.shutdown()
        for c in clients:
            (c.stop if hasattr(c, "stop") else c.shutdown)()
        server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
