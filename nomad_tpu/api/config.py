"""Agent configuration files (reference:
/root/reference/command/agent/config_parse.go + config.go defaults/merge):
HCL config parsed with the in-repo HCL parser, merged over defaults, with
CLI flags taking final precedence (the reference's merge order).

Supported surface (the operational core):

    region       = "global"
    datacenter   = "dc1"
    ports        { http = 4646 }
    server       { enabled = true  workers = 4  eval_batching = true
                   batch_width = 8  acl_enabled = false
                   scheduler_algorithm = "tpu-binpack" }
    client       { enabled = true  simulated_nodes = 3  data_dir = "..." }
    tls          { http = true  rpc = true  ca_file = "..."
                   cert_file = "..."  key_file = "..." }

(prometheus needs no config: /v1/metrics?format=prometheus always serves)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..jobspec.hcl import Block, HclError, parse_hcl
from ..tlsutil import TLSConfig


@dataclass
class ServerConfig:
    enabled: bool = True
    workers: int = 2
    eval_batching: bool = False
    batch_width: int = 0
    acl_enabled: bool = False
    scheduler_algorithm: str = ""


@dataclass
class ClientConfig:
    enabled: bool = True
    simulated_nodes: int = 3
    real_clients: bool = False
    data_dir: str = ""


@dataclass
class TelemetryConfig:
    """(reference: the telemetry{} agent block,
    command/agent/command.go:1164 sink wiring)"""

    statsd_address: str = ""
    interval_s: float = 1.0


@dataclass
class AgentConfig:
    region: str = "global"
    datacenter: str = "dc1"
    http_port: int = 4646
    server: ServerConfig = field(default_factory=ServerConfig)
    client: ClientConfig = field(default_factory=ClientConfig)
    tls: TLSConfig = field(default_factory=TLSConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)


def _apply(obj, attrs: Dict[str, Any], mapping: Dict[str, str]) -> None:
    for key, attr in mapping.items():
        if key in attrs:
            setattr(obj, attr, attrs[key])


def parse_agent_config(src: str) -> AgentConfig:
    """Parse one agent config document. Raises HclError/ValueError on
    malformed input (admission-style: bad config must not half-apply)."""
    root = parse_hcl(src)
    cfg = AgentConfig()
    attrs = root.attrs()
    _apply(cfg, attrs, {"region": "region", "datacenter": "datacenter"})

    ports = root.first("ports")
    if ports is not None:
        p = ports.attrs()
        if "http" in p:
            cfg.http_port = int(p["http"])

    srv = root.first("server")
    if srv is not None:
        a = srv.attrs()
        _apply(cfg.server, a, {
            "enabled": "enabled", "workers": "workers",
            "eval_batching": "eval_batching", "batch_width": "batch_width",
            "acl_enabled": "acl_enabled",
            "scheduler_algorithm": "scheduler_algorithm"})
        cfg.server.workers = int(cfg.server.workers)
        cfg.server.batch_width = int(cfg.server.batch_width)

    cli = root.first("client")
    if cli is not None:
        a = cli.attrs()
        _apply(cfg.client, a, {
            "enabled": "enabled", "simulated_nodes": "simulated_nodes",
            "real_clients": "real_clients", "data_dir": "data_dir"})
        cfg.client.simulated_nodes = int(cfg.client.simulated_nodes)

    tel = root.first("telemetry")
    if tel is not None:
        a = tel.attrs()
        _apply(cfg.telemetry, a, {"statsd_address": "statsd_address",
                                  "interval": "interval_s"})
        cfg.telemetry.interval_s = float(cfg.telemetry.interval_s)

    tls = root.first("tls")
    if tls is not None:
        a = tls.attrs()
        _apply(cfg.tls, a, {
            "http": "enable_http", "rpc": "enable_rpc",
            "ca_file": "ca_file", "cert_file": "cert_file",
            "key_file": "key_file", "verify_incoming": "verify_incoming"})
        if cfg.tls.any and (not cfg.tls.cert_file or not cfg.tls.key_file):
            raise ValueError("tls block requires cert_file and key_file")
    return cfg


def load_agent_config(path: str) -> AgentConfig:
    with open(path, encoding="utf-8") as fh:
        return parse_agent_config(fh.read())
