"""TLS configuration for the HTTP API and server-to-server transport
(reference: /root/reference/nomad/rpc.go:31 TLS wrapping + helper/tlsutil;
agent tls{} config block, command/agent/config.go).

Mutual TLS: when a CA is configured, both sides verify peers against it
(the reference's verify_incoming/verify_outgoing model).
"""
from __future__ import annotations

import ssl
from dataclasses import dataclass
from typing import Optional


@dataclass
class TLSConfig:
    """(reference: config.TLSConfig -- the tls{} agent block)"""

    enable_http: bool = False
    enable_rpc: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    verify_incoming: bool = True

    @property
    def any(self) -> bool:
        return self.enable_http or self.enable_rpc


def server_context(cfg: TLSConfig) -> ssl.SSLContext:
    """Context for listeners: presents the server cert; requires client
    certs signed by the CA when verify_incoming."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cfg.cert_file, cfg.key_file)
    if cfg.ca_file:
        ctx.load_verify_locations(cfg.ca_file)
        if cfg.verify_incoming:
            ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_context(cfg: TLSConfig,
                   server_hostname: Optional[str] = None) -> ssl.SSLContext:
    """Context for outbound connections: verifies the server against the
    configured CA and presents our cert (mutual TLS). Without a CA the
    SYSTEM trust store applies with full hostname verification -- "no CA
    configured" must never mean "no verification"."""
    if cfg.ca_file:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(cfg.ca_file)
        # cluster-internal certs use fixed SANs, not per-host names
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_REQUIRED
    else:
        ctx = ssl.create_default_context()
    if cfg.cert_file:
        ctx.load_cert_chain(cfg.cert_file, cfg.key_file)
    return ctx
