"""nomad-tpu: a TPU-native cluster-scheduling framework.

A from-scratch re-design of the capabilities of HashiCorp Nomad (reference:
/root/reference) built TPU-first: the control plane (state store, eval broker,
plan applier, client agents, HTTP API) is host-side Python/C++, while the
scheduler's hot inner loop -- feasibility filtering, bin-pack/spread/affinity
scoring, and preemption search -- is reformulated as dense, vmapped JAX/XLA
computations over allocation x node resource matrices and solved on TPU.

Layout (mirrors SURVEY.md section 2 component inventory):
  structs/    data model: Job/TaskGroup/Task/Node/Allocation/Evaluation/Plan
              (reference: nomad/structs/)
  state/      MVCC state store with index-watch blocking queries
              (reference: nomad/state/)
  tensor/     tensorization: structs <-> packed dense int32/float32 matrices
  scheduler/  host-side reference-path scheduler -- the parity oracle
              (reference: scheduler/)
  solver/     the TPU solver core: vmapped feasibility/binpack/preemption
  server/     control plane: eval broker, plan queue+applier, workers,
              heartbeats, blocked evals, periodic dispatch, GC
              (reference: nomad/)
  client/     node agent: fingerprinting, alloc/task runners, drivers
              (reference: client/)
  api/        HTTP API + agent glue (reference: command/agent/)
  parallel/   device-mesh sharding of the solver (multi-chip scale axis)
"""

__version__ = "0.1.0"

# NOMAD_TPU_LOCKCHECK=1 installs the lock-order sanitizer before any
# package module constructs its locks (lockcheck.py); unset/0 is a true
# no-op -- one env read, threading untouched.
from . import lockcheck as _lockcheck  # noqa: E402

_lockcheck.maybe_install_from_env()

# NOMAD_TPU_JITCHECK=1 installs the device-dispatch discipline
# sanitizer before any module constructs a jitted callable
# (jitcheck.py); unset/0 is a true no-op -- one env read, jax
# untouched (and not even imported).
from . import jitcheck as _jitcheck  # noqa: E402

_jitcheck.maybe_install_from_env()

# NOMAD_TPU_STATECHECK=1 installs the MVCC snapshot-isolation &
# state-aliasing sanitizer before any store/table is constructed
# (statecheck.py); unset/0 is a true no-op -- one env read, the state
# classes untouched.
from . import statecheck as _statecheck  # noqa: E402

_statecheck.maybe_install_from_env()

# NOMAD_TPU_SCHEDCHECK=1 installs the deterministic schedule explorer
# and roots a controlled run at the importing thread (schedcheck.py);
# unset/0 is a true no-op -- one env read, Thread/Event/queue/sleep
# untouched and no controller observable.
from . import schedcheck as _schedcheck  # noqa: E402

_schedcheck.maybe_install_from_env()

# NOMAD_TPU_SHARDCHECK=1 installs the sharding-discipline sanitizer
# before any mesh program is constructed (shardcheck.py); unset/0 is a
# true no-op -- one env read, the parallel/mesh.py entry points
# untouched (and jax not even imported).
from . import shardcheck as _shardcheck  # noqa: E402

_shardcheck.maybe_install_from_env()
