"""MVCC snapshot-isolation & state-aliasing sanitizer ("statecheck").

The reference control plane runs NumCPU scheduler workers against MVCC
snapshots; ROADMAP item 2 commits this repo to the same refactor.  Every
one of those workers will depend on invariants that today are enforced
by nothing but the single coalescing worker's accidental serialization:
a snapshot read is version-consistent, nothing mutates state reachable
from a published snapshot, and every alloc version transition is
coverable from the PR-6 delta journal.  This module is the third
sanitizer in the lockcheck/jitcheck family -- it turns violations of
the store discipline into named reports with witness stacks before the
multi-worker refactor multiplies the interleavings that expose them.

What it checks while enabled:

  * **torn snapshot reads** -- every instrumented ``AllocTable`` read
    (``pack`` / ``fold_verify`` / ``_fold_verify_all`` /
    ``count_placed`` / ``usage_by_node``) re-checks the table version
    on exit: a version that moved DURING one read means a writer raced
    a lockless reader (all mutators hold the store lock, so the reader
    cannot have).  On top of that, per-thread *snapshot scopes* group
    reads: the plan applier's verification opens a STRICT scope
    (``plan_apply._evaluate_plan``) -- observing two different table
    versions inside one strict scope is a torn read with both witness
    stacks.  Scheduler eval scopes (``worker.invoke_scheduler``) are
    non-strict: the fast packing path is *documented* to observe usage
    newer than the eval's snapshot (the applier re-verifies every
    plan), so version drift there is recorded as report-only
    ``drift`` entries, not violations.
  * **aliasing writes** -- mutation of state reachable from a published
    snapshot or a version-keyed memo, caught three ways: (1) published
    memo arrays (NodeMatrix payloads, usage bases, pack memos --
    everything ``tensor/pack`` freezes) register here and a rotating
    sampled re-fingerprint catches both a thawed ``writeable`` flag and
    a content change; (2) the live fold views ``_fold_verify_all``
    hands out register with the table version -- content drift while
    the version stands still means a consumer wrote into the store's
    resident fold; (3) table mutators must bump ``version`` (a
    version-blind mutation invalidates every version-keyed cache
    silently), and a rotating sample of recently-written rows is
    re-hashed -- a row whose bytes changed under an unchanged version
    was mutated behind the instrumented mutators' back.
  * **delta-journal coverage gaps** -- an ``allocs`` index bump that
    carries ``delta=None`` creates a span ``alloc_deltas_since`` can
    never cover, silently degrading every incremental-memo holder to a
    wholesale rebuild.  The designed wholesale writes (snapshot
    restore) mark themselves with ``with statecheck.mark_uncoverable
    (reason):``; everything else is reported with a witness stack.
    Report-only (the journal itself stays correct: a ``None`` entry is
    an explicit gap, never a wrong delta).
  * **write-skew witnesses** -- two plan results landing in ONE
    ``apply_plan_results_batch`` transaction touching the same node:
    the group-commit applier guarantees batch disjointness through its
    conflict path (``_select_group``), so an overlap inside a
    committed batch means two same-snapshot plans skipped it -- the
    exact hazard ROADMAP-2's N workers multiply.  Report-only until
    triaged (the re-verify still bounds the damage today).
  * **stale version-keyed memos** -- a version-tagged cache entry that
    outlived its invalidation: the audit sweeps ``_NODE_MATRIX_CACHE``
    and the constcache registry for entries older than the latest
    node-table write each cache was notified of, and the usage-base /
    fold-cache hit paths assert the served entry's version token
    matches the snapshot's (``note_memo_served``).

Kill-switch semantics mirror lockcheck/jitcheck: OFF by default,
``NOMAD_TPU_STATECHECK=0``/unset is a true no-op -- the ``AllocTable``
and ``StateStore`` methods are untouched and no wrapper is observable
anywhere (bitwise-parity-tested on a real dispatch + plan-commit
cycle).  ``NOMAD_TPU_STATECHECK=1`` at process start (or ``enable()``
at runtime, how the conftest fixture runs the plan-batch / pack-delta /
churn-storm / lpq suites) installs the patches.

State rides the usual surfaces: ``stats.statecheck`` in
``/v1/agent/self``, ``operator statecheck [--stacks]`` CLI (exit 1 on
torn reads or aliasing writes), ``statecheck.json`` in operator debug
bundles, ``nomad.statecheck.{torn_read,aliasing_write,journal_gap,
write_skew,stale_memo}`` counters, and ``state_*`` fields in bench
artifacts gated by scripts/check_bench_regress.py.

Knobs: ``NOMAD_TPU_STATECHECK`` (off; ``1`` installs at import),
``NOMAD_TPU_STATECHECK_STACK`` (16: witness stack depth),
``NOMAD_TPU_STATECHECK_MAX`` (256: retained reports per class),
``NOMAD_TPU_STATECHECK_REHASH`` (32: registered rows/arrays re-hashed
per state() read).
"""
from __future__ import annotations

import hashlib
import os
import sys
import threading
import traceback
from collections import OrderedDict
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SELF_FILE = os.path.abspath(__file__).rstrip("co")  # .pyc -> .py

_ACTIVE = False                  # module-global fast gate (one dict read)
_REAL: dict = {}                 # originals, captured at first enable

# checker-internal state; _slock is a leaf: nothing is acquired under
# it and no user code runs under it
_slock = threading.Lock()

_stack_depth = 16
_max_reports = 256
_rehash_n = 32

# report lists + dedup keys, one pair per detector class
_torn: List[dict] = []
_torn_keys: set = set()
_aliasing: List[dict] = []
_aliasing_keys: set = set()
_gaps: List[dict] = []
_gap_keys: set = set()
_skews: List[dict] = []
_skew_keys: set = set()
_stale: List[dict] = []
_stale_keys: set = set()
_drifts: List[dict] = []         # report-only: designed optimistic reads
_drift_keys: set = set()

# published-array registry: id(arr) -> (arr, digest, site). numpy
# arrays are not weakref-able, so strong refs under a FIFO byte budget
# (the jitcheck trade: an opt-in sanitizer pins a bounded sample).
_published: "OrderedDict[int, tuple]" = OrderedDict()
_PUB_CAP = 1024
_PUB_MAX_BYTES = 64 * 1024 * 1024
_pub_bytes = [0]
_pub_cursor = [0]
# fold-view registry: id(arr) -> (arr, table, version, digest, site)
_fold_views: "OrderedDict[int, tuple]" = OrderedDict()
_FOLD_CAP = 64
# sampled row registry: (id(table), row) -> (table, digest, version)
_rows: "OrderedDict[tuple, tuple]" = OrderedDict()
_ROWS_CAP = 512
_row_cursor = [0]
_ROWS_PER_WRITE = 4              # rows fingerprinted per mutator call

# the newest node-table index each cache layer was told to invalidate
# to (fed by the patched _bump); the stale-memo sweep compares
# version-tagged entries against it
_latest_nodes_index = [0]

_counters = {"reads": 0, "mutations": 0, "scopes": 0, "journal_writes": 0,
             "uncoverable_marked": 0, "batch_commits": 0,
             "memo_serves": 0, "reports_dropped": 0}

_tls = threading.local()


def _scopes() -> list:
    st = getattr(_tls, "scopes", None)
    if st is None:
        st = _tls.scopes = []
    return st


def _uncoverable_depth() -> int:
    return getattr(_tls, "uncoverable", 0)


def _rel(path: str) -> str:
    if path.startswith(_REPO_ROOT):
        return path[len(_REPO_ROOT) + 1:]
    return path


def _metrics():
    """Telemetry sink, or None mid-teardown -- the sanitizer must
    never take the process down with it."""
    try:
        from .server.telemetry import metrics
        return metrics
    except Exception:  # noqa: BLE001
        return None


def _span_ids() -> str:
    """The enclosing PR-3 tracing span's eval ids, or '-'."""
    try:
        from .server.tracing import tracer
        return ",".join(tracer.current_ids()) or "-"
    except Exception:  # noqa: BLE001
        return "-"


def _repo_site() -> str:
    """First repo frame outside this module, as 'rel/path.py:line'."""
    f = sys._getframe(2)
    for _ in range(24):
        if f is None:
            return "?"
        fn = f.f_code.co_filename
        if fn.startswith(_REPO_ROOT) and \
                os.path.abspath(fn) != _SELF_FILE:
            return f"{_rel(fn)}:{f.f_lineno}"
        f = f.f_back
    return "?"


def _fmt_stack() -> str:
    try:
        return "".join(traceback.format_stack(
            sys._getframe(2), limit=_stack_depth))
    except Exception:  # noqa: BLE001 -- diagnostics must never raise
        return "<stack unavailable>"


def _digest(arr) -> bytes:
    import numpy as np
    h = hashlib.blake2b(digest_size=16)
    h.update(str((arr.dtype.str, arr.shape)).encode())
    h.update(np.ascontiguousarray(arr).data)
    return h.digest()


def _report(lst: List[dict], keys: set, key, payload: dict) -> bool:
    """Dedup + cap + record one finding; returns True when it is new.
    Callers emit their own counter with a literal series name (the
    metrics-doc checker reads emit sites, and one finding = one
    increment of its class counter).  Findings recorded during an
    active schedcheck run carry its schedule witness (seed + policy +
    decision step): ``operator schedcheck --replay <seed>`` re-runs
    the interleaving that manifested them."""
    from . import schedcheck
    payload.setdefault("schedule", schedcheck.witness())
    with _slock:
        if key in keys:
            return False
        keys.add(key)
        if len(lst) >= _max_reports:
            _counters["reports_dropped"] += 1
            return False
        lst.append(payload)
    return True


def _incr_metric_torn() -> None:
    m = _metrics()
    if m is not None:
        m.incr("nomad.statecheck.torn_read")


# ----------------------------------------------------------------------
# snapshot scopes (torn reads + drift)


class _Scope:
    __slots__ = ("tag", "strict", "obs", "span", "baseline")

    def __init__(self, tag: str, strict: bool, baseline):
        self.tag = tag
        self.strict = strict
        # id(table) -> (version, site) of the first observation; the
        # baseline (the eval snapshot's table version at scope open)
        # seeds it so drift against the *snapshot* is visible even when
        # the scope performs a single read
        self.obs: Dict[int, tuple] = {}
        self.span = _span_ids()
        self.baseline = baseline


class _NullScope:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SCOPE = _NullScope()


class _ScopeCM:
    __slots__ = ("_scope",)

    def __init__(self, scope: _Scope):
        self._scope = scope

    def __enter__(self):
        _scopes().append(self._scope)
        _counters["scopes"] += 1
        return self

    def __exit__(self, *exc):
        st = _scopes()
        if st and st[-1] is self._scope:
            st.pop()
        return False


def eval_scope(snapshot=None):
    """Per-eval snapshot scope (worker.invoke_scheduler): reads during
    the scope are attributed to it; version drift against the eval's
    snapshot is recorded report-only (the fast packing path observes
    newer usage BY DESIGN -- the applier re-verifies)."""
    if not _ACTIVE:
        return _NULL_SCOPE
    baseline = None
    if snapshot is not None:
        table = getattr(snapshot, "alloc_table", None)
        if table is not None:
            baseline = (id(table), table.version)
    return _ScopeCM(_Scope("eval", False, baseline))


def strict_scope(tag: str):
    """A scope whose reads MUST observe one table version (the plan
    applier's verification: fold + python walk against one state).
    Two versions inside a strict scope is a torn read."""
    if not _ACTIVE:
        return _NULL_SCOPE
    return _ScopeCM(_Scope(tag, True, None))


def _note_scope_read(op: str, table, version: int) -> None:
    st = _scopes()
    if not st:
        return
    scope = st[-1]
    prev = scope.obs.get(id(table))
    if prev is not None and prev[0] == version:
        return                    # steady state: no frame walk paid
    site = _repo_site()
    if prev is None:
        if scope.baseline is not None and scope.baseline[0] == id(table) \
                and scope.baseline[1] != version:
            _note_drift(scope, op, site, scope.baseline[1], version)
        scope.obs[id(table)] = (version, site)
        return
    if scope.strict:
        if _report(
                _torn, _torn_keys, ("scope", scope.tag, op, site),
                {"kind": "scope-tear", "scope": scope.tag, "op": op,
                 "site": site, "first_site": prev[1],
                 "versions": [prev[0], version], "evals": scope.span,
                 "thread": threading.current_thread().name,
                 "stack": _fmt_stack()}):
            _incr_metric_torn()
    else:
        _note_drift(scope, op, site, prev[0], version)
    scope.obs[id(table)] = (version, site)


def _note_drift(scope: _Scope, op: str, site: str, v0: int,
                v1: int) -> None:
    _report(
        _drifts, _drift_keys, (scope.tag, op, site),
        {"scope": scope.tag, "op": op, "site": site,
         "versions": [v0, v1], "evals": scope.span,
         "thread": threading.current_thread().name})


# ----------------------------------------------------------------------
# AllocTable read instrumentation (torn reads)


def _mk_read(name: str, real):
    def wrapper(self, *a, **k):
        if not _ACTIVE:
            return real(self, *a, **k)
        _counters["reads"] += 1
        v0 = self.version
        try:
            return real(self, *a, **k)
        finally:
            v1 = self.version
            if v1 != v0:
                if _report(
                        _torn, _torn_keys,
                        ("intra", name, _repo_site()),
                        {"kind": "intra-read-tear", "op": name,
                         "site": _repo_site(),
                         "versions": [v0, v1], "evals": _span_ids(),
                         "thread": threading.current_thread().name,
                         "stack": _fmt_stack()}):
                    _incr_metric_torn()
            _note_scope_read(name, self, v1)

    wrapper.__name__ = name
    wrapper._statecheck_wrapped = True
    return wrapper


def _fold_verify_all_wrapper(self):
    """_fold_verify_all hands out VIEWS of the live incremental fold
    columns on the delta path -- register their content against the
    table version so a consumer writing into them (they cannot be
    frozen: the table itself maintains them in place under the store
    lock) is caught by the audit."""
    real = _REAL["table._fold_verify_all"]
    if not _ACTIVE:
        return real(self)
    _counters["reads"] += 1
    v0 = self.version
    try:
        out = real(self)
        with _slock:
            already = any(v[1] is self and v[2] == self.version
                          for v in _fold_views.values())
        if not already:
            # one registration per (table, version): steady-state
            # verifies re-serve the same views and pay nothing
            site = _repo_site()
            with _slock:
                for arr in out:
                    if getattr(arr, "nbytes", 0) == 0:
                        continue
                    _fold_views[id(arr)] = (arr, self, self.version,
                                            _digest(arr), site)
                while len(_fold_views) > _FOLD_CAP:
                    _fold_views.popitem(last=False)
        return out
    finally:
        v1 = self.version
        if v1 != v0:
            if _report(
                    _torn, _torn_keys,
                    ("intra", "_fold_verify_all", _repo_site()),
                    {"kind": "intra-read-tear",
                     "op": "_fold_verify_all",
                     "site": _repo_site(), "versions": [v0, v1],
                     "evals": _span_ids(),
                     "thread": threading.current_thread().name,
                     "stack": _fmt_stack()}):
                _incr_metric_torn()
        _note_scope_read("_fold_verify_all", self, v1)


# ----------------------------------------------------------------------
# AllocTable mutator instrumentation (aliasing writes)


def _note_aliasing(kind: str, site: str, detail: str) -> None:
    if _report(
            _aliasing, _aliasing_keys, (kind, site),
            {"kind": kind, "site": site, "detail": detail,
             "thread": threading.current_thread().name,
             "stack": _fmt_stack()}):
        m = _metrics()
        if m is not None:
            m.incr("nomad.statecheck.aliasing_write")


def _row_digest(table, row: int) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    for col in ("node_slot", "cpu", "mem", "disk", "live",
                "live_strict", "special", "job_hash", "jobtg_hash"):
        h.update(bytes(getattr(table, col)[row:row + 1].data))
    h.update(bytes(table.ports[row].data))
    return h.digest()


def _register_rows(table, rows) -> None:
    with _slock:
        for row in rows[:_ROWS_PER_WRITE]:
            _rows[(id(table), int(row))] = (
                table, _row_digest(table, int(row)), table.version)
        while len(_rows) > _ROWS_CAP:
            _rows.popitem(last=False)


def _mk_mutator(name: str, real, must_bump):
    """``must_bump(self, args) -> bool``: whether this call is required
    to advance ``version`` (an empty upsert_many or a remove() of an
    unknown id legitimately leaves it alone). The real method is read
    from _REAL at call time so tests can stub a buggy mutator under
    the wrapper."""
    key = f"table.{name}"

    def wrapper(self, *a, **k):
        real = _REAL[key]
        if not _ACTIVE:
            return real(self, *a, **k)
        _counters["mutations"] += 1
        v0 = self.version
        # kwargs-only calls (nothing in the repo does this) skip the
        # must-bump judgment rather than index a missing positional
        required = must_bump(self, a) if a or name in (
            "register_node", "compact") else False
        try:
            return real(self, *a, **k)
        finally:
            if required and self.version == v0:
                _note_aliasing(
                    "version-blind-mutation", _repo_site(),
                    f"AllocTable.{name} mutated rows without bumping "
                    f"version (every version-keyed cache above is now "
                    f"silently stale)")
            elif a and self.version != v0 and \
                    name in ("upsert", "upsert_many"):
                allocs = a[0] if name == "upsert_many" else [a[0]]
                rows = [self._row_of[al.id] for al in
                        list(allocs)[:_ROWS_PER_WRITE]
                        if al.id in self._row_of]
                _register_rows(self, rows)

    wrapper.__name__ = name
    wrapper._statecheck_wrapped = True
    return wrapper


# ----------------------------------------------------------------------
# published memo arrays (aliasing writes, jitcheck-style registry)


def note_published(arr, site: Optional[str] = None) -> None:
    """An array became reachable from a published snapshot or a
    version-keyed memo (tensor/pack freezes route here): it must be
    ``writeable=False`` and its content must never change again."""
    if not _ACTIVE:
        return
    if getattr(arr, "nbytes", None) is None:
        return
    site = site or _repo_site()
    writable_now = bool(getattr(arr, "flags", None) is not None
                        and arr.flags.writeable)
    nbytes = int(arr.nbytes)
    with _slock:
        if id(arr) not in _published:
            _pub_bytes[0] += nbytes
        _published[id(arr)] = (arr, _digest(arr), site)
        while _published and (len(_published) > _PUB_CAP
                              or _pub_bytes[0] > _PUB_MAX_BYTES):
            _, (old, _d, _s) = _published.popitem(last=False)
            _pub_bytes[0] -= int(getattr(old, "nbytes", 0))
    if writable_now:
        _note_aliasing("published-writeable", site,
                       "array published to a snapshot/memo without "
                       "writeable=False")


def note_memo_served(kind: str, entry_version, live_version,
                     site: Optional[str] = None) -> None:
    """A version-keyed memo hit: the served entry's version token must
    match the version the caller's snapshot pins (hit paths that skip
    their catch-up/refold on a mismatched token serve stale state)."""
    if not _ACTIVE:
        return
    _counters["memo_serves"] += 1
    if entry_version is None or live_version is None:
        return
    if entry_version == live_version:
        return
    site = site or _repo_site()
    if _report(
            _stale, _stale_keys, (kind, site),
            {"kind": kind, "site": site,
             "entry_version": int(entry_version),
             "live_version": int(live_version), "evals": _span_ids(),
             "thread": threading.current_thread().name,
             "stack": _fmt_stack()}):
        m = _metrics()
        if m is not None:
            m.incr("nomad.statecheck.stale_memo")


# ----------------------------------------------------------------------
# delta-journal coverage (gaps) + write-skew + stale-memo feeds
# (StateStore patches)


class _Uncoverable:
    __slots__ = ("reason", "_entered")

    def __init__(self, reason: str):
        self.reason = reason
        self._entered = False

    def __enter__(self):
        if _ACTIVE:
            self._entered = True
            _tls.uncoverable = _uncoverable_depth() + 1
            _counters["uncoverable_marked"] += 1
        return self

    def __exit__(self, *exc):
        if self._entered:
            _tls.uncoverable = max(0, _uncoverable_depth() - 1)
        return False


def mark_uncoverable(reason: str) -> _Uncoverable:
    """Marks a write that REPLACES alloc state wholesale (snapshot
    restore): its delta-less journal entry is an explicit gap, not a
    silent one, so the checker stays quiet about it."""
    return _Uncoverable(reason)


def _patched_bump(self, *tables, delta=None):
    if _ACTIVE:
        if "allocs" in tables:
            _counters["journal_writes"] += 1
            if delta is None and _uncoverable_depth() == 0:
                site = _repo_site()
                if _report(
                        _gaps, _gap_keys, site,
                        {"site": site, "tables": list(tables),
                         "evals": _span_ids(),
                         "thread": threading.current_thread().name,
                         "stack": _fmt_stack()}):
                    m = _metrics()
                    if m is not None:
                        m.incr("nomad.statecheck.journal_gap")
    idx = _REAL["store._bump"](self, *tables, delta=delta)
    if _ACTIVE and "nodes" in tables:
        ni = self._table_index.get("nodes", 0)
        with _slock:
            if ni > _latest_nodes_index[0]:
                _latest_nodes_index[0] = ni
    return idx


def _patched_apply_batch(self, entries):
    if _ACTIVE and len(entries) > 1:
        _counters["batch_commits"] += 1
        seen: Dict[str, str] = {}
        for result, _evs in entries:
            label = "?"
            for nid in list(result.node_allocation) + \
                    list(result.node_update):
                allocs = (result.node_allocation.get(nid)
                          or result.node_update.get(nid) or [])
                if allocs:
                    label = allocs[0].eval_id or "?"
                first = seen.get(nid)
                if first is not None and first != label:
                    if _report(
                            _skews, _skew_keys, (nid, first, label),
                            {"node": nid, "plans": [first, label],
                             "evals": _span_ids(),
                             "thread": threading.current_thread().name,
                             "stack": _fmt_stack()}):
                        m = _metrics()
                        if m is not None:
                            m.incr("nomad.statecheck.write_skew")
                elif first is None:
                    seen[nid] = label
    return _REAL["store.apply_batch"](self, entries)


# ----------------------------------------------------------------------
# audit pass (rotating samples; runs on every state() read)


def verify_state(sample: Optional[int] = None) -> int:
    """Re-check the registries: published-array freeze + content, live
    fold views, sampled row fingerprints, and the version-tagged cache
    sweeps. Returns the number of NEW findings."""
    if not _ACTIVE:
        return 0
    n = sample if sample is not None else _rehash_n
    found = 0
    with _slock:
        pub = list(_published.items())
        cursor = _pub_cursor[0]
        views = list(_fold_views.items())
        rows = list(_rows.items())
        row_cursor = _row_cursor[0]
    # published memo arrays: thawed flag or content drift
    for i in range(min(n, len(pub))):
        key, (arr, digest, site) = pub[(cursor + i) % len(pub)]
        if getattr(arr, "flags", None) is not None \
                and arr.flags.writeable:
            if _note_aliasing_ret("published-thawed", site,
                                  "published memo array became "
                                  "writeable again"):
                found += 1
            continue
        try:
            fresh = _digest(arr)
        except Exception:  # noqa: BLE001 -- resized/retyped arrays
            fresh = b"?"
        if fresh != digest:
            if _note_aliasing_ret(
                    "published-mutated", site,
                    f"published memo array content changed after "
                    f"registration (dtype={arr.dtype}, "
                    f"shape={arr.shape})"):
                found += 1
            with _slock:
                if key in _published:
                    _published[key] = (arr, fresh, site)
    if pub:
        with _slock:
            _pub_cursor[0] = (cursor + n) % max(len(_published), 1)
    # live fold views: content drift under an unchanged table version
    for key, (arr, table, version, digest, site) in views:
        if table.version != version:
            with _slock:
                _fold_views.pop(key, None)
            continue
        try:
            fresh = _digest(arr)
        except Exception:  # noqa: BLE001
            fresh = b"?"
        if fresh != digest:
            if _note_aliasing_ret(
                    "fold-view-mutated", site,
                    "a consumer wrote into the store's resident fold "
                    "columns (handed out as read views by "
                    "_fold_verify_all)"):
                found += 1
            with _slock:
                _fold_views.pop(key, None)
    # sampled rows: bytes changed under an unchanged version
    for i in range(min(n, len(rows))):
        key, (table, digest, version) = rows[(row_cursor + i)
                                             % len(rows)]
        if table.version != version:
            with _slock:
                _rows.pop(key, None)
            continue
        try:
            fresh = _row_digest(table, key[1])
        except Exception:  # noqa: BLE001 -- compacted/shrunk tables
            with _slock:
                _rows.pop(key, None)
            continue
        if fresh != digest:
            if _note_aliasing_ret(
                    "row-mutated", f"row {key[1]}",
                    "alloc-table row bytes changed without a version "
                    "bump (direct column write bypassing the "
                    "instrumented mutators)"):
                found += 1
            with _slock:
                _rows.pop(key, None)
    if rows:
        with _slock:
            _row_cursor[0] = (row_cursor + n) % max(len(_rows), 1)
    found += _sweep_version_tagged_caches()
    return found


def _note_aliasing_ret(kind: str, site: str, detail: str) -> bool:
    before = len(_aliasing)
    _note_aliasing(kind, site, detail)
    return len(_aliasing) > before


def _sweep_version_tagged_caches() -> int:
    """Entries tagged with a node-table version older than the latest
    write their cache was notified of should have been invalidated by
    that notification; survivors are stale memos."""
    latest = _latest_nodes_index[0]
    if not latest:
        return 0
    found = 0
    try:
        from .tensor import pack as tpack
        with tpack._NODE_MATRIX_LOCK:
            stale_keys = [k for k in tpack._NODE_MATRIX_CACHE
                          if k[0] < latest]
        for k in stale_keys:
            if _report(
                    _stale, _stale_keys, ("node_matrix", k[0]),
                    {"kind": "node_matrix", "site": "tensor/pack.py",
                     "entry_version": int(k[0]),
                     "live_version": int(latest), "evals": "-",
                     "thread": threading.current_thread().name,
                     "stack": "<audit sweep>"}):
                found += 1
                m = _metrics()
                if m is not None:
                    m.incr("nomad.statecheck.stale_memo")
    except Exception:  # noqa: BLE001 -- solver stack not imported
        pass
    try:
        import sys as _sys
        cc = _sys.modules.get("nomad_tpu.solver.constcache")
        if cc is not None:
            with cc._LOCK:
                stale_vs = [ent.version for ent in cc._CACHE.values()
                            if ent.version is not None
                            and ent.version < latest]
            for v in stale_vs:
                if _report(
                        _stale, _stale_keys, ("constcache", v),
                        {"kind": "constcache",
                         "site": "solver/constcache.py",
                         "entry_version": int(v),
                         "live_version": int(latest), "evals": "-",
                         "thread": threading.current_thread().name,
                         "stack": "<audit sweep>"}):
                    found += 1
                    m = _metrics()
                    if m is not None:
                        m.incr("nomad.statecheck.stale_memo")
    except Exception:  # noqa: BLE001
        pass
    return found


# ----------------------------------------------------------------------
# lifecycle


def enabled() -> bool:
    return _ACTIVE


_TABLE_READS = ("pack", "fold_verify", "count_placed", "usage_by_node")


def enable() -> None:
    """Patch the AllocTable read/write paths and the StateStore journal
    + batch-commit entry points. Arrays/rows published before enable
    are invisible until re-registered (documented gap, same shape as
    lockcheck's pre-enable locks)."""
    global _ACTIVE, _stack_depth, _max_reports, _rehash_n
    with _slock:
        if _ACTIVE:
            return
        _stack_depth = int(os.environ.get(
            "NOMAD_TPU_STATECHECK_STACK", "16"))
        _max_reports = int(os.environ.get(
            "NOMAD_TPU_STATECHECK_MAX", "256"))
        _rehash_n = max(1, int(os.environ.get(
            "NOMAD_TPU_STATECHECK_REHASH", "32")))
    from .state.alloc_table import AllocTable
    from .state.store import StateStore
    if not _REAL:
        for name in _TABLE_READS:
            _REAL[f"table.{name}"] = getattr(AllocTable, name)
        _REAL["table._fold_verify_all"] = AllocTable._fold_verify_all
        _REAL["table.upsert"] = AllocTable.upsert
        _REAL["table.upsert_many"] = AllocTable.upsert_many
        _REAL["table.remove"] = AllocTable.remove
        _REAL["table.register_node"] = AllocTable.register_node
        _REAL["table.compact"] = AllocTable.compact
        _REAL["store._bump"] = StateStore._bump
        _REAL["store.apply_batch"] = StateStore.apply_plan_results_batch
    for name in _TABLE_READS:
        setattr(AllocTable, name,
                _mk_read(name, _REAL[f"table.{name}"]))
    AllocTable._fold_verify_all = _fold_verify_all_wrapper
    AllocTable.upsert = _mk_mutator(
        "upsert", _REAL["table.upsert"], lambda t, a: True)
    AllocTable.upsert_many = _mk_mutator(
        "upsert_many", _REAL["table.upsert_many"],
        lambda t, a: bool(len(a[0])))
    AllocTable.remove = _mk_mutator(
        "remove", _REAL["table.remove"],
        lambda t, a: a[0] in t._row_of)
    AllocTable.register_node = _mk_mutator(
        "register_node", _REAL["table.register_node"],
        lambda t, a: True)
    AllocTable.compact = _mk_mutator(
        "compact", _REAL["table.compact"], lambda t, a: True)
    StateStore._bump = _patched_bump
    StateStore.apply_plan_results_batch = _patched_apply_batch
    _ACTIVE = True


def disable() -> None:
    """Restore the real methods. Scopes opened while enabled drain
    naturally (their context managers go inert)."""
    global _ACTIVE
    if not _ACTIVE:
        return
    _ACTIVE = False
    from .state.alloc_table import AllocTable
    from .state.store import StateStore
    for name in _TABLE_READS:
        setattr(AllocTable, name, _REAL[f"table.{name}"])
    AllocTable._fold_verify_all = _REAL["table._fold_verify_all"]
    AllocTable.upsert = _REAL["table.upsert"]
    AllocTable.upsert_many = _REAL["table.upsert_many"]
    AllocTable.remove = _REAL["table.remove"]
    AllocTable.register_node = _REAL["table.register_node"]
    AllocTable.compact = _REAL["table.compact"]
    StateStore._bump = _REAL["store._bump"]
    StateStore.apply_plan_results_batch = _REAL["store.apply_batch"]


def maybe_install_from_env() -> None:
    if os.environ.get("NOMAD_TPU_STATECHECK", "0") == "1":
        enable()


# ----------------------------------------------------------------------
# reporting


def state() -> dict:
    """Full checker state (capped); rides /v1/agent/self, the operator
    CLI, debug bundles and bench artifacts."""
    if _ACTIVE:
        verify_state()
    with _slock:
        return {
            "enabled": _ACTIVE,
            "reads": _counters["reads"],
            "mutations": _counters["mutations"],
            "scopes": _counters["scopes"],
            "journal_writes": _counters["journal_writes"],
            "uncoverable_marked": _counters["uncoverable_marked"],
            "batch_commits": _counters["batch_commits"],
            "memo_serves": _counters["memo_serves"],
            "published_arrays": len(_published),
            "registered_rows": len(_rows),
            "reports_dropped": _counters["reports_dropped"],
            "torn_read_count": len(_torn),
            "aliasing_write_count": len(_aliasing),
            "journal_gap_count": len(_gaps),
            "write_skew_count": len(_skews),
            "stale_memo_count": len(_stale),
            "drift_count": len(_drifts),
            "torn_reads": [dict(r) for r in _torn],
            "aliasing_writes": [dict(r) for r in _aliasing],
            "journal_gaps": [dict(r) for r in _gaps],
            "write_skews": [dict(r) for r in _skews],
            "stale_memos": [dict(r) for r in _stale],
            "drifts": [dict(r) for r in _drifts],
        }


def _reset_for_tests() -> None:
    with _slock:
        _torn.clear()
        _torn_keys.clear()
        _aliasing.clear()
        _aliasing_keys.clear()
        _gaps.clear()
        _gap_keys.clear()
        _skews.clear()
        _skew_keys.clear()
        _stale.clear()
        _stale_keys.clear()
        _drifts.clear()
        _drift_keys.clear()
        _published.clear()
        _fold_views.clear()
        _rows.clear()
        _pub_bytes[0] = 0
        _pub_cursor[0] = 0
        _row_cursor[0] = 0
        _latest_nodes_index[0] = 0
        for k in _counters:
            _counters[k] = 0
