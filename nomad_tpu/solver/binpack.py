"""The TPU solver core: the scheduler's inner loop as a kernel family.

This is the north star (BASELINE.json): the per-candidate work of
BinPackIterator.Next (reference: scheduler/rank.go:205) -- fit check,
BestFit-v3 scoring, anti-affinity/penalty/affinity/spread scoring, and the
LimitIterator/MaxScoreIterator selection semantics (select.go, stack.go:82)
-- with the within-eval sequential dependence (earlier placements consume
resources, context.go:176 ProposedAllocs) carried through a lax.scan.
Three kernels share those semantics, picked by lane shape:

  - **wavefront** (solve_lane_wave; the production fast path): uniform-ask
    lanes admit a closed-form per-node placement capacity, so the scan
    carries only a B-slot buffer of the front-of-order fit nodes -- O(B)
    per step, a compact (P+B, 8+S) table as the only transfer, spread
    counts in the carry, penalties in the scan xs.
  - **dense** (solve_placements[_preempt]): every node rescored per step;
    handles the node-coupling features the wavefront gates out
    (distinct_property, devices, cores, dense preemption search).
  - **system** (solve_system): one INDEPENDENT fit+score per node, no
    window at all (scheduler_system.go semantics).

Selection parity: the reference scans a shuffled, log2-limited window with
up-to-3 low-score skips and picks the max score (first-seen wins ties).
Every kernel reproduces that exactly (see _select_window and the
wavefront's in-buffer emulation); the oracle suites gate all of them.

All arrays are in SHUFFLED ORDER (nomad_tpu/scheduler/util.py
shuffled_order); callers map chosen indexes back to node ids.
"""
from __future__ import annotations

import functools
import threading
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import jitcheck


def _single_flight(fn):
    """Serialize invocations of a program factory: functools.lru_cache
    does NOT single-flight, so two pipelined generations hitting one
    COLD shape bucket concurrently would both execute the factory --
    a duplicated multi-second XLA trace/compile of the same program,
    and exactly the fresh-identical-closure-per-call pattern jitcheck
    flags as a steady-state retrace (found by the dispatch-pipeline
    overlap test racing a cold wave bucket).  Warm lookups pay one
    uncontended lock acquire per dispatch."""
    lock = threading.Lock()

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with lock:
            return fn(*args, **kwargs)
    # the lru wrapper's cache management stays reachable (tests and
    # the jitcheck gauntlet rebuild buckets via cache_clear); not a
    # store-derived memo, so version-keyed-memo has nothing to key
    for attr in ("cache_clear", "cache_info"):
        setattr(wrapped, attr, getattr(fn, attr))
    return wrapped

MAX_SKIP = 3               # select.go maxSkip
SKIP_THRESHOLD = 0.0       # select.go skipScoreThreshold
BINPACK_MAX = 18.0


def _dense_unroll() -> int:
    """Dense-scan unroll: 4 on TPU (amortizes per-step loop overhead in
    the O(N)-per-step kernels), 1 elsewhere (the body is large; unrolling
    multiplies compile time on CPU test/virtual-mesh runs)."""
    import jax as _jax
    return 4 if _jax.default_backend() == "tpu" else 1

_EMPTY_I2 = np.zeros((0, 0), dtype=np.int32)
_EMPTY_I1 = np.zeros(0, dtype=np.int32)
_EMPTY_B1 = np.zeros(0, dtype=bool)
_EMPTY_F1 = np.zeros(0, dtype=np.float32)
_EMPTY_F3 = np.zeros((0, 0, 0), dtype=np.float32)
_EMPTY_I3 = np.zeros((0, 0, 0), dtype=np.int32)


class PlacementBatch(NamedTuple):
    """Per-placement (scan-step) inputs, each shaped (P,)."""

    ask_cpu: jnp.ndarray
    ask_mem: jnp.ndarray
    ask_disk: jnp.ndarray
    n_dyn_ports: jnp.ndarray    # int32 dynamic ports asked
    has_static: jnp.ndarray     # bool: TG asks static ports
    limit: jnp.ndarray          # int32 scan-window limit for this placement
    count: jnp.ndarray          # int32 TG desired count (anti-affinity denom)
    penalty_idx: jnp.ndarray    # int32 node index to penalize, -1 = none
    active: jnp.ndarray         # bool: real placement vs padding
    # reserved-core ask (rank.go:481-524): effective cpu becomes
    # ask_cpu + ask_cores * mhz_per_core[node]; zeros when no core asks
    ask_cores: jnp.ndarray = _EMPTY_I1


class NodeState(NamedTuple):
    """Scan carry: mutable usage along the node axis, shaped (N,)."""

    used_cpu: jnp.ndarray
    used_mem: jnp.ndarray
    used_disk: jnp.ndarray
    placed: jnp.ndarray         # int32: this job+TG alloc count per node
    placed_job: jnp.ndarray     # int32: this job's alloc count (any TG)
    static_free: jnp.ndarray    # bool: TG's static ports still free
    dyn_avail: jnp.ndarray      # int32: free dynamic-range ports
    spread_counts: jnp.ndarray  # (S, V) int32
    dp_counts: jnp.ndarray = _EMPTY_I2     # (Dp, Vd) int32 allocs per value
    dev_free: jnp.ndarray = _EMPTY_I3      # (R, Gd, N) int32 free
                                           # instances; -1 = no match
    cores_free: jnp.ndarray = _EMPTY_I1    # (N,) int32 free reservable
                                           # cores; 0-size when no core ask


class NodeConst(NamedTuple):
    """Static per-eval node arrays, shaped (N,) (+ spread/distinct/device
    tables; the trailing fields default to 0-size axes, statically skipped
    at trace time)."""

    cpu_cap: jnp.ndarray
    mem_cap: jnp.ndarray
    disk_cap: jnp.ndarray
    feasible: jnp.ndarray       # bool: constraint/driver/etc feasibility
    affinity: jnp.ndarray       # float: normalized affinity score per node
    has_affinity: jnp.ndarray   # bool scalar
    distinct_hosts: jnp.ndarray  # bool scalar: distinct_hosts applies
    distinct_job_level: jnp.ndarray  # bool scalar: it is a JOB-level
                                     # constraint (blocks any of the job's
                                     # allocs, feasible.go:507)
    # spreads
    spread_vidx: jnp.ndarray    # (S, N) int32 value index per node, -1 missing
    spread_desired: jnp.ndarray  # (S, V) float; -1 = no target for value
    spread_has_targets: jnp.ndarray  # (S,) bool
    spread_weights: jnp.ndarray      # (S,) float
    spread_sum_weights: jnp.ndarray  # float scalar
    n_spreads: jnp.ndarray      # int32 scalar (0 = no spreads)
    # distinct_property (feasible.go:661, propertyset.go): per constraint
    # d, value index per node (-1 = attr missing -> infeasible) + limit
    dp_vidx: jnp.ndarray = _EMPTY_I2       # (Dp, N) int32
    dp_limit: jnp.ndarray = _EMPTY_I1       # (Dp,) int32
    dp_tg_scope: jnp.ndarray = _EMPTY_B1   # (Dp,) bool (info only)
    # devices (feasible.go:1270, scheduler/device.go): per TG device
    # request r and matching node device-group g
    dev_aff: jnp.ndarray = _EMPTY_F3       # (R, Gd, N) affinity score
    dev_count: jnp.ndarray = _EMPTY_I1     # (R,) int32 asked count
    dev_sum_weight: jnp.ndarray = np.float32(0.0)  # scalar sum |weights|
    # cores (rank.go:340-344): per-node MHz per reservable core; 0-size
    # when the lane carries no core asks (statically skipped at trace time)
    mhz_per_core: jnp.ndarray = _EMPTY_F1  # (N,) float


def _binpack_score(free_cpu, free_mem, spread_alg: bool):
    """BestFit v3 / worst-fit, normalized to [0,1]
    (reference: structs/funcs.go:236,263; rank.go:571 fitness/18)."""
    total = jnp.power(10.0, free_cpu) + jnp.power(10.0, free_mem)
    raw = jnp.where(spread_alg, total - 2.0, 20.0 - total)
    return jnp.clip(raw, 0.0, BINPACK_MAX) / BINPACK_MAX


def _spread_score(state: NodeState, const: NodeConst, dtype):
    """Vectorized SpreadIterator.Next + evenSpreadScoreBoost
    (reference: spread.go:128-270). Returns (N,) total spread boost."""
    S, N = const.spread_vidx.shape
    if S == 0:
        return jnp.zeros(N, dtype=dtype)

    def one_spread(vidx, desired, has_targets, weight, counts):
        # vidx: (N,) value index; counts: (V,) current counts
        missing = vidx < 0
        safe_vidx = jnp.maximum(vidx, 0)
        used = counts[safe_vidx] + 1          # include this placement
        weight_frac = weight / jnp.maximum(const.spread_sum_weights, 1e-9)

        # -- target path (reference: spread.go:171-200)
        des = desired[safe_vidx]
        no_target = des < 0.0
        boost_t = jnp.where(
            no_target, -1.0,
            jnp.where(des == 0.0, -1.0,
                      (des - used.astype(dtype)) / jnp.maximum(des, 1e-9)
                      * weight_frac))

        # -- even-spread path (reference: spread.go:216-270)
        present = counts > 0
        any_present = jnp.any(present)
        big = jnp.iinfo(jnp.int32).max
        min_c = jnp.min(jnp.where(present, counts, big))
        max_c = jnp.max(jnp.where(present, counts, 0))
        current = counts[safe_vidx]
        min_f = min_c.astype(dtype)
        max_f = max_c.astype(dtype)
        cur_f = current.astype(dtype)
        even = jnp.where(
            current != min_c,
            jnp.where(min_c == 0, -1.0, (min_f - cur_f) / jnp.maximum(min_f, 1e-9)),
            jnp.where(min_c == max_c, -1.0,
                      (max_f - min_f) / jnp.maximum(min_f, 1e-9)))
        boost_e = jnp.where(any_present, even, 0.0)

        per_node = jnp.where(has_targets, boost_t, boost_e)
        return jnp.where(missing, -1.0, per_node).astype(dtype)

    boosts = jax.vmap(one_spread)(
        const.spread_vidx, const.spread_desired, const.spread_has_targets,
        const.spread_weights, state.spread_counts)
    return jnp.sum(boosts, axis=0)


def _select_window(score, fit, limit, dtype):
    """Dense emulation of LimitIterator + MaxScoreIterator over nodes laid
    out in shuffled order (reference: select.go:38-77, stack.go:82).

    Yield set = first min(L, C) counted options (C = feasible minus the
    first <=3 low-score skips) plus skipped options as fallback when the
    source ran dry; winner = max score, earliest yield wins ties.
    Returns (chosen_index, chosen_score, n_yielded); chosen = -1 if none.
    """
    n = score.shape[0]
    low = fit & (score <= SKIP_THRESHOLD)
    skip_rank = jnp.cumsum(low.astype(jnp.int32))        # 1-based among low
    skipped = low & (skip_rank <= MAX_SKIP)
    counted = fit & ~skipped
    cpos = jnp.cumsum(counted.astype(jnp.int32))         # 1-based
    total_counted = cpos[-1] if n > 0 else jnp.int32(0)
    window = counted & (cpos <= limit)
    # fallback: yield skipped (in skip order) for the deficit
    deficit = jnp.maximum(0, limit - jnp.minimum(total_counted, limit))
    srank = jnp.cumsum(skipped.astype(jnp.int32))
    fallback = skipped & (srank <= deficit)
    yielded = window | fallback
    # yield order: counted first (cpos), then skipped (limit + srank)
    order = jnp.where(window, cpos, limit + srank)
    neg_inf = jnp.array(-jnp.inf, dtype=dtype)
    eff_score = jnp.where(yielded, score, neg_inf)
    best_score = jnp.max(eff_score)
    is_best = yielded & (eff_score == best_score)
    big = jnp.iinfo(jnp.int32).max
    best_order = jnp.min(jnp.where(is_best, order, big))
    chosen = jnp.argmax(is_best & (order == best_order))
    any_yield = jnp.any(yielded)
    chosen = jnp.where(any_yield, chosen, -1)
    return chosen, jnp.where(any_yield, best_score, neg_inf), \
        jnp.sum(yielded.astype(jnp.int32))


class PreemptTables(NamedTuple):
    """Per-eval candidate-eviction tables for dense preemption
    (reference: scheduler/preemption.go PreemptForTaskGroup :201-271,
    filterAndGroupPreemptibleAllocs :666, basicResourceDistance :611,
    filterSuperset :705). Candidate axis A = padded max allocs/node; rows
    are in the SAME order as ctx.proposed_allocs so float-tie argmins break
    identically to the host's first-strictly-smaller scan."""

    cpu: jnp.ndarray         # (N, A) comparable usage per candidate
    mem: jnp.ndarray         # (N, A)
    disk: jnp.ndarray        # (N, A)
    prio: jnp.ndarray        # (N, A) int32 job priority
    maxp: jnp.ndarray        # (N, A) int32 migrate.max_parallel
    grp: jnp.ndarray         # (N, A) int32 index into counts, -1 none
    dyn_ports: jnp.ndarray   # (N, A) int32 dynamic-range ports held
    static_rel: jnp.ndarray  # (N, A) bool holds an asked static port
    valid: jnp.ndarray       # (N, A) bool eligible candidate
    job_prio: jnp.ndarray    # () int32 scheduling job's priority


class PreemptState(NamedTuple):
    """Preemption scan carry: which candidates this eval already evicted,
    and per-(job,tg) eviction counts feeding the max_parallel penalty
    (reference: preemption.go scoreForTaskGroup / currentPreemptions)."""

    evicted: jnp.ndarray     # (N, A) bool
    counts: jnp.ndarray      # (G,) int32


MAX_PARALLEL_PENALTY = 50.0  # preemption.go:16
PREEMPT_SCORE_RATE = 0.0048  # rank.go preemptionScore
PREEMPT_SCORE_ORIGIN = 2048.0


def _distance(need_c, need_m, need_d, used_c, used_m, used_d):
    """basicResourceDistance (preemption.go:611): component is 0 when the
    corresponding ask dimension is <= 0."""
    dc = jnp.where(need_c > 0, (need_c - used_c) / jnp.maximum(need_c, 1e-9),
                   0.0)
    dm = jnp.where(need_m > 0, (need_m - used_m) / jnp.maximum(need_m, 1e-9),
                   0.0)
    dd = jnp.where(need_d > 0, (need_d - used_d) / jnp.maximum(need_d, 1e-9),
                   0.0)
    return jnp.sqrt(dc * dc + dm * dm + dd * dd)


def _preempt_search(state: NodeState, pstate: PreemptState,
                    ptab: PreemptTables, const: NodeConst,
                    ask_cpu, ask_mem, ask_disk, dtype,
                    lo: int, hi: Optional[int]):
    """Vectorized PreemptForTaskGroup over node positions [lo:hi).

    Per node: greedily pick eligible candidates (ascending priority group,
    then minimal distance+penalty) until the freed+free resources superset
    the ask, then filterSuperset. Returns per-node (met, evict_mask (n,A),
    freed_cpu/mem/disk, net_prio) for the slice."""
    sl = slice(lo, hi)
    used_c = ptab.cpu[sl].astype(dtype)
    used_m = ptab.mem[sl].astype(dtype)
    used_d = ptab.disk[sl].astype(dtype)
    valid_now = ptab.valid[sl] & ~pstate.evicted[sl]
    eligible = valid_now & (ptab.job_prio - ptab.prio[sl] >= 10)
    return _preempt_search_core(
        used_c, used_m, used_d, ptab.prio[sl], ptab.maxp[sl], ptab.grp[sl],
        valid_now, eligible, const.cpu_cap[sl], const.mem_cap[sl],
        const.disk_cap[sl], pstate.counts, ask_cpu, ask_mem, ask_disk,
        dtype)


def _preempt_search_core(used_c, used_m, used_d, prio, maxp, grp,
                         valid_now, eligible, cpu_cap, mem_cap, disk_cap,
                         counts, ask_cpu, ask_mem, ask_disk, dtype,
                         static_iters: bool = False):
    """The search itself over raw (n, A) candidate arrays -- shared by the
    dense per-node form (_preempt_search) and the windowed wavefront form
    (the slot buffer passes its B carried slots). ``static_iters`` runs
    the greedy as a fixed-length A-step scan instead of a while_loop:
    identical results (the body no-ops once a node is met), but
    straight-line compilable -- inside another scan a dynamic-trip-count
    loop of tiny (B, A) ops is pure dispatch latency."""
    n, A = used_c.shape

    # The host Preemptor's nodeRemaining subtracts only the CANDIDATE
    # allocs (own-job and terminal allocs are filtered before the
    # subtraction, preemption.go setCandidates) -- NOT the full carried
    # usage. An eviction set that "covers" the ask by this accounting can
    # still fail the authoritative AllocsFit re-check (rank.go:541), which
    # the caller models as the fit2 clamp.
    avail_c0 = cpu_cap - jnp.sum(jnp.where(valid_now, used_c, 0.0), axis=1)
    avail_m0 = mem_cap - jnp.sum(jnp.where(valid_now, used_m, 0.0), axis=1)
    avail_d0 = disk_cap - jnp.sum(jnp.where(valid_now, used_d, 0.0), axis=1)

    # max_parallel penalty from preemptions committed earlier in this eval
    n_pre = jnp.where(grp >= 0, counts[jnp.maximum(grp, 0)], 0)
    penalty = jnp.where((maxp > 0) & (n_pre >= maxp),
                        ((n_pre + 1 - maxp).astype(dtype)
                         * MAX_PARALLEL_PENALTY), 0.0)

    big_i = jnp.iinfo(jnp.int32).max
    inf = jnp.array(jnp.inf, dtype=dtype)

    def cond(carry):
        picked, av_c, av_m, av_d, _, _, _ = carry
        # allMet starts False in the host loop: the first pick is
        # unconditional even when available already covers the ask
        met = ((av_c >= ask_cpu) & (av_m >= ask_mem) & (av_d >= ask_disk)
               & jnp.any(picked, axis=1))
        cand = eligible & ~picked
        return jnp.any(~met & jnp.any(cand, axis=1))

    def body(carry):
        picked, av_c, av_m, av_d, ne_c, ne_m, ne_d = carry
        met = ((av_c >= ask_cpu) & (av_m >= ask_mem) & (av_d >= ask_disk)
               & jnp.any(picked, axis=1))
        cand = eligible & ~picked
        # ascending priority-group gating (preemption.go:666): only the
        # lowest remaining priority is pickable this round
        cur_prio = jnp.min(jnp.where(cand, prio, big_i), axis=1)
        in_group = cand & (prio == cur_prio[:, None])
        dist = _distance(ne_c[:, None], ne_m[:, None], ne_d[:, None],
                         used_c, used_m, used_d) + penalty
        key = jnp.where(in_group, dist, inf)
        pick = jnp.argmin(key, axis=1)          # first-min ties = host order
        do = ~met & jnp.any(in_group, axis=1)
        onehot = (jnp.arange(A)[None, :] == pick[:, None]) & do[:, None]
        pc = jnp.sum(jnp.where(onehot, used_c, 0.0), axis=1)
        pm = jnp.sum(jnp.where(onehot, used_m, 0.0), axis=1)
        pd = jnp.sum(jnp.where(onehot, used_d, 0.0), axis=1)
        return (picked | onehot, av_c + pc, av_m + pm, av_d + pd,
                ne_c - pc, ne_m - pm, ne_d - pd)

    init = (jnp.zeros((n, A), dtype=bool), avail_c0, avail_m0, avail_d0,
            jnp.full(n, ask_cpu, dtype=dtype),
            jnp.full(n, ask_mem, dtype=dtype),
            jnp.full(n, ask_disk, dtype=dtype))
    if static_iters:
        def scan_body(carry, _):
            return body(carry), None
        out_carry, _ = jax.lax.scan(scan_body, init, None, length=A,
                                    unroll=min(A, 8))
        picked, av_c, av_m, av_d, _, _, _ = out_carry
    else:
        picked, av_c, av_m, av_d, _, _, _ = jax.lax.while_loop(
            cond, body, init)
    met = ((av_c >= ask_cpu) & (av_m >= ask_mem) & (av_d >= ask_disk)
           & jnp.any(picked, axis=1))

    # filterSuperset (preemption.go:705): re-add picked in DESCENDING
    # distance-to-original-ask order until the ask is covered again.
    d0 = _distance(ask_cpu, ask_mem, ask_disk, used_c, used_m, used_d)
    sort_key = jnp.where(picked, -d0, inf)       # ascending(-d) = desc(d)
    order = jnp.argsort(sort_key, axis=1, stable=True)
    oc = jnp.take_along_axis(jnp.where(picked, used_c, 0.0), order, axis=1)
    om = jnp.take_along_axis(jnp.where(picked, used_m, 0.0), order, axis=1)
    od = jnp.take_along_axis(jnp.where(picked, used_d, 0.0), order, axis=1)
    cum_c = avail_c0[:, None] + jnp.cumsum(oc, axis=1)
    cum_m = avail_m0[:, None] + jnp.cumsum(om, axis=1)
    cum_d = avail_d0[:, None] + jnp.cumsum(od, axis=1)
    met_at = ((cum_c >= ask_cpu) & (cum_m >= ask_mem) & (cum_d >= ask_disk))
    # first position (in sorted order) where cumulative covers the ask;
    # keep sorted positions 0..first_met inclusive
    first_met = jnp.argmax(met_at, axis=1)
    keep_sorted = (jnp.arange(A)[None, :] <= first_met[:, None])
    in_picked_sorted = jnp.take_along_axis(picked, order, axis=1)
    keep_sorted = keep_sorted & in_picked_sorted
    evict = jnp.zeros_like(picked)
    evict = jax.vmap(lambda e, o, k: e.at[o].set(k))(evict, order,
                                                     keep_sorted)

    freed_c = jnp.sum(jnp.where(evict, used_c, 0.0), axis=1)
    freed_m = jnp.sum(jnp.where(evict, used_m, 0.0), axis=1)
    freed_d = jnp.sum(jnp.where(evict, used_d, 0.0), axis=1)

    # netPriority (rank.go): max prio + sum/max over the evicted set
    prio_f = prio.astype(dtype)
    mx = jnp.max(jnp.where(evict, prio_f, 0.0), axis=1)
    sm = jnp.sum(jnp.where(evict, prio_f, 0.0), axis=1)
    net_prio = jnp.where(mx > 0, mx + sm / jnp.maximum(mx, 1e-9), 0.0)
    return met, evict, freed_c, freed_m, freed_d, net_prio


# The selection window only ever yields the first `limit` (<= ~14 for 10K
# nodes) counted options in shuffled order, plus up to MAX_SKIP skips. So
# whenever the first FAST_T shuffled positions contain >= limit counted
# options, the outcome is fully determined by those FAST_T nodes -- the
# common case on healthy fleets. The scan step then runs O(FAST_T) work
# instead of O(N), falling back to the full pass via lax.cond otherwise.
FAST_T = 1024


def _scoring_parts(state: NodeState, const: NodeConst, b, dtype,
                   spread_alg: bool, lo: int, hi: Optional[int]):
    """Shared per-node fit + scoring over positions [lo:hi): returns
    (fit, final, feas_nonres, other_sum, nscores, new_cpu, new_mem)."""
    (ask_cpu, ask_mem, ask_disk, n_dyn, has_static, limit, count,
     penalty_idx, active, ask_cores) = b
    sl = slice(lo, hi)
    cpu_cap = const.cpu_cap[sl]
    mem_cap = const.mem_cap[sl]
    n = cpu_cap.shape[0]

    # reserved cores (rank.go:481-524): core-asking tasks' cpu becomes
    # mhz_per_core * cores on the candidate node, so the effective cpu
    # ask is node-dependent; count-exact core availability gates fit
    has_cores = const.mhz_per_core.shape[0] > 0
    eff_cpu = (ask_cpu + ask_cores.astype(dtype) * const.mhz_per_core[sl]
               if has_cores else ask_cpu)
    new_cpu = state.used_cpu[sl] + eff_cpu
    new_mem = state.used_mem[sl] + ask_mem
    new_disk = state.used_disk[sl] + ask_disk

    distinct_count = jnp.where(const.distinct_job_level,
                               state.placed_job[sl], state.placed[sl])
    # non-resource feasibility (constraints/ports/distinct) -- the part a
    # successful preemption cannot rescue
    feas_nonres = (const.feasible[sl]
                   & (state.dyn_avail[sl] >= n_dyn)
                   & (state.static_free[sl] | ~has_static)
                   & (~const.distinct_hosts | (distinct_count == 0)))

    # distinct_property (feasible.go:661): attr must resolve and the
    # job/tg's alloc count at this node's value must be under the limit
    Dp = const.dp_vidx.shape[0]
    if Dp > 0:
        vidx_d = const.dp_vidx[:, sl]
        safe_d = jnp.maximum(vidx_d, 0)
        cnt_d = jnp.take_along_axis(state.dp_counts, safe_d, axis=1)
        feas_nonres &= jnp.all(
            (vidx_d >= 0) & (cnt_d < const.dp_limit[:, None]), axis=0)

    # devices (feasible.go:1270 + device.go): every request needs a
    # matching group with enough free instances; affinity score of the
    # best group per request contributes one normalized score component
    R = const.dev_aff.shape[0]
    dev_score = None
    if R > 0:
        free_g = state.dev_free[:, :, sl]
        ok_g = free_g >= const.dev_count[:, None, None]
        feas_nonres &= jnp.all(jnp.any(ok_g, axis=1), axis=0)
        neg_inf = jnp.array(-jnp.inf, dtype=dtype)
        aff_g = jnp.where(ok_g, const.dev_aff[:, :, sl].astype(dtype),
                          neg_inf)
        best_aff = jnp.max(aff_g, axis=1)                   # (R, n)
        sum_aff = jnp.sum(jnp.where(jnp.any(ok_g, axis=1), best_aff, 0.0),
                          axis=0)
        dev_present = const.dev_sum_weight > 0
        dev_score = jnp.where(
            dev_present,
            sum_aff / jnp.maximum(const.dev_sum_weight, 1e-9), 0.0)
    if has_cores:
        feas_nonres &= state.cores_free[sl] >= ask_cores
    fit = (feas_nonres
           & (new_cpu <= cpu_cap)
           & (new_mem <= mem_cap)
           & (new_disk <= const.disk_cap[sl]))

    free_cpu = 1.0 - new_cpu / jnp.maximum(cpu_cap, 1e-9)
    free_mem = 1.0 - new_mem / jnp.maximum(mem_cap, 1e-9)
    binpack = _binpack_score(free_cpu, free_mem, spread_alg)

    collisions = state.placed[sl]
    anti = jnp.where(
        collisions > 0,
        -(collisions.astype(dtype) + 1.0) / jnp.maximum(
            count.astype(dtype), 1.0),
        0.0)
    idx = jnp.arange(lo, lo + n)
    is_penalty = idx == penalty_idx
    resched = jnp.where(is_penalty, -1.0, 0.0)
    aff = jnp.where(const.has_affinity, const.affinity[sl], 0.0)
    aff_present = aff != 0.0
    sliced_const = const._replace(spread_vidx=const.spread_vidx[:, sl])
    spread_total = _spread_score(state, sliced_const, dtype)
    spread_present = spread_total != 0.0

    nscores = (1
               + (collisions > 0).astype(dtype)
               + is_penalty.astype(dtype)
               + aff_present.astype(dtype)
               + spread_present.astype(dtype))
    other_sum = anti + resched + aff + spread_total
    if dev_score is not None:
        dev_present_f = (const.dev_sum_weight > 0).astype(dtype)
        nscores = nscores + dev_present_f
        other_sum = other_sum + dev_score
    final = (binpack + other_sum) / nscores
    return (fit, final, feas_nonres, other_sum, nscores, new_cpu, new_mem,
            new_disk)


def _window_outputs(final, fit, limit, dtype, lo):
    chosen, cscore, n_yield = _select_window(final, fit, limit, dtype)
    low = fit & (final <= SKIP_THRESHOLD)
    skip_rank = jnp.cumsum(low.astype(jnp.int32))
    skipped = low & (skip_rank <= MAX_SKIP)
    counted_total = jnp.sum((fit & ~skipped).astype(jnp.int32))
    chosen = jnp.where(chosen >= 0, chosen + lo, -1)
    return chosen, cscore, n_yield, counted_total


def _score_and_select(state: NodeState, const: NodeConst, b, dtype,
                      spread_alg: bool, lo: int, hi: Optional[int]):
    """One Stack.Select over node positions [lo:hi) (static slice).
    Returns (chosen global index, score, n_yield, counted_in_slice)."""
    limit = b[5]
    fit, final = _scoring_parts(state, const, b, dtype, spread_alg,
                                lo, hi)[:2]
    return _window_outputs(final, fit, limit, dtype, lo)


def _score_and_select_preempt(state: NodeState, pstate: PreemptState,
                              ptab: PreemptTables, const: NodeConst, b,
                              dtype, spread_alg: bool,
                              lo: int, hi: Optional[int]):
    """Stack.Select with eviction enabled (BinPackIterator evict=True,
    rank.go:545-565): nodes that fail the resource fit but have a
    successful preemption search are yielded with the post-eviction
    binpack score plus the preemption penalty (rank.go:851 logistic on
    netPriority), exactly like the host chain. Returns the plain window
    outputs plus the chosen node's eviction row and freed resources."""
    (ask_cpu, ask_mem, ask_disk, n_dyn, has_static, limit, count,
     penalty_idx, active, ask_cores) = b
    sl = slice(lo, hi)
    (fit, final, feas_nonres, other_sum, nscores, new_cpu, new_mem,
     new_disk) = _scoring_parts(state, const, b, dtype, spread_alg, lo, hi)

    met, evict, freed_c, freed_m, freed_d, net_prio = _preempt_search(
        state, pstate, ptab, const, ask_cpu, ask_mem, ask_disk, dtype,
        lo, hi)

    # fit2: the authoritative re-check after eviction (rank.go:541 ->
    # preemption insufficient under FULL usage -> node exhausted). The
    # search's candidates-only accounting can overstate availability when
    # this eval already placed on the node.
    fit2 = ((new_cpu - freed_c <= const.cpu_cap[sl])
            & (new_mem - freed_m <= const.mem_cap[sl])
            & (new_disk - freed_d <= const.disk_cap[sl]))
    fit_p = feas_nonres & ~fit & met & fit2
    free_cpu_p = 1.0 - (new_cpu - freed_c) / jnp.maximum(
        const.cpu_cap[sl], 1e-9)
    free_mem_p = 1.0 - (new_mem - freed_m) / jnp.maximum(
        const.mem_cap[sl], 1e-9)
    binpack_p = _binpack_score(free_cpu_p, free_mem_p, spread_alg)
    pscore = 1.0 / (1.0 + jnp.exp(
        PREEMPT_SCORE_RATE * (net_prio - PREEMPT_SCORE_ORIGIN)))
    final_p = (binpack_p + other_sum + pscore) / (nscores + 1.0)

    fit_c = fit | fit_p
    final_c = jnp.where(fit_p, final_p, final)
    chosen, cscore, n_yield, counted = _window_outputs(
        final_c, fit_c, limit, dtype, lo)

    # Gather the chosen node's eviction info (slice-local index)
    local = jnp.clip(chosen - lo, 0, evict.shape[0] - 1)
    was_preempt = (chosen >= 0) & fit_p[local]
    evict_row = jnp.where(was_preempt, evict[local],
                          jnp.zeros_like(evict[0]))
    freed = jnp.where(
        was_preempt,
        jnp.stack([freed_c[local], freed_m[local], freed_d[local]]),
        jnp.zeros(3, dtype=dtype))
    return chosen, cscore, n_yield, counted, evict_row, freed


def _commit_tables(state: NodeState, new_state: NodeState,
                   const: NodeConst, do, safe) -> NodeState:
    """Shared per-step commit of the spread / distinct_property / device
    carry tables for the winning node."""
    sel_vidx = const.spread_vidx[:, safe]               # (S,)
    S, V = state.spread_counts.shape
    if S > 0:
        upd = ((jnp.arange(V)[None, :] == jnp.maximum(sel_vidx, 0)[:, None])
               & (sel_vidx >= 0)[:, None] & do)
        new_state = new_state._replace(
            spread_counts=state.spread_counts + upd.astype(jnp.int32))

    Dp = const.dp_vidx.shape[0]
    if Dp > 0:
        dvidx = const.dp_vidx[:, safe]                  # (Dp,)
        Vd = state.dp_counts.shape[1]
        upd = ((jnp.arange(Vd)[None, :] == jnp.maximum(dvidx, 0)[:, None])
               & (dvidx >= 0)[:, None] & do)
        new_state = new_state._replace(
            dp_counts=state.dp_counts + upd.astype(jnp.int32))

    R = const.dev_aff.shape[0]
    if R > 0:
        Gd = state.dev_free.shape[1]
        free_c = state.dev_free[:, :, safe]             # (R, Gd)
        ok_gc = free_c >= const.dev_count[:, None]
        neg_inf = jnp.array(-jnp.inf, dtype=const.dev_aff.dtype)
        aff_c = jnp.where(ok_gc, const.dev_aff[:, :, safe], neg_inf)
        g_star = jnp.argmax(aff_c, axis=1)              # (R,) first-max
        oh = (jnp.arange(Gd)[None, :] == g_star[:, None])
        dec = (oh & do) * const.dev_count[:, None]
        new_state = new_state._replace(
            dev_free=state.dev_free.at[:, :, safe].add(
                -dec.astype(jnp.int32)))
    return new_state


def _solve_placements_impl(const: NodeConst, init: NodeState,
                           batch: PlacementBatch, spread_alg: bool = False,
                           dtype_name: str = "float32"):
    """Place a batch of allocations sequentially via lax.scan.

    Each step reproduces one Stack.Select call (stack.go:128): score every
    node against current usage, select within the limited window, commit the
    winner's resources into the carry. Returns (chosen (P,), scores (P,),
    n_yielded (P,), final NodeState).
    """
    dtype = jnp.dtype(dtype_name)
    n_total = const.cpu_cap.shape[0]
    use_fast = n_total > 2 * FAST_T
    has_cores = const.mhz_per_core.shape[0] > 0

    def step(state: NodeState, b):
        (ask_cpu, ask_mem, ask_disk, n_dyn, has_static, limit, count,
         penalty_idx, active, ask_cores) = b

        if use_fast:
            # fast path: the window resolved within the first FAST_T
            # shuffled positions -- valid iff they contain >= limit
            # counted options (then the full-pass window is identical)
            f_chosen, f_score, f_yield, f_counted = _score_and_select(
                state, const, b, dtype, spread_alg, 0, FAST_T)

            def full(_):
                c, s, y, _cnt = _score_and_select(
                    state, const, b, dtype, spread_alg, 0, None)
                return c, s, y

            def fast(_):
                return f_chosen, f_score, f_yield

            chosen, cscore, n_yield = jax.lax.cond(
                f_counted >= limit, fast, full, operand=None)
        else:
            chosen, cscore, n_yield, _ = _score_and_select(
                state, const, b, dtype, spread_alg, 0, None)

        do = active & (chosen >= 0)
        safe = jnp.maximum(chosen, 0)
        # O(1) scatter updates: only the winner's usage changes
        add_f = do.astype(dtype)
        add_i = do.astype(jnp.int32)
        eff_cpu = (ask_cpu + ask_cores.astype(dtype)
                   * const.mhz_per_core[safe] if has_cores else ask_cpu)
        new_state = state._replace(
            used_cpu=state.used_cpu.at[safe].add(add_f * eff_cpu),
            used_mem=state.used_mem.at[safe].add(add_f * ask_mem),
            used_disk=state.used_disk.at[safe].add(add_f * ask_disk),
            placed=state.placed.at[safe].add(add_i),
            placed_job=state.placed_job.at[safe].add(add_i),
            static_free=state.static_free.at[safe].set(
                state.static_free[safe] & ~(do & has_static)),
            dyn_avail=state.dyn_avail.at[safe].add(-add_i * n_dyn),
        )
        if has_cores:
            new_state = new_state._replace(
                cores_free=state.cores_free.at[safe].add(
                    -add_i * ask_cores))
        new_state = _commit_tables(state, new_state, const, do, safe)
        chosen_out = jnp.where(do, chosen, -1)
        return new_state, (chosen_out, cscore, n_yield)

    ask_cores_xs = (batch.ask_cores if batch.ask_cores.shape[0]
                    else jnp.zeros_like(batch.count))
    final_state, (chosen, scores, n_yielded) = jax.lax.scan(
        step, init,
        (batch.ask_cpu, batch.ask_mem, batch.ask_disk, batch.n_dyn_ports,
         batch.has_static, batch.limit, batch.count, batch.penalty_idx,
         batch.active, ask_cores_xs), unroll=_dense_unroll())
    return chosen, scores, n_yielded, final_state


solve_placements = functools.partial(
    jax.jit, static_argnames=("spread_alg", "dtype_name"))(
        _solve_placements_impl)


def _solve_placements_preempt_impl(const: NodeConst, init: NodeState,
                                   batch: PlacementBatch,
                                   ptab: PreemptTables,
                                   pinit: PreemptState,
                                   spread_alg: bool = False,
                                   dtype_name: str = "float32"):
    """solve_placements with dense preemption: each scan step runs the
    eviction-enabled select; committing a preempting winner releases the
    evicted candidates' resources and ports into the carry and bumps the
    per-(job,tg) eviction counts (the reference's plan.NodePreemptions +
    currentPreemptions bookkeeping, generic_sched.go:924 + preemption.go).

    Extra outputs: evict_rows (P, A) bool -- candidate rows evicted by each
    placement on its chosen node."""
    dtype = jnp.dtype(dtype_name)
    n_total = const.cpu_cap.shape[0]
    use_fast = n_total > 2 * FAST_T
    G = pinit.counts.shape[0]
    A = ptab.cpu.shape[1]

    def step(carry, b):
        state, pstate = carry
        (ask_cpu, ask_mem, ask_disk, n_dyn, has_static, limit, count,
         penalty_idx, active, ask_cores) = b

        if use_fast:
            f = _score_and_select_preempt(
                state, pstate, ptab, const, b, dtype, spread_alg,
                0, FAST_T)

            def full(_):
                return _score_and_select_preempt(
                    state, pstate, ptab, const, b, dtype, spread_alg,
                    0, None)

            def fast(_):
                return f

            chosen, cscore, n_yield, _cnt, evict_row, freed = jax.lax.cond(
                f[3] >= limit, fast, full, operand=None)
        else:
            chosen, cscore, n_yield, _cnt, evict_row, freed = \
                _score_and_select_preempt(
                    state, pstate, ptab, const, b, dtype, spread_alg,
                    0, None)

        do = active & (chosen >= 0)
        safe = jnp.maximum(chosen, 0)
        add_f = do.astype(dtype)
        add_i = do.astype(jnp.int32)
        evict_row = evict_row & do

        # release evicted usage + ports, then charge the placement
        dyn_back = jnp.sum(
            jnp.where(evict_row, ptab.dyn_ports[safe], 0)).astype(jnp.int32)
        static_back = jnp.any(evict_row & ptab.static_rel[safe])
        new_state = state._replace(
            used_cpu=state.used_cpu.at[safe].add(
                add_f * ask_cpu - freed[0]),
            used_mem=state.used_mem.at[safe].add(
                add_f * ask_mem - freed[1]),
            used_disk=state.used_disk.at[safe].add(
                add_f * ask_disk - freed[2]),
            placed=state.placed.at[safe].add(add_i),
            placed_job=state.placed_job.at[safe].add(add_i),
            static_free=state.static_free.at[safe].set(
                (state.static_free[safe] | static_back)
                & ~(do & has_static)),
            dyn_avail=state.dyn_avail.at[safe].add(
                dyn_back - add_i * n_dyn),
        )
        new_state = _commit_tables(state, new_state, const, do, safe)

        grp_row = ptab.grp[safe]                      # (A,)
        grp_hot = ((jnp.arange(G, dtype=jnp.int32)[None, :]
                    == jnp.maximum(grp_row, 0)[:, None])
                   & (grp_row >= 0)[:, None] & evict_row[:, None])
        new_counts = (pstate.counts
                      + jnp.sum(grp_hot, axis=0)).astype(jnp.int32)
        new_pstate = PreemptState(
            evicted=pstate.evicted.at[safe].set(
                pstate.evicted[safe] | evict_row),
            counts=new_counts)
        chosen_out = jnp.where(do, chosen, -1)
        return (new_state, new_pstate), (chosen_out, cscore, n_yield,
                                         evict_row)

    ask_cores_xs = (batch.ask_cores if batch.ask_cores.shape[0]
                    else jnp.zeros_like(batch.count))
    (final_state, final_pstate), (chosen, scores, n_yielded, evict_rows) = \
        jax.lax.scan(
            step, (init, pinit),
            (batch.ask_cpu, batch.ask_mem, batch.ask_disk,
             batch.n_dyn_ports, batch.has_static, batch.limit, batch.count,
             batch.penalty_idx, batch.active, ask_cores_xs),
            unroll=_dense_unroll())
    return chosen, scores, n_yielded, evict_rows, final_state


solve_placements_preempt = functools.partial(
    jax.jit, static_argnames=("spread_alg", "dtype_name"))(
        _solve_placements_preempt_impl)


def solve_eval_batch_preempt(const, init, batch, ptab, pinit,
                             spread_alg: bool = False,
                             dtype_name: str = "float32"):
    """Batched-eval form of solve_placements_preempt (leading (E, ...)
    axis), mirroring solve_eval_batch."""
    inner = functools.partial(solve_placements_preempt,
                              spread_alg=spread_alg, dtype_name=dtype_name)
    return jax.vmap(inner)(const, init, batch, ptab, pinit)


def solve_eval_batch(const: NodeConst, init: NodeState, batch: PlacementBatch,
                     spread_alg: bool = False,
                     dtype_name: str = "float32"):
    """Solve E independent evaluations in one dispatch: every leaf carries a
    leading eval axis (E, ...). This is the TPU-native form of the
    reference's optimistic concurrency (SURVEY.md section 2.6: N scheduler
    workers scheduling concurrently against snapshots, serialized only at
    plan apply) -- evals don't see each other's placements; the plan
    applier resolves conflicts exactly as nomad/plan_apply.go does.

    The eval axis is the data-parallel axis for multi-chip sharding; the
    node axis shards as the model axis (see parallel/mesh.py).
    """
    from .cache import enable_compile_cache
    enable_compile_cache()
    import functools as _ft
    inner = _ft.partial(solve_placements, spread_alg=spread_alg,
                        dtype_name=dtype_name)
    return jax.vmap(inner)(const, init, batch)


# ---------------------------------------------------------------------------
# Fused transport: one host->device transfer per dispatch.
#
# A lane's NamedTuples flatten to ~30-45 small leaves; transferring each
# separately pays one host<->device round trip apiece, which over a
# tunneled TPU dominates the whole eval (measured: the compiled 2000-step
# scan runs in ~0.4ms while per-leaf transfers cost 100ms+). Here leaves
# are grouped by (dtype, shape), stacked into a handful of buffers, moved
# in ONE jax.device_put, and re-sliced INSIDE the jit (free -- XLA fuses
# the slices away). Outputs are stacked in-jit and fetched once.

# group-class -> transfer-ledger tree-group name (solver/xferobs.py):
# position i is the i-th tree handed to _fuse_trees
_FUSE_TREE_NAMES = ("const", "init", "batch", "ptab", "pinit")


def _fuse_trees(trees):
    """Flatten trees and group non-empty leaves by (tree-index, dtype,
    shape). Returns (stacked buffers, per-leaf meta, treedef, group
    keys). The tree-index marker (0 = the NodeConst tree; 1.. = the
    mutable init/batch/preempt trees) keeps fleet-constant leaves in
    their OWN stacked buffers even when a usage leaf shares dtype+shape
    (cpu_cap vs used_cpu): the device-resident const cache can then pin
    the const buffers across dispatches while the delta buffers ship
    fresh every time.  Keying by the full tree index (not just the
    const/delta class) additionally keeps init, batch and the
    preemption port tables in separate buffers, so the transfer ledger
    (solver/xferobs.py) can decompose every dispatch's bytes by tree
    group; same bytes either way, one stacked buffer more or less per
    shape bucket."""
    metas = []
    groups: dict = {}
    per_tree = [jax.tree_util.tree_flatten(t) for t in trees]
    treedef = jax.tree_util.tree_structure(tuple(trees))
    for ti, (leaves, _) in enumerate(per_tree):
        for leaf in leaves:
            arr = np.asarray(leaf)
            if arr.size == 0:
                metas.append(("zero", arr.shape, arr.dtype.str))
                continue
            key = (ti, arr.dtype.str, arr.shape)
            rows = groups.setdefault(key, [])
            metas.append(("buf", key, len(rows)))
            rows.append(arr)
    group_keys = tuple(groups.keys())
    stacked = [np.stack(groups[k]) for k in group_keys]
    return stacked, tuple(metas), treedef, group_keys


@_single_flight
@functools.lru_cache(maxsize=None)
def _make_fused_fn(metas, treedef, group_keys, spread_alg: bool,
                   dtype_name: str, preempt: bool, batched: bool):
    """Per-shape-bucket factory for the fused-transport program. The
    lru_cache IS the dispatch discipline: one jitted callable per
    bucket signature, constructed exactly once, so steady state holds
    exactly one trace per bucket (jitcheck's retrace gate; the old
    module dict kept the same keys but hid the `@jax.jit` behind a
    bare call site)."""
    gpos = {k: i for i, k in enumerate(group_keys)}

    def rebuild(buffers):
        leaves = []
        for m in metas:
            if m[0] == "zero":
                leaves.append(jnp.zeros(m[1], dtype=np.dtype(m[2])))
            else:
                leaves.append(buffers[gpos[m[1]]][m[2]])
        return jax.tree_util.tree_unflatten(treedef, leaves)

    if preempt:
        inner = functools.partial(_solve_placements_preempt_impl,
                                  spread_alg=spread_alg,
                                  dtype_name=dtype_name)
        if batched:
            inner = jax.vmap(inner)

        @jax.jit
        def fn(*buffers):
            const, init, batch, ptab, pinit = rebuild(buffers)
            chosen, scores, n_yielded, evict_rows, _ = inner(
                const, init, batch, ptab, pinit)
            out = jnp.stack([chosen.astype(scores.dtype), scores,
                             n_yielded.astype(scores.dtype)])
            return out, evict_rows
        return fn

    inner = functools.partial(_solve_placements_impl, spread_alg=spread_alg,
                              dtype_name=dtype_name)
    if batched:
        inner = jax.vmap(inner)

    @jax.jit
    def fn(*buffers):
        const, init, batch = rebuild(buffers)
        chosen, scores, n_yielded, _ = inner(const, init, batch)
        return jnp.stack([chosen.astype(scores.dtype), scores,
                          n_yielded.astype(scores.dtype)])
    return fn


def solve_lane_fused(const, init, batch, ptab=None, pinit=None, *,
                     spread_alg: bool, dtype_name: str,
                     batched: bool = False, wave: bool = False,
                     cache_version=None, delta_src=None):
    """Solve with minimal transfers: returns host-side numpy
    (chosen int64, scores, n_yielded int64[, evict_rows]). When ``batched``
    every leaf carries a leading eval axis and outputs do too. ``wave``
    routes through the wavefront path (caller must have checked
    eligibility): host-side O(N) precompute + compact-table device scan
    (solve_lane_wave). Stacking chosen/n_yielded through the score dtype
    is exact: node indexes and yield counts are < 2^24. ``cache_version``
    tags const-tree buffers in the device-resident cache with the
    packing snapshot's node_table_index (solver/constcache.py);
    ``delta_src`` is that snapshot's (store, index) pair for the
    ISSUE-20 version chain -- journal-covered generations ship only
    their diff and scatter it into the resident buffers on device."""
    from .cache import enable_compile_cache
    enable_compile_cache()
    if wave and ptab is None:
        return solve_lane_wave(const, init, batch, spread_alg=spread_alg,
                               dtype_name=dtype_name, batched=batched,
                               cache_version=cache_version,
                               delta_src=delta_src)
    if wave and ptab is not None:
        return solve_lane_wave_preempt(
            const, init, batch, ptab, pinit, spread_alg=spread_alg,
            dtype_name=dtype_name, batched=batched,
            cache_version=cache_version, delta_src=delta_src)
    trees = ((const, init, batch) if ptab is None
             else (const, init, batch, ptab, pinit))
    stacked, metas, treedef, group_keys = _fuse_trees(trees)
    fn = _make_fused_fn(metas, treedef, group_keys, spread_alg,
                        dtype_name, ptab is not None, batched)
    from . import xferobs
    from .constcache import device_put_cached
    # only const-tree buffers (tree index 0) are pinned: init/batch
    # deltas change every dispatch and would churn the LRU. Tags name
    # each stacked buffer's tree group for the transfer ledger; the
    # stacked buffers are _fuse_trees' fresh np.stack outputs, so the
    # version chain may retain them as frozen shadows without copying.
    buffers, _ = device_put_cached(
        stacked, version=cache_version,
        cacheable=[k[0] == 0 for k in group_keys],
        tags=[_FUSE_TREE_NAMES[k[0]] for k in group_keys],
        delta_src=delta_src)
    out = fn(*buffers)
    # the 3-way output axis is leading in both forms: (3, P) or (3, E, P)
    if ptab is not None:
        with jitcheck.sanctioned_fetch("fused_preempt"):
            # the ONE designed bulk fetch of the fused transport
            combined, evict_rows = jax.device_get(out)
        xferobs.note_fetch(
            xferobs.tree_nbytes((combined, evict_rows)), "fused_preempt")
        return (combined[0].astype(np.int64), combined[1],
                combined[2].astype(np.int64), np.asarray(evict_rows))
    with jitcheck.sanctioned_fetch("fused"):
        combined = jax.device_get(out)
    xferobs.note_fetch(xferobs.tree_nbytes(combined), "fused")
    return (combined[0].astype(np.int64), combined[1],
            combined[2].astype(np.int64))


# ---------------------------------------------------------------------------
# Wavefront kernel: O(B)-per-step selection for uniform-ask lanes.
#
# Every placement in a lane is the SAME TaskGroup ask (service.pack fills the
# (P,) ask arrays with one value), so a node's whole score/feasibility
# trajectory is a closed form of how many copies it already took:
#   new_cpu(j) = used0 + (j+1)*ask          (bit-exact vs the scan's
#                                            accumulation for integer-valued
#                                            floats -- cpu/mem/disk are ints)
#   capacity c = max m with used0 + m*ask <= cap (per resource, ports,
#                distinct_hosts), computed ONCE per node.
# The selection window (select.go LimitIterator + MaxScoreIterator) only
# ever examines the first limit+MAX_SKIP FIT nodes in shuffled order, so the
# scan carries just a B-slot buffer of those front nodes (position, copies
# taken j, capacity c, score inputs) instead of rescoring all N nodes:
# per-step work drops from O(N) to O(B), the chosen slot's j increments, and
# a saturated slot (j == c) is shifted out and refilled from a precomputed
# fit-order list. Steps are ~100x cheaper than the dense pass; parity with
# the host oracle is enforced by the same gating suites (test_solver_parity,
# test_parity_scale) because eligible lanes route here in production.
#
# Eligibility (checked host-side, service.PackedLane.wavefront_ok): no
# distinct_property / devices / cores / preemption, uniform asks over the
# active prefix, and limit + MAX_SKIP within a buffer variant (WAVE_B for
# log2 windows, WAVE_B_WIDE for spread/affinity windows). Spreads ride the
# compact kernel's carry as (S, V) counts; reschedule penalties ride the
# scan xs. The in-kernel variant below (_solve_wavefront_impl) stays
# S == 0-only and is the test reference; production routes through
# solve_lane_wave (host precompute + compact (C, 8+S) table).

WAVE_B = 32
# wide-window variant for spread/affinity lanes (the host stack forces
# limit = max(count, 100) when either is present, stack.go:176-185)
WAVE_B_WIDE = 128


class _WaveSpread(NamedTuple):
    """Spread tables the compact wavefront carries: per-spread value
    counts (the ONLY cross-placement coupling spreads add) plus the
    static scoring tables."""

    counts: jnp.ndarray       # (S, V) int32
    desired: jnp.ndarray      # (S, V)
    has_targets: jnp.ndarray  # (S,) bool
    weights: jnp.ndarray      # (S,)
    sum_weights: jnp.ndarray  # ()


# Placement-axis padding for wavefront dispatch shapes: pow2 with a floor,
# so production lanes of many sizes land on FEW compiled variants (inert
# padded steps cost ~a microsecond each; an extra XLA compile costs
# seconds).
WAVE_P_BUCKETS_MIN = 32


def _wave_p_bucket(p: int) -> int:
    b = WAVE_P_BUCKETS_MIN
    while b < p:
        b *= 2
    return b


def _wave_unroll() -> int:
    """Scan unroll: 8 on TPU (amortizes per-step loop overhead), 1
    elsewhere (unrolling multiplies the compiled body; CPU/virtual-mesh
    runs are compile-time-bound, not step-overhead-bound).
    NOMAD_TPU_WAVE_UNROLL overrides (perf experiments)."""
    import os

    import jax as _jax
    ov = os.environ.get("NOMAD_TPU_WAVE_UNROLL")
    if ov:
        return max(1, int(ov))
    return 8 if _jax.default_backend() == "tpu" else 1


def _wave_gather_dynslice() -> bool:
    """Refill-row gather strategy: one-hot masked reduce (default; safe
    under vmap on TPU) vs dynamic_slice (NOMAD_TPU_WAVE_GATHER=dynslice;
    perf experiments -- vmapped scalar-index slices lower to gathers,
    which are fast or slow depending on backend/shape)."""
    import os
    return os.environ.get("NOMAD_TPU_WAVE_GATHER") == "dynslice"


def _wave_refill_shift(compact, cursor, w, j2, slot, gate, arangeB,
                       arangeC):
    """Shared winner shift/refill for the compact and run-block wave
    kernels: shift slots above ``w`` left, append the ``cursor`` row of
    ``compact``, advance the cursor -- all gated on ``gate``. The two
    kernels' bit-parity contract depends on this being ONE
    implementation (tests/test_wave_block.py)."""
    C = compact.shape[0]
    B = arangeB.shape[0]
    if _wave_gather_dynslice():
        entry_row = jax.lax.dynamic_slice_in_dim(
            compact, jnp.clip(cursor, 0, C - 1), 1, axis=0)[0]
    else:
        oh_c = arangeC == jnp.clip(cursor, 0, C - 1)
        entry_row = jnp.sum(jnp.where(oh_c[:, None], compact, 0.0),
                            axis=0)
    take_next = arangeB >= w
    is_last = arangeB == B - 1
    j_sh = jnp.where(is_last, 0,
                     jnp.where(take_next, jnp.roll(j2, -1), j2))
    slot_sh = jnp.where(
        is_last[:, None], entry_row[None, :],
        jnp.where(take_next[:, None], jnp.roll(slot, -1, axis=0), slot))
    j3 = jnp.where(gate, j_sh, j2)
    slot2 = jnp.where(gate, slot_sh, slot)
    cursor2 = cursor + gate.astype(jnp.int32)
    return j3, slot2, cursor2


def _slotmat_cols(c, init: NodeState, const: NodeConst, aff_node, dtype):
    """(N, 7) per-node row: [c, used_cpu0, used_mem0, cpu_cap, mem_cap,
    placed0, affinity]. c/placed are < 2^24 so the float cast is exact."""
    return jnp.stack([
        c.astype(dtype), init.used_cpu.astype(dtype),
        init.used_mem.astype(dtype), const.cpu_cap.astype(dtype),
        const.mem_cap.astype(dtype), init.placed.astype(dtype),
        aff_node.astype(dtype)], axis=1)


def _solve_wavefront_impl(const: NodeConst, init: NodeState,
                          batch: PlacementBatch, spread_alg: bool = False,
                          dtype_name: str = "float32"):
    """Uniform-ask lane solve; returns (chosen (P,) i32, scores (P,),
    n_yielded (P,) i32), identical to _solve_placements_impl's first three
    outputs on eligible lanes."""
    dtype = jnp.dtype(dtype_name)
    N = const.cpu_cap.shape[0]
    P = batch.ask_cpu.shape[0]
    B = WAVE_B

    # Lane scalars from row 0 (uniform over the active prefix; padding rows
    # are inert and their outputs are sliced off by the caller).
    ask_cpu = batch.ask_cpu[0]
    ask_mem = batch.ask_mem[0]
    ask_disk = batch.ask_disk[0]
    n_dyn = batch.n_dyn_ports[0]
    has_static = batch.has_static[0]
    L = batch.limit[0]
    count = batch.count[0]
    n_active = jnp.sum(batch.active.astype(jnp.int32))

    BIG_I = jnp.int32(2 ** 30)

    def cap_dim(used0, cap, ask):
        # c = max m >= 0 with used0 + m*ask <= cap, using the SAME float
        # predicate as scoring (float division then +-2 correction).
        q = jnp.floor((cap - used0) / jnp.maximum(ask, 1e-9)).astype(
            jnp.int32)

        def fits(m):
            return used0 + m.astype(dtype) * ask <= cap

        q = jnp.where(fits(q), q, q - 1)
        q = jnp.where(fits(q), q, q - 1)
        q = jnp.maximum(q, 0)
        q = jnp.where(fits(q + 1), q + 1, q)
        q = jnp.where(fits(q + 1), q + 1, q)
        q = jnp.where(fits(q), q, 0)       # used0 alone already over cap
        return jnp.where(ask > 0, q, BIG_I)

    c = jnp.minimum(cap_dim(init.used_cpu, const.cpu_cap, ask_cpu),
                    cap_dim(init.used_mem, const.mem_cap, ask_mem))
    c = jnp.minimum(c, cap_dim(init.used_disk, const.disk_cap, ask_disk))
    c = jnp.minimum(c, jnp.where(n_dyn > 0,
                                 init.dyn_avail // jnp.maximum(n_dyn, 1),
                                 BIG_I))
    c = jnp.where(has_static,
                  jnp.minimum(c, jnp.where(init.static_free, 1, 0)), c)
    distinct0 = jnp.where(const.distinct_job_level, init.placed_job,
                          init.placed)
    c = jnp.where(const.distinct_hosts,
                  jnp.minimum(c, jnp.where(distinct0 > 0, 0, 1)), c)
    c = jnp.where(const.feasible, c, 0)
    c = jnp.clip(c, 0, P)

    aff_node = jnp.where(const.has_affinity, const.affinity,
                         jnp.zeros_like(const.affinity))

    # fit_order[k] = shuffled position of the k-th fit node; N = sentinel.
    # Length covers both the node count and the compact prefix P+B (P can
    # exceed N on tiny fleets).
    L_fo = max(N, P) + B
    tak = c > 0
    kpos = jnp.cumsum(tak.astype(jnp.int32)) - 1
    scatter_idx = jnp.where(tak, kpos, L_fo)         # OOB -> dropped
    fit_order = jnp.full(L_fo, N, dtype=jnp.int32).at[scatter_idx].set(
        jnp.arange(N, dtype=jnp.int32), mode="drop")

    nodemat = _slotmat_cols(c, init, const, aff_node, dtype)

    # Only the first P+B fit nodes can ever enter the buffer (one pull per
    # saturation, at most one saturation per placement), so gather their
    # rows ONCE into a compact table: per-step refills then index (P+B, 7)
    # instead of the full (N, 7) -- the big-table gather inside the scan is
    # what dominated at larger fused widths.
    C = P + B
    compact_pos = fit_order[:C]                        # (C,) node positions
    safe_cp = jnp.clip(compact_pos, 0, N - 1)
    compact = nodemat[safe_cp]                         # (C, 7) one gather
    compact = compact.at[:, 0].set(
        jnp.where(compact_pos < N, compact[:, 0], 0.0))

    pos0 = compact_pos[:B]
    slot0 = compact[:B]
    j0 = jnp.zeros(B, dtype=jnp.int32)
    cursor0 = jnp.int32(B)

    arangeB = jnp.arange(B, dtype=jnp.int32)
    arangeC = jnp.arange(C, dtype=jnp.int32)
    neg_inf = jnp.array(-jnp.inf, dtype=dtype)
    big = jnp.iinfo(jnp.int32).max

    def step(carry, xs):
        i, pen_i = xs
        pos, j, slot, cursor = carry
        cs = slot[:, 0]
        fit = (pos < N) & (j.astype(dtype) < cs)
        jp1 = (j + 1).astype(dtype)
        new_cpu = slot[:, 1] + jp1 * ask_cpu
        new_mem = slot[:, 2] + jp1 * ask_mem
        free_cpu = 1.0 - new_cpu / jnp.maximum(slot[:, 3], 1e-9)
        free_mem = 1.0 - new_mem / jnp.maximum(slot[:, 4], 1e-9)
        binpack = _binpack_score(free_cpu, free_mem, spread_alg)
        coll = slot[:, 5] + j.astype(dtype)
        anti = jnp.where(
            coll > 0, -(coll + 1.0) / jnp.maximum(count.astype(dtype), 1.0),
            0.0)
        # per-placement reschedule penalty: the previous alloc's node
        # scores -1 for THIS placement only (rank.go penalty iterator)
        is_pen = (pen_i >= 0) & (pos == pen_i)
        resched = jnp.where(is_pen, -1.0, 0.0)
        affs = slot[:, 6]
        aff_present = affs != 0.0
        nscores = (1.0 + (coll > 0).astype(dtype)
                   + is_pen.astype(dtype) + aff_present.astype(dtype))
        other = (anti + resched) + affs
        final = (binpack + other) / nscores

        low = fit & (final <= SKIP_THRESHOLD)
        skip_rank = jnp.cumsum(low.astype(jnp.int32))
        skipped = low & (skip_rank <= MAX_SKIP)
        counted = fit & ~skipped
        cpos = jnp.cumsum(counted.astype(jnp.int32))
        total_counted = cpos[-1]
        window = counted & (cpos <= L)
        deficit = jnp.maximum(0, L - jnp.minimum(total_counted, L))
        srank = jnp.cumsum(skipped.astype(jnp.int32))
        fallback = skipped & (srank <= deficit)
        yielded = window | fallback
        order = jnp.where(window, cpos, L + srank)
        eff = jnp.where(yielded, final, neg_inf)
        best = jnp.max(eff)
        is_best = yielded & (eff == best)
        border = jnp.min(jnp.where(is_best, order, big))
        w = jnp.argmax(is_best & (order == border))
        any_yield = jnp.any(yielded)
        do = (i < n_active) & any_yield
        # NOTE: the step body is deliberately gather/scatter-free beyond
        # the one-hot selects below -- per-lane dynamic indexing inside the
        # scan turns into batched gather/scatter under vmap, which costs
        # ~usec per op on TPU and dominated the fused-eval dispatch.
        oh_w = arangeB == w
        chosen = jnp.where(
            do, jnp.sum(jnp.where(oh_w, pos, 0), dtype=jnp.int32), -1)
        score_out = jnp.where(any_yield, best, neg_inf)
        ny = jnp.sum(yielded.astype(jnp.int32))

        # commit: the chosen slot takes one more copy; shift it out + refill
        # from the fit order once saturated (at most one per step)
        do_i = do.astype(jnp.int32)
        j2 = j + oh_w.astype(jnp.int32) * do_i
        jw = jnp.sum(jnp.where(oh_w, j2, 0), dtype=jnp.int32)
        csw = jnp.sum(jnp.where(oh_w, cs, 0.0))
        sat = do & (jw.astype(dtype) >= csw)
        ccur = jnp.clip(cursor, 0, C - 1)
        oh_c = arangeC == ccur
        entry = jnp.sum(jnp.where(oh_c, compact_pos, 0), dtype=jnp.int32)
        entry_row = jnp.sum(jnp.where(oh_c[:, None], compact, 0.0), axis=0)
        # shift-left at w (static roll + masks), refill the last slot
        take_next = arangeB >= w
        is_last = arangeB == B - 1
        pos_sh = jnp.where(is_last, entry,
                           jnp.where(take_next, jnp.roll(pos, -1), pos))
        j_sh = jnp.where(is_last, 0,
                         jnp.where(take_next, jnp.roll(j2, -1), j2))
        slot_sh = jnp.where(
            is_last[:, None], entry_row[None, :],
            jnp.where(take_next[:, None], jnp.roll(slot, -1, axis=0), slot))
        pos2 = jnp.where(sat, pos_sh, pos)
        j3 = jnp.where(sat, j_sh, j2)
        slot2 = jnp.where(sat, slot_sh, slot)
        cursor2 = cursor + sat.astype(jnp.int32)
        return (pos2, j3, slot2, cursor2), (chosen, score_out, ny)

    _, (chosen, scores, n_yielded) = jax.lax.scan(
        step, (pos0, j0, slot0, cursor0),
        (jnp.arange(P, dtype=jnp.int32),
         batch.penalty_idx.astype(jnp.int32)), unroll=_wave_unroll())
    return chosen.astype(jnp.int32), scores, n_yielded


solve_wavefront = functools.partial(
    jax.jit, static_argnames=("spread_alg", "dtype_name"))(
        _solve_wavefront_impl)


def _solve_system_impl(const: NodeConst, init: NodeState,
                       batch: PlacementBatch, spread_alg: bool = False,
                       dtype_name: str = "float32"):
    """System-job dense solve: one INDEPENDENT fit+score per node, all at
    once (reference: scheduler_system.go runs one Stack.Select per node
    with that node as the only candidate). SystemStack has no limit
    window, no distinct-hosts iterator and no affinity/spread/
    anti-affinity scoring (stack.go:201 SystemStack chain), so the score
    is the normalized binpack fitness alone. Returns (fit (N,) bool,
    score (N,)) in shuffled order."""
    dtype = jnp.dtype(dtype_name)
    ask_cpu = batch.ask_cpu[0]
    ask_mem = batch.ask_mem[0]
    ask_disk = batch.ask_disk[0]
    n_dyn = batch.n_dyn_ports[0]
    has_static = batch.has_static[0]
    has_cores = const.mhz_per_core.shape[0] > 0
    if has_cores:
        ask_cores = batch.ask_cores[0]
        eff_cpu = ask_cpu + ask_cores.astype(dtype) * const.mhz_per_core
    else:
        eff_cpu = ask_cpu
    new_cpu = init.used_cpu + eff_cpu
    new_mem = init.used_mem + ask_mem
    new_disk = init.used_disk + ask_disk
    feas = (const.feasible
            & (init.dyn_avail >= n_dyn)
            & (init.static_free | ~has_static))
    if has_cores:
        feas &= init.cores_free >= ask_cores
    fit = (feas
           & (new_cpu <= const.cpu_cap)
           & (new_mem <= const.mem_cap)
           & (new_disk <= const.disk_cap))
    free_cpu = 1.0 - new_cpu / jnp.maximum(const.cpu_cap, 1e-9)
    free_mem = 1.0 - new_mem / jnp.maximum(const.mem_cap, 1e-9)
    score = _binpack_score(free_cpu, free_mem, spread_alg)
    return fit, score


solve_system = functools.partial(
    jax.jit, static_argnames=("spread_alg", "dtype_name"))(
        _solve_system_impl)


# -- compact wavefront: host-side O(N) precompute, device-side scan --------
#
# The wavefront scan only ever reads the first C = P + B fit-order rows, so
# the O(N) precompute (capacity fold + fit-order compress + row gather) runs
# on the HOST in numpy and only the compact (C, 8) table crosses the
# host->device boundary: ~65KB/lane instead of ~0.5MB of N-sized tables.
# Over a tunneled TPU the transfer dominated the whole dispatch; on local
# hardware it still cuts per-dispatch HBM traffic E-fold in fused batches.
# The float predicates here MUST mirror _solve_wavefront_impl / the dense
# kernel op-for-op (IEEE ops agree between numpy and XLA) so placements
# stay bit-identical.

def wavefront_buffer_size(limit: int) -> Optional[int]:
    """Static slot-buffer size for a lane's scan window: small for log2
    windows, wide for the limit>=100 spread/affinity windows; None when
    the window outgrows every variant (dense kernel territory)."""
    if limit + MAX_SKIP <= WAVE_B:
        return WAVE_B
    if limit + MAX_SKIP <= WAVE_B_WIDE:
        return WAVE_B_WIDE
    return None


# shared with service._wave_devices_ok's eligibility bound: a lane passes
# the wave gate ONLY if the capacity replay provably terminates within
# this many steps, so wavefront_compact_host can assert the replay
# succeeded rather than silently skipping the device clamp
WAVE_DEVICE_CAP_STEPS = 1024


def _wave_device_capacity(const, init,
                          cap_steps: int = WAVE_DEVICE_CAP_STEPS
                          ) -> Optional[np.ndarray]:
    """Per-node placement capacity in the DEVICE dimension for a uniform
    lane: numpy replay of the dense kernel's per-step commit (feasible if
    every request has a group with free >= count; the first-max-affinity
    eligible group is drained, _commit_carry_tables). Capacity is the
    number of placements until device-infeasible. Returns None when the
    simulation can't bound (a request with count <= 0 would never drain).

    Eligibility for the wave path additionally requires
    dev_sum_weight == 0 (no device affinities): with zero weight the
    dense kernel's device score component vanishes, so capacity is the
    ONLY device effect and the wave scoring stays bit-identical.
    """
    R = int(np.asarray(const.dev_aff).shape[0])
    if R == 0:
        return None
    dev_cnt = np.asarray(const.dev_count, dtype=np.int64)
    if (dev_cnt <= 0).any():
        return None
    free = np.asarray(init.dev_free, dtype=np.int64).copy()  # (R, Gd, N)
    aff = np.asarray(const.dev_aff, dtype=np.float64)
    N = free.shape[2]
    c_dev = np.zeros(N, dtype=np.int64)
    alive = np.ones(N, dtype=bool)
    rr = np.arange(R)
    nn = np.arange(N)
    for _ in range(cap_steps):
        ok_g = free >= dev_cnt[:, None, None]            # (R, Gd, N)
        feas = ok_g.any(axis=1).all(axis=0) & alive      # (N,)
        if not feas.any():
            break
        # first-max affinity among eligible groups, exactly the dense
        # argmax (ties -> lowest group index)
        aff_m = np.where(ok_g, aff, -np.inf)
        g_star = aff_m.argmax(axis=1)                    # (R, N)
        dec = np.zeros_like(free)
        dec[rr[:, None], g_star, nn[None, :]] = dev_cnt[:, None]
        free -= np.where(feas[None, None, :], dec, 0)
        c_dev += feas
        alive = feas
    else:
        return None             # capacity unbounded within cap_steps
    return c_dev


def wavefront_compact_host(const, init, batch, dtype_name: str,
                           p_pad: Optional[int] = None,
                           B: int = WAVE_B):
    """Numpy precompute for ONE lane: returns (compact (C, 8+S),
    scal_f (3,), scal_i (2,), pen (P,), spread tables). Columns: c,
    used_cpu, used_mem, cpu_cap, mem_cap, placed, affinity,
    pos(sentinel -1), then one spread value-index column per spread.
    ``p_pad`` grows the output axis (C = p_pad + B) so many lane sizes
    share one compiled variant; the padded steps are inert (beyond
    n_active) and callers slice outputs."""
    dt = np.dtype(dtype_name)
    P = int(np.asarray(batch.ask_cpu).shape[0])
    P_out = max(P, p_pad or 0)
    N = int(np.asarray(const.cpu_cap).shape[0])
    ask_cpu = np.asarray(batch.ask_cpu, dtype=dt)[0]
    ask_mem = np.asarray(batch.ask_mem, dtype=dt)[0]
    ask_disk = np.asarray(batch.ask_disk, dtype=dt)[0]
    n_dyn = int(np.asarray(batch.n_dyn_ports)[0])
    has_static = bool(np.asarray(batch.has_static)[0])
    count = np.asarray(batch.count, dtype=dt)[0]
    L = int(np.asarray(batch.limit)[0])
    n_active = int(np.asarray(batch.active).sum())

    BIG = np.int64(2 ** 30)
    cpu_cap = np.asarray(const.cpu_cap, dtype=dt)
    mem_cap = np.asarray(const.mem_cap, dtype=dt)
    disk_cap = np.asarray(const.disk_cap, dtype=dt)
    used_cpu = np.asarray(init.used_cpu, dtype=dt)
    used_mem = np.asarray(init.used_mem, dtype=dt)
    used_disk = np.asarray(init.used_disk, dtype=dt)

    def cap_dim(used0, cap, ask):
        with np.errstate(divide="ignore", invalid="ignore",
                         over="ignore"):
            q = np.floor((cap - used0) / np.maximum(ask, dt.type(1e-9)))
        q = np.where(np.isfinite(q), q, 0).astype(np.int64)

        def fits(m):
            return used0 + m.astype(dt) * ask <= cap

        q = np.where(fits(q), q, q - 1)
        q = np.where(fits(q), q, q - 1)
        q = np.maximum(q, 0)
        q = np.where(fits(q + 1), q + 1, q)
        q = np.where(fits(q + 1), q + 1, q)
        q = np.where(fits(q), q, 0)
        return np.where(ask > 0, q, BIG)

    c = np.minimum(cap_dim(used_cpu, cpu_cap, ask_cpu),
                   cap_dim(used_mem, mem_cap, ask_mem))
    c = np.minimum(c, cap_dim(used_disk, disk_cap, ask_disk))
    if n_dyn > 0:
        c = np.minimum(c, np.asarray(init.dyn_avail, dtype=np.int64)
                       // n_dyn)
    if has_static:
        c = np.minimum(c, np.where(np.asarray(init.static_free), 1, 0))
    if bool(np.asarray(const.distinct_hosts)):
        distinct0 = (np.asarray(init.placed_job)
                     if bool(np.asarray(const.distinct_job_level))
                     else np.asarray(init.placed))
        c = np.minimum(c, np.where(distinct0 > 0, 0, 1))
    if np.asarray(const.dev_aff).shape[0]:
        c_dev = _wave_device_capacity(const, init)
        # wavefront_ok admits device lanes only when the replay bound
        # holds, so a None here is an eligibility bug, not a fallback
        assert c_dev is not None, "unbounded device capacity replay"
        # uniform device asks fold into the closed-form capacity; the
        # score is unaffected (wavefront_ok gates on zero device
        # affinity weight, where the dense device score component is 0)
        c = np.minimum(c, c_dev)
    c = np.where(np.asarray(const.feasible), c, 0)
    c = np.clip(c, 0, P)

    aff = (np.asarray(const.affinity, dtype=dt)
           if bool(np.asarray(const.has_affinity))
           else np.zeros(N, dtype=dt))

    S = int(np.asarray(const.spread_vidx).shape[0])
    fit_pos = np.nonzero(c > 0)[0][:P_out + B]
    C = P_out + B
    compact = np.zeros((C, 8 + S), dtype=dt)
    compact[:, 7] = -1.0
    if S:
        compact[:, 8:] = -1.0           # missing spread attr sentinel
    k = fit_pos.shape[0]
    compact[:k, 0] = c[fit_pos]
    compact[:k, 1] = used_cpu[fit_pos]
    compact[:k, 2] = used_mem[fit_pos]
    compact[:k, 3] = cpu_cap[fit_pos]
    compact[:k, 4] = mem_cap[fit_pos]
    compact[:k, 5] = np.asarray(init.placed)[fit_pos].astype(dt)
    compact[:k, 6] = aff[fit_pos]
    compact[:k, 7] = fit_pos.astype(dt)
    if S:
        compact[:k, 8:] = np.asarray(
            const.spread_vidx)[:, fit_pos].T.astype(dt)
    scal_f = np.array([ask_cpu, ask_mem, count], dtype=dt)
    scal_i = np.array([L, n_active], dtype=np.int32)
    pen = np.full(P_out, -1, dtype=np.int32)
    pen[:P] = np.asarray(batch.penalty_idx, dtype=np.int32)
    sp = _WaveSpread(
        counts=np.asarray(init.spread_counts, dtype=np.int32),
        desired=np.asarray(const.spread_desired, dtype=dt),
        has_targets=np.asarray(const.spread_has_targets, dtype=bool),
        weights=np.asarray(const.spread_weights, dtype=dt),
        sum_weights=np.asarray(const.spread_sum_weights, dtype=dt))
    return compact, scal_f, scal_i, pen, sp


def _solve_wave_compact_impl(compact, scal_f, scal_i, pen, sp=None,
                             spread_alg: bool = False,
                             dtype_name: str = "float32",
                             B: int = WAVE_B):
    """Device-side scan over a host-precomputed compact table; identical
    outputs to the dense kernel on eligible lanes (P = C - B). ``sp``
    carries spread tables when the lane has spreads (the wide-window
    variant; spreads couple placements only through per-value counts,
    which ride the carry)."""
    dtype = jnp.dtype(dtype_name)
    C = compact.shape[0]
    P = C - B
    S = sp.counts.shape[0] if sp is not None else 0
    ask_cpu = scal_f[0]
    ask_mem = scal_f[1]
    count = scal_f[2]
    L = scal_i[0]
    n_active = scal_i[1]

    slot0 = compact[:B]
    j0 = jnp.zeros(B, dtype=jnp.int32)
    cursor0 = jnp.int32(B)
    arangeB = jnp.arange(B, dtype=jnp.int32)
    arangeC = jnp.arange(C, dtype=jnp.int32)
    neg_inf = jnp.array(-jnp.inf, dtype=dtype)
    big = jnp.iinfo(jnp.int32).max
    if S:
        V = sp.counts.shape[1]
        arangeV = jnp.arange(V, dtype=jnp.int32)
        weight_fracs = sp.weights / jnp.maximum(sp.sum_weights, 1e-9)

    def _spread_boosts(slot, counts):
        """(S, B) per-slot spread boost, mirroring _spread_score op for
        op; slot value indexes live in columns 8.. as exact int floats.
        Gathers go through one-hot matmuls (V is small; batched gathers
        under vmap hit TPU slow paths)."""
        def one_spread(vidx_f, desired, has_targets, weight_frac, cnts):
            missing = vidx_f < 0
            safe = jnp.maximum(vidx_f, 0.0).astype(jnp.int32)
            oh_v = arangeV[None, :] == safe[:, None]          # (B, V)
            current_i = jnp.sum(jnp.where(oh_v, cnts[None, :], 0),
                                axis=1)
            used = current_i + 1
            des = jnp.sum(jnp.where(oh_v, desired[None, :], 0.0), axis=1)
            no_target = des < 0.0
            boost_t = jnp.where(
                no_target, -1.0,
                jnp.where(des == 0.0, -1.0,
                          (des - used.astype(dtype))
                          / jnp.maximum(des, 1e-9) * weight_frac))
            present = cnts > 0
            any_present = jnp.any(present)
            big_i = jnp.iinfo(jnp.int32).max
            min_c = jnp.min(jnp.where(present, cnts, big_i))
            max_c = jnp.max(jnp.where(present, cnts, 0))
            min_f = min_c.astype(dtype)
            max_f = max_c.astype(dtype)
            cur_f = current_i.astype(dtype)
            even = jnp.where(
                current_i != min_c,
                jnp.where(min_c == 0, -1.0,
                          (min_f - cur_f) / jnp.maximum(min_f, 1e-9)),
                jnp.where(min_c == max_c, -1.0,
                          (max_f - min_f) / jnp.maximum(min_f, 1e-9)))
            boost_e = jnp.where(any_present, even, 0.0)
            per_node = jnp.where(has_targets, boost_t, boost_e)
            return jnp.where(missing, -1.0, per_node).astype(dtype)

        return jax.vmap(one_spread)(
            jnp.moveaxis(slot[:, 8:], 1, 0), sp.desired, sp.has_targets,
            weight_fracs, counts)

    def step(carry, xs):
        i, pen_i = xs
        if S:
            j, slot, cursor, counts = carry
        else:
            j, slot, cursor = carry
        cs = slot[:, 0]
        fit = j.astype(dtype) < cs            # sentinel rows: c = 0
        jp1 = (j + 1).astype(dtype)
        new_cpu = slot[:, 1] + jp1 * ask_cpu
        new_mem = slot[:, 2] + jp1 * ask_mem
        free_cpu = 1.0 - new_cpu / jnp.maximum(slot[:, 3], 1e-9)
        free_mem = 1.0 - new_mem / jnp.maximum(slot[:, 4], 1e-9)
        binpack = _binpack_score(free_cpu, free_mem, spread_alg)
        coll = slot[:, 5] + j.astype(dtype)
        anti = jnp.where(
            coll > 0, -(coll + 1.0) / jnp.maximum(count, 1.0), 0.0)
        # per-placement reschedule penalty via the pos column (exact int
        # floats), matching the dense kernel's is_penalty term
        is_pen = (pen_i >= 0) & (slot[:, 7] == pen_i.astype(dtype))
        resched = jnp.where(is_pen, -1.0, 0.0)
        affs = slot[:, 6]
        if S:
            spread_total = jnp.sum(_spread_boosts(slot, counts), axis=0)
        else:
            spread_total = jnp.zeros(B, dtype=dtype)
        spread_present = spread_total != 0.0
        nscores = (1.0 + (coll > 0).astype(dtype)
                   + is_pen.astype(dtype) + (affs != 0.0).astype(dtype)
                   + spread_present.astype(dtype))
        final = (binpack
                 + (((anti + resched) + affs) + spread_total)) / nscores

        low = fit & (final <= SKIP_THRESHOLD)
        skip_rank = jnp.cumsum(low.astype(jnp.int32))
        skipped = low & (skip_rank <= MAX_SKIP)
        counted = fit & ~skipped
        cpos = jnp.cumsum(counted.astype(jnp.int32))
        total_counted = cpos[-1]
        window = counted & (cpos <= L)
        deficit = jnp.maximum(0, L - jnp.minimum(total_counted, L))
        srank = jnp.cumsum(skipped.astype(jnp.int32))
        fallback = skipped & (srank <= deficit)
        yielded = window | fallback
        order = jnp.where(window, cpos, L + srank)
        eff = jnp.where(yielded, final, neg_inf)
        best = jnp.max(eff)
        is_best = yielded & (eff == best)
        border = jnp.min(jnp.where(is_best, order, big))
        w = jnp.argmax(is_best & (order == border))
        any_yield = jnp.any(yielded)
        do = (i < n_active) & any_yield
        oh_w = arangeB == w
        chosen = jnp.where(
            do,
            jnp.sum(jnp.where(oh_w, slot[:, 7], 0.0)).astype(jnp.int32),
            -1)
        score_out = jnp.where(any_yield, best, neg_inf)
        ny = jnp.sum(yielded.astype(jnp.int32))

        do_i = do.astype(jnp.int32)
        j2 = j + oh_w.astype(jnp.int32) * do_i
        jw = jnp.sum(jnp.where(oh_w, j2, 0), dtype=jnp.int32)
        csw = jnp.sum(jnp.where(oh_w, cs, 0.0))
        sat = do & (jw.astype(dtype) >= csw)
        j3, slot2, cursor2 = _wave_refill_shift(
            compact, cursor, w, j2, slot, sat, arangeB, arangeC)
        if S:
            # winner's value index per spread -> bump its count
            vw = jnp.sum(jnp.where(oh_w[:, None], slot[:, 8:], 0.0),
                         axis=0)                              # (S,)
            safe_vw = jnp.maximum(vw, 0.0).astype(jnp.int32)
            upd = ((arangeV[None, :] == safe_vw[:, None])
                   & (vw >= 0)[:, None] & do)
            counts2 = counts + upd.astype(jnp.int32)
            return ((j3, slot2, cursor2, counts2),
                    (chosen, score_out, ny))
        return (j3, slot2, cursor2), (chosen, score_out, ny)

    carry0 = ((j0, slot0, cursor0, sp.counts.astype(jnp.int32)) if S
              else (j0, slot0, cursor0))
    _, (chosen, scores, n_yielded) = jax.lax.scan(
        step, carry0,
        (jnp.arange(P, dtype=jnp.int32), pen.astype(jnp.int32)),
        unroll=_wave_unroll())
    return chosen, scores, n_yielded


# ---------------------------------------------------------------------------
# Run-block wavefront: the compact kernel's semantics in ~P/7 chain
# steps instead of P.
#
# On-chip profiling (scripts/wave_step_bisect.py) showed the per-step
# cost of the compact scan is dependency-chain LATENCY -- a handful of
# sequentially dependent vector ops -- not arithmetic width; the chip
# pays it P times because the scan commits one placement per step. The
# shortcut is the FROZEN-OPPONENT structure of the greedy select
# (rank.go:205 BinPackIterator + select.go MaxScoreIterator): scores
# couple placements only through the winner's own per-node count j, so
# while one slot keeps winning, every other slot's head score is
# frozen. One chain step can therefore commit a winner's whole RUN:
# pick the argmax head (first-seen-in-order tie rule), then compute in
# closed form how many consecutive picks q it takes before
#   - its stream value loses to the frozen runner-up head (strictly
#     below, or tied with a runner-up of earlier window order),
#   - it saturates its closed-form capacity c (committed, then the
#     classic shift/refill runs and the block ends -- refills change
#     window composition),
#   - its value crosses the skip threshold in either direction (the
#     low/skip sets, select.go maxSkip, are recomputed at the next
#     block start), or
#   - the eval's n_active placements are exhausted,
# and emit all q picks (scores are the winner's precomputed stream
# values) in one dynamic-update-slice. BestFit streams mostly RISE with
# usage (fuller nodes score higher), so winners run until saturation
# and runs are long: the headline lane shape (10K nodes, 2000
# placements) has 272 winner runs averaging 7.4 picks
# (scripts/wave_event_stats.py). No assumption on stream shape is
# needed -- a run ends exactly when the per-step argmax would change.
#
# Equivalence argument (induction on committed picks): at a block start
# the head state (fit/low/skip/window/fallback/order/deficit) is
# recomputed exactly as the per-placement kernel's step does, so the
# argmax-with-tie-rule winner is the classic step's winner. While the
# winner runs, opponents' heads and every selection set are unchanged
# (fit changes only at the winner's saturation, low/skip sets only at
# threshold crossings -- both end the block), so the q-th pick of the
# run faces the same frozen comparison the classic kernel would
# compute, and the run-length conditions stop precisely at the first
# pick where the classic winner would differ. Outputs are
# bit-identical: emitted scores are the same elementwise expressions
# (broadcast over (B, K) instead of (B,)), and n_yielded is frozen
# between events by the same argument.
#
# Eligibility (enforced by solve_lane_wave): no spread tables (S == 0;
# spread boosts couple scores across slots through shared value
# counts) and no active reschedule penalties (penalties couple the
# score to the absolute placement index).

WAVE_K = 32            # run-block width: max picks committed per step
WAVE_INNER = 64        # run decisions per outer buffer-commit round


def _wave_block_shape() -> tuple:
    """(K, INNER) defaults by backend: measured on CPU, (16, 32) runs
    ~20% faster than the TPU-tuned (32, 64) (smaller matrices stay
    cache-resident; the CPU pays per-element, not per-chain-step). TPU
    keeps the tuned shape -- chain-step count dominates there."""
    import jax as _jax
    if _jax.default_backend() == "tpu":
        return WAVE_K, WAVE_INNER
    return 16, 32


def _wave_block_enabled() -> bool:
    """Run-block dispatch gate: on by default everywhere (the CPU test
    suite then parity-gates it continuously); NOMAD_TPU_WAVE_BLOCK=0
    falls back to the per-placement compact scan."""
    import os
    return os.environ.get("NOMAD_TPU_WAVE_BLOCK", "1") != "0"


def _solve_wave_block_impl(compact, scal_f, scal_i, pen,
                           spread_alg: bool = False,
                           dtype_name: str = "float32",
                           B: int = WAVE_B, K: int = WAVE_K,
                           INNER: int = WAVE_INNER):
    """Run-block wavefront solve over a host-precomputed compact table;
    bit-identical outputs to _solve_wave_compact_impl on eligible lanes
    (see block comment above). ``pen`` is accepted for call-signature
    parity and must be penalty-free (callers gate)."""
    del pen                     # gated: no active reschedule penalties
    dtype = jnp.dtype(dtype_name)
    C = compact.shape[0]
    P = C - B
    ask_cpu = scal_f[0]
    ask_mem = scal_f[1]
    count = scal_f[2]
    L = scal_i[0]
    n_active = scal_i[1]
    arangeB = jnp.arange(B, dtype=jnp.int32)
    arangeK = jnp.arange(K, dtype=jnp.int32)
    arangeC = jnp.arange(C, dtype=jnp.int32)
    arangePK = jnp.arange(P + K, dtype=jnp.int32)
    neg_inf = jnp.array(-jnp.inf, dtype=dtype)
    big = jnp.iinfo(jnp.int32).max

    def head_state(j, slot):
        """The classic step's per-slot head computation at the current
        (j, slot) -- (B,)-wide only; the winner's forward stream is
        rebuilt from scalars in block_step. All expressions mirror
        _solve_wave_compact_impl op for op so scores are bit-identical.
        The three selection cumsums collapse to one stacked cumsum via
        cumsum(skipped) == min(cumsum(low), MAX_SKIP) (the skip budget
        takes exactly the first MAX_SKIP lows) and cumsum(counted) ==
        cumsum(fit) - cumsum(skipped) (skipped is a subset of fit)."""
        cs = slot[:, 0]
        fit0 = j.astype(dtype) < cs
        jp1 = (j + 1).astype(dtype)
        new_cpu = slot[:, 1] + jp1 * ask_cpu
        new_mem = slot[:, 2] + jp1 * ask_mem
        free_cpu = 1.0 - new_cpu / jnp.maximum(slot[:, 3], 1e-9)
        free_mem = 1.0 - new_mem / jnp.maximum(slot[:, 4], 1e-9)
        binpack = _binpack_score(free_cpu, free_mem, spread_alg)
        coll = slot[:, 5] + j.astype(dtype)
        anti = jnp.where(
            coll > 0, -(coll + 1.0) / jnp.maximum(count, 1.0), 0.0)
        affs = slot[:, 6]
        nsc = (1.0 + (coll > 0).astype(dtype)
               + (affs != 0.0).astype(dtype))
        f0 = (binpack + (anti + affs)) / nsc
        low = fit0 & (f0 <= SKIP_THRESHOLD)
        cs2 = jnp.cumsum(
            jnp.stack([low, fit0]).astype(jnp.int32), axis=1)
        skip_rank = cs2[0]
        srank = jnp.minimum(skip_rank, MAX_SKIP)
        skipped = low & (skip_rank <= MAX_SKIP)
        cpos = cs2[1] - srank
        counted = fit0 & ~skipped
        window = counted & (cpos <= L)
        deficit = jnp.maximum(0, L - jnp.minimum(cpos[-1], L))
        fallback = skipped & (srank <= deficit)
        yielded = window | fallback
        order = jnp.where(window, cpos, L + srank)
        ny = jnp.sum(yielded.astype(jnp.int32), dtype=jnp.int32)
        any_yield = jnp.any(yielded)
        return f0, low, yielded, order, ny, any_yield

    def block_step(carry, _):
        """One greedy run decision over the SMALL solver state. Emitted
        records (winner pos, run length, start offset, ny, the winner's
        K score values) are lax.scan ys -- kept OUT of the carry so the
        vmapped loop's per-iteration masking touches only ~B*9 floats,
        not the (P+K,) output buffers."""
        j, slot, cursor, p, done = carry
        f0, low, yielded, order, ny, any_yield = head_state(j, slot)

        # classic winner: max head, ties to the earliest window order.
        # The candidate set must be masked to YIELDED slots (the compact
        # kernel's `is_best = yielded & (eff == best)` rule): if every
        # yielded head is exactly -inf, best == neg_inf also matches
        # non-yielded slots, and one with a smaller order value would
        # steal the win (ADVICE low #1).
        effH = jnp.where(yielded, f0, neg_inf)
        best = jnp.max(effH)
        w = jnp.argmin(jnp.where(yielded & (effH == best), order, big))
        oh_w = arangeB == w

        # winner scalars in ONE masked reduce (all integer-valued
        # columns are < 2^24: exact in the score dtype)
        svals = jnp.sum(jnp.where(
            oh_w[:, None],
            jnp.concatenate(
                [slot[:, :8],
                 jnp.stack([j.astype(dtype), order.astype(dtype),
                            low.astype(dtype)], axis=1)], axis=1),
            0.0), axis=0)
        cs_w, ucpu_w, umem_w = svals[0], svals[1], svals[2]
        ccap_w, mcap_w, placed_w = svals[3], svals[4], svals[5]
        aff_w, pos_w = svals[6], svals[7]
        j_wf, order_wf, low_wf = svals[8], svals[9], svals[10]
        low_w = low_wf != 0.0
        eff_o = jnp.where(oh_w, neg_inf, effH)
        rub = jnp.max(eff_o)
        rub_ord = jnp.min(jnp.where(eff_o == rub, order, big))

        # winner's forward stream from scalars: vals[q] = score of its
        # (j_w + q + 1)-th placement, the same elementwise expressions
        # as head_state broadcast over q (exact-int float arithmetic)
        jq = j_wf + arangeK.astype(dtype)
        validw = jq < cs_w
        jp1q = jq + 1.0
        fcq = 1.0 - (ucpu_w + jp1q * ask_cpu) / jnp.maximum(ccap_w, 1e-9)
        fmq = 1.0 - (umem_w + jp1q * ask_mem) / jnp.maximum(mcap_w, 1e-9)
        bpq = _binpack_score(fcq, fmq, spread_alg)
        collq = placed_w + jq
        antiq = jnp.where(
            collq > 0, -(collq + 1.0) / jnp.maximum(count, 1.0), 0.0)
        nscq = (1.0 + (collq > 0).astype(dtype)
                + jnp.where(aff_w != 0.0, 1.0, 0.0))
        vals = (bpq + (antiq + aff_w)) / nscq

        # run length: picks until the winner loses, transitions through
        # the skip threshold, runs out of capacity, or exhausts the eval
        q = arangeK
        win_q = ((vals > rub)
                 | ((vals == rub) & (order_wf < rub_ord.astype(dtype)))
                 | (q == 0))
        cross = jnp.where(low_w, vals > SKIP_THRESHOLD,
                          vals <= SKIP_THRESHOLD) & (q > 0)
        stop_q = (~validw) | (~win_q) | cross | (q >= n_active - p)
        tlim = jnp.min(jnp.where(stop_q, q, K))
        # saturation: the q_sat-th pick fills the slot (j_w + q_sat + 1
        # == c_w); commit it, then shift/refill. c/j < 2^24: exact
        # floats.
        q_sat = (cs_w - 1.0 - j_wf).astype(jnp.int32)
        has_sat = (q_sat < K) & (q_sat < tlim)
        t = jnp.where(has_sat, q_sat + 1, tlim)
        # t >= 1 whenever active: q=0 is valid (the winner is yielded,
        # hence fit), wins by construction, and cannot be a threshold
        # crossing
        active = any_yield & ~done & (p < n_active)
        t = jnp.where(active, t, 0)
        has_sat = has_sat & active

        j2 = j + oh_w.astype(jnp.int32) * t

        # classic shift/refill, gated on the saturation event
        j3, slot2, cursor2 = _wave_refill_shift(
            compact, cursor, w, j2, slot, has_sat, arangeB, arangeC)
        done2 = done | ~any_yield
        # invalid stream positions store 0.0 (not -inf): the outer
        # expansion reads them through a one-hot matmul, and
        # 0 * -inf would poison the row sums with NaN; positions
        # beyond the run length are never selected anyway
        rec = (pos_w, t, p, ny, jnp.where(validw, vals, 0.0))
        return (j3, slot2, cursor2, p + t, done2), rec

    def outer_body(carry):
        """INNER run decisions via lax.scan (small carry), then ONE
        vectorized expansion of the records into the output buffers --
        the buffers ride only this outer loop, whose trip count is
        ~P / (INNER * mean-run) instead of the block count."""
        j, slot, cursor, p, done, ch_buf, sc_buf, ny_buf = carry
        p_begin = p
        (j2, slot2, cursor2, p2, done2), recs = jax.lax.scan(
            block_step, (j, slot, cursor, p, done), None, length=INNER)
        pos_r, t_r, p0_r, ny_r, vals_r = recs

        # expansion: position s belongs to the LAST block whose start
        # offset is <= s (starts are non-decreasing; finished-lane
        # records have t=0 and start=p2 > s for any committed s). All
        # record lookups go through one-hot MATMULS, not gathers --
        # batched gathers hit TPU slow paths, one (P+K, INNER) matmul
        # rides the MXU. Record scalars are exact small ints in the
        # score dtype.
        s = arangePK
        leq = (p0_r[None, :] <= s[:, None])            # (P+K, INNER)
        nxt = jnp.concatenate(
            [leq[:, 1:], jnp.zeros((P + K, 1), dtype=bool)], axis=1)
        blk_oh = (leq & ~nxt).astype(dtype)            # one-hot of blk
        recmat = jnp.stack(
            [pos_r, t_r.astype(dtype), p0_r.astype(dtype),
             ny_r.astype(dtype)], axis=1)              # (INNER, 4)
        # HIGHEST precision: TPU matmuls default to bf16 passes,
        # which would round the exact-int node positions; with one-hot
        # rows (single nonzero term) full-f32 passes are exact
        rs = jnp.matmul(blk_oh, recmat,
                        precision=jax.lax.Precision.HIGHEST)

        q_s = s.astype(dtype) - rs[:, 2]
        covered = ((s >= p_begin) & (s < p2)
                   & (q_s >= 0) & (q_s < rs[:, 1]))
        rowvals = jnp.matmul(blk_oh, vals_r,
                             precision=jax.lax.Precision.HIGHEST)
        q_oh = (arangeK[None, :].astype(dtype)
                == jnp.clip(q_s, 0, K - 1)[:, None])
        sc_s = jnp.sum(jnp.where(q_oh, rowvals, 0.0), axis=1)
        ch_buf = jnp.where(covered, rs[:, 0].astype(jnp.int32), ch_buf)
        sc_buf = jnp.where(covered, sc_s, sc_buf)
        ny_buf = jnp.where(covered, rs[:, 3].astype(jnp.int32), ny_buf)
        return (j2, slot2, cursor2, p2, done2, ch_buf, sc_buf, ny_buf)

    slot0 = compact[:B]
    j0 = jnp.zeros(B, dtype=jnp.int32)
    carry0 = (j0, slot0, jnp.int32(B), jnp.int32(0),
              jnp.array(False),
              jnp.full(P + K, -1, dtype=jnp.int32),
              jnp.full(P + K, -jnp.inf, dtype=dtype),
              jnp.zeros(P + K, dtype=jnp.int32))

    def cond(carry):
        _, _, _, p, done, _, _, _ = carry
        return (p < n_active) & ~done

    (j_f, slot_f, _, p_end, _, ch_buf, sc_buf,
     ny_buf) = jax.lax.while_loop(cond, outer_body, carry0)

    # beyond-active / stuck tail: the classic scan keeps emitting
    # (chosen=-1, best-head score, n_yielded) from its frozen state for
    # every remaining step; broadcast the same from the final state
    f0_f, _, yielded_f, _, ny_f, any_yield_f = head_state(j_f, slot_f)
    effH_f = jnp.where(yielded_f, f0_f, neg_inf)
    best_f = jnp.max(effH_f)
    fill_mask = arangePK >= p_end
    sc_fill = jnp.where(any_yield_f, best_f, neg_inf)
    ch_buf = jnp.where(fill_mask, -1, ch_buf)
    sc_buf = jnp.where(fill_mask, sc_fill, sc_buf)
    ny_buf = jnp.where(fill_mask, ny_f, ny_buf)
    return ch_buf[:P], sc_buf[:P], ny_buf[:P]


# ---------------------------------------------------------------------------
# Wavefront preemption: the windowed kernel family extended to the
# eviction-enabled select (VERDICT r3 next-step 3).
#
# The dense preempt path re-runs the greedy eviction search over ALL N
# nodes' (N, A) candidate tables per placement step -- the tier-5 lanes
# where the dense scan was slowest. But the selection window only ever
# examines the first limit+MAX_SKIP OPTION nodes in shuffled order, where
# an option is plain-fit OR eviction-met (rank.go:545-565); so the scan
# can carry a B-slot buffer of front option nodes -- each slot holding its
# (A,) candidate columns and accumulated eviction mask -- and run the
# search over (B, A) instead of (N, A): ~N/B (=300x at 10K nodes) less
# per-step work, sharing _preempt_search_core with the dense kernel.
#
# Window-membership correctness: a node OUTSIDE the window has never been
# chosen, so its state is pristine and its option-status is static ->
# precomputable on the host (the refill list). Option-status is monotone
# non-increasing (picks and evictions only consume), so a shifted-out
# slot can never become an option again; eviction-met is coverage-based
# and therefore independent of the max_parallel penalty ordering, so
# global count changes can't resurrect a node either. Slots shift out
# when the chosen node exhausts BOTH plain fit and eviction potential;
# refills enter pristine from the precomputed list.
#
# Eligibility (wavefront_preempt_ok): preempt lanes already exclude
# networks/devices/cores (service.tg_solver_eligible preempt=True), so
# the kernel models cpu/mem/disk + distinct_hosts + affinity + penalties;
# spreads stay dense.

# slot columns for the preempt wavefront (compactP, (C, _WPC_NCOLS))
_WPC_FEAS = 0
_WPC_UC, _WPC_UM, _WPC_UD = 1, 2, 3
_WPC_CC, _WPC_CM, _WPC_CD = 4, 5, 6
_WPC_PLACED, _WPC_PLACED_JOB = 7, 8
_WPC_AFF, _WPC_POS = 9, 10
_WPC_CDEV = 11          # device-dimension placement capacity (2^24 =
_WPC_NCOLS = 12         # unbounded; exact in float32)
_WPC_DEV_UNBOUNDED = float(2 ** 24)


def _numpy_preempt_pristine(ccpu, cmem, cdisk, cprio, cmaxp, cgrp, cvalid,
                            counts, cpu_cap, mem_cap, disk_cap, job_prio,
                            ask_cpu, ask_mem, ask_disk):
    """Exact host-side transcription of _preempt_search_core at pristine
    state (no prior evictions), vectorized over all N nodes in numpy.
    Returns (met (N,), freed (3, N)) using the greedy + filterSuperset
    eviction set -- the same values the device search would produce.
    All arithmetic runs in the candidate arrays' dtype: a float64 host
    pass against a float32 device search could flip near-tie argmins and
    admit nodes the in-step search can't yield (window-starving zombies)
    or drop real options."""
    dt = ccpu.dtype
    ask_cpu = dt.type(ask_cpu)
    ask_mem = dt.type(ask_mem)
    ask_disk = dt.type(ask_disk)
    N, A = ccpu.shape
    elig = cvalid & (job_prio - cprio >= 10)
    avail_c0 = (cpu_cap - np.sum(np.where(cvalid, ccpu, 0.0), axis=1,
                                 dtype=dt)).astype(dt)
    avail_m0 = (mem_cap - np.sum(np.where(cvalid, cmem, 0.0), axis=1,
                                 dtype=dt)).astype(dt)
    avail_d0 = (disk_cap - np.sum(np.where(cvalid, cdisk, 0.0), axis=1,
                                  dtype=dt)).astype(dt)
    n_pre = np.where(cgrp >= 0, counts[np.maximum(cgrp, 0)], 0)
    penalty = np.where((cmaxp > 0) & (n_pre >= cmaxp),
                       (n_pre + 1 - cmaxp) * dt.type(MAX_PARALLEL_PENALTY),
                       dt.type(0.0)).astype(dt)

    def dist(ne_c, ne_m, ne_d):
        eps = dt.type(1e-9)
        zero = dt.type(0.0)
        dc = np.where(ne_c > 0, (ne_c - ccpu) / np.maximum(ne_c, eps), zero)
        dm = np.where(ne_m > 0, (ne_m - cmem) / np.maximum(ne_m, eps), zero)
        dd = np.where(ne_d > 0, (ne_d - cdisk) / np.maximum(ne_d, eps),
                      zero)
        return np.sqrt(dc * dc + dm * dm + dd * dd).astype(dt)

    picked = np.zeros((N, A), dtype=bool)
    av_c, av_m, av_d = avail_c0.copy(), avail_m0.copy(), avail_d0.copy()
    ne_c = np.full(N, ask_cpu, dtype=dt)
    ne_m = np.full(N, ask_mem, dtype=dt)
    ne_d = np.full(N, ask_disk, dtype=dt)
    # must fit cprio's dtype: a wider sentinel silently WRAPS under
    # NEP-50 value-based casting (int64 max as int32 == -1, which then
    # wins every np.min and empties the pick group)
    big_i = np.iinfo(np.int32).max
    for _ in range(A):
        met = ((av_c >= ask_cpu) & (av_m >= ask_mem) & (av_d >= ask_disk)
               & picked.any(axis=1))
        cand = elig & ~picked
        if not np.any(~met & cand.any(axis=1)):
            break
        cur_prio = np.min(np.where(cand, cprio, big_i), axis=1)
        in_group = cand & (cprio == cur_prio[:, None])
        key = np.where(in_group,
                       dist(ne_c[:, None], ne_m[:, None], ne_d[:, None])
                       + penalty, np.inf)
        pick = np.argmin(key, axis=1)
        do = ~met & in_group.any(axis=1)
        onehot = (np.arange(A)[None, :] == pick[:, None]) & do[:, None]
        pc = np.sum(np.where(onehot, ccpu, 0.0), axis=1)
        pm = np.sum(np.where(onehot, cmem, 0.0), axis=1)
        pd = np.sum(np.where(onehot, cdisk, 0.0), axis=1)
        picked |= onehot
        av_c += pc; av_m += pm; av_d += pd            # noqa: E702
        ne_c -= pc; ne_m -= pm; ne_d -= pd            # noqa: E702
    met = ((av_c >= ask_cpu) & (av_m >= ask_mem) & (av_d >= ask_disk)
           & picked.any(axis=1))

    # filterSuperset: re-add picked in descending distance-to-ask order
    d0 = dist(np.full(N, ask_cpu)[:, None], np.full(N, ask_mem)[:, None],
              np.full(N, ask_disk)[:, None])
    sort_key = np.where(picked, -d0, np.inf)
    order = np.argsort(sort_key, axis=1, kind="stable")
    oc = np.take_along_axis(np.where(picked, ccpu, 0.0), order, axis=1)
    om = np.take_along_axis(np.where(picked, cmem, 0.0), order, axis=1)
    od = np.take_along_axis(np.where(picked, cdisk, 0.0), order, axis=1)
    cum_c = avail_c0[:, None] + np.cumsum(oc, axis=1)
    cum_m = avail_m0[:, None] + np.cumsum(om, axis=1)
    cum_d = avail_d0[:, None] + np.cumsum(od, axis=1)
    met_at = ((cum_c >= ask_cpu) & (cum_m >= ask_mem)
              & (cum_d >= ask_disk))
    first_met = np.argmax(met_at, axis=1)
    keep_sorted = (np.arange(A)[None, :] <= first_met[:, None])
    keep_sorted &= np.take_along_axis(picked, order, axis=1)
    evict = np.zeros_like(picked)
    np.put_along_axis(evict, order, keep_sorted, axis=1)
    freed = np.stack([np.sum(np.where(evict, t, 0.0), axis=1)
                      for t in (ccpu, cmem, cdisk)])
    return met, freed


def wavefront_preempt_compact_host(const, init, batch, ptab, pinit,
                                   dtype_name: str,
                                   p_pad: Optional[int] = None,
                                   B: int = WAVE_B):
    """Host precompute for ONE preempt lane: the pristine option
    predicate + refill-ordered compact node columns and candidate tables.
    Returns (compactP (C, _WPC_NCOLS), cand dict of (C, A) arrays, scal_f (4,),
    scal_i (4,), pen (P,), counts0 (G,))."""
    dt = np.dtype(dtype_name)
    P = int(np.asarray(batch.ask_cpu).shape[0])
    P_out = max(P, p_pad or 0)
    N = int(np.asarray(const.cpu_cap).shape[0])
    A = int(np.asarray(ptab.cpu).shape[1])
    ask_cpu = float(np.asarray(batch.ask_cpu, dtype=dt)[0])
    ask_mem = float(np.asarray(batch.ask_mem, dtype=dt)[0])
    ask_disk = float(np.asarray(batch.ask_disk, dtype=dt)[0])
    count = float(np.asarray(batch.count, dtype=dt)[0])
    L = int(np.asarray(batch.limit)[0])
    n_active = int(np.asarray(batch.active).sum())
    job_prio = int(np.asarray(ptab.job_prio))

    cpu_cap = np.asarray(const.cpu_cap, dtype=dt)
    mem_cap = np.asarray(const.mem_cap, dtype=dt)
    disk_cap = np.asarray(const.disk_cap, dtype=dt)
    used_c = np.asarray(init.used_cpu, dtype=dt)
    used_m = np.asarray(init.used_mem, dtype=dt)
    used_d = np.asarray(init.used_disk, dtype=dt)
    feas = np.asarray(const.feasible, dtype=bool)
    placed0 = np.asarray(init.placed)
    placed_job0 = np.asarray(init.placed_job)
    distinct = bool(np.asarray(const.distinct_hosts))
    job_level = bool(np.asarray(const.distinct_job_level))
    distinct_flag = (2 if distinct and job_level
                     else (1 if distinct else 0))

    dcount0 = placed_job0 if job_level else placed0
    feas_nonres0 = feas if not distinct else (feas & (dcount0 == 0))
    # device-dimension capacity (uniform ask, zero affinity weight --
    # wavefront_ok gates): a node with no eligible group (or drained by
    # earlier placements, tracked via j in the kernel) is NOT an option,
    # not even via eviction -- eviction never frees matching devices
    # (pack() rejects lanes whose evictable candidates hold them), so a
    # failed device assign skips the node exactly like rank.go:443's
    # PreemptForDevice returning nil
    if np.asarray(const.dev_aff).shape[0]:
        c_dev = _wave_device_capacity(const, init)
        assert c_dev is not None, "unbounded device capacity replay"
        dev_ok0 = c_dev >= 1
    else:
        c_dev = None
        dev_ok0 = np.ones(N, dtype=bool)
    fit0 = (feas_nonres0 & dev_ok0
            & (used_c + ask_cpu <= cpu_cap)
            & (used_m + ask_mem <= mem_cap)
            & (used_d + ask_disk <= disk_cap))

    cvalid = np.asarray(ptab.valid, dtype=bool)               # (N, A)
    cprio = np.asarray(ptab.prio)
    ccpu = np.asarray(ptab.cpu, dtype=dt)
    cmem = np.asarray(ptab.mem, dtype=dt)
    cdisk = np.asarray(ptab.disk, dtype=dt)
    cmaxp = np.asarray(ptab.maxp)
    cgrp = np.asarray(ptab.grp)
    counts_np = np.asarray(pinit.counts, dtype=np.int64)
    # pristine eviction outcome, computed EXACTLY (numpy transcription of
    # _preempt_search_core's greedy + filterSuperset + the fit2 clamp): a
    # conservative coverage bound here admits nodes the in-step search
    # can never actually yield, and B such zombies starve the window
    met0, freed0 = _numpy_preempt_pristine(
        ccpu, cmem, cdisk, cprio, cmaxp, cgrp, cvalid, counts_np,
        cpu_cap, mem_cap, disk_cap, job_prio,
        ask_cpu, ask_mem, ask_disk)
    fit2g0 = ((used_c + ask_cpu - freed0[0] <= cpu_cap)
              & (used_m + ask_mem - freed0[1] <= mem_cap)
              & (used_d + ask_disk - freed0[2] <= disk_cap))
    option0 = fit0 | (feas_nonres0 & dev_ok0 & ~fit0 & met0 & fit2g0)

    fit_pos = np.nonzero(option0)[0][:P_out + B]
    C = P_out + B
    compact = np.zeros((C, _WPC_NCOLS), dtype=dt)
    compact[:, _WPC_POS] = -1.0
    k = fit_pos.shape[0]
    compact[:k, _WPC_FEAS] = feas[fit_pos].astype(dt)
    compact[:k, _WPC_UC] = used_c[fit_pos]
    compact[:k, _WPC_UM] = used_m[fit_pos]
    compact[:k, _WPC_UD] = used_d[fit_pos]
    compact[:k, _WPC_CC] = cpu_cap[fit_pos]
    compact[:k, _WPC_CM] = mem_cap[fit_pos]
    compact[:k, _WPC_CD] = disk_cap[fit_pos]
    compact[:k, _WPC_PLACED] = placed0[fit_pos].astype(dt)
    compact[:k, _WPC_PLACED_JOB] = placed_job0[fit_pos].astype(dt)
    aff = (np.asarray(const.affinity, dtype=dt)
           if bool(np.asarray(const.has_affinity))
           else np.zeros(N, dtype=dt))
    compact[:k, _WPC_AFF] = aff[fit_pos]
    compact[:k, _WPC_POS] = fit_pos.astype(dt)
    if c_dev is not None:
        compact[:k, _WPC_CDEV] = np.minimum(
            c_dev[fit_pos], P_out + 1).astype(dt)
    else:
        compact[:, _WPC_CDEV] = dt.type(_WPC_DEV_UNBOUNDED)

    def take(arr, fill):
        out = np.full((C, A), fill, dtype=arr.dtype)
        out[:k] = arr[fit_pos]
        return out

    cand = {
        "cpu": take(ccpu, dt.type(0)),
        "mem": take(cmem, dt.type(0)),
        "disk": take(cdisk, dt.type(0)),
        "prio": take(cprio.astype(np.int32), np.int32(0)),
        "maxp": take(np.asarray(ptab.maxp, dtype=np.int32), np.int32(0)),
        "grp": take(np.asarray(ptab.grp, dtype=np.int32), np.int32(-1)),
        "valid": take(cvalid, False),
    }
    scal_f = np.array([ask_cpu, ask_mem, ask_disk, count], dtype=dt)
    scal_i = np.array([L, n_active, job_prio, distinct_flag],
                      dtype=np.int32)
    pen = np.full(P_out, -1, dtype=np.int32)
    pen[:P] = np.asarray(batch.penalty_idx, dtype=np.int32)
    counts0 = np.asarray(pinit.counts, dtype=np.int32)
    return compact, cand, scal_f, scal_i, pen, counts0


def _solve_wave_preempt_impl(compact, cand, scal_f, scal_i, pen, counts0,
                             B: int = WAVE_B, spread_alg: bool = False,
                             dtype_name: str = "float32"):
    """Device scan for the windowed preemption select. Returns
    (chosen (P,), scores (P,), n_yielded (P,), evict_rows (P, A))."""
    dtype = jnp.dtype(dtype_name)
    C = compact.shape[0]
    A = cand["cpu"].shape[1]
    P = C - B
    G = counts0.shape[0]
    ask_cpu = scal_f[0]
    ask_mem = scal_f[1]
    ask_disk = scal_f[2]
    count = scal_f[3]
    L = scal_i[0]
    n_active = scal_i[1]
    job_prio = scal_i[2]
    distinct_flag = scal_i[3]

    slot0 = compact[:B]
    cand0 = {k: v[:B] for k, v in cand.items()}
    j0 = jnp.zeros(B, dtype=jnp.int32)
    evict0 = jnp.zeros((B, A), dtype=bool)
    cursor0 = jnp.int32(B)
    arangeB = jnp.arange(B, dtype=jnp.int32)
    arangeC = jnp.arange(C, dtype=jnp.int32)
    neg_inf = jnp.array(-jnp.inf, dtype=dtype)
    big = jnp.iinfo(jnp.int32).max

    def option_state(slot, cd, j, evicted, counts):
        """Per-slot fit/preempt status + scores against current state."""
        jf = j.astype(dtype)
        freed_prev_c = jnp.sum(jnp.where(evicted, cd["cpu"], 0.0), axis=1)
        freed_prev_m = jnp.sum(jnp.where(evicted, cd["mem"], 0.0), axis=1)
        freed_prev_d = jnp.sum(jnp.where(evicted, cd["disk"], 0.0), axis=1)
        used_now_c = slot[:, _WPC_UC] + jf * ask_cpu - freed_prev_c
        used_now_m = slot[:, _WPC_UM] + jf * ask_mem - freed_prev_m
        used_now_d = slot[:, _WPC_UD] + jf * ask_disk - freed_prev_d
        new_c = used_now_c + ask_cpu
        new_m = used_now_m + ask_mem
        new_d = used_now_d + ask_disk

        dcount = jnp.where(distinct_flag == 2,
                           slot[:, _WPC_PLACED_JOB] + jf,
                           slot[:, _WPC_PLACED] + jf)
        # device capacity countdown: each landed placement (j) consumed
        # one unit; a drained node stops being an option entirely (no
        # eviction can free matching devices -- pack() gates on that)
        dev_ok = slot[:, _WPC_CDEV] - jf >= 1.0
        feas_nonres = ((slot[:, _WPC_FEAS] > 0.5) & dev_ok
                       & ((distinct_flag == 0) | (dcount == 0.0)))
        fit = (feas_nonres
               & (new_c <= slot[:, _WPC_CC])
               & (new_m <= slot[:, _WPC_CM])
               & (new_d <= slot[:, _WPC_CD]))

        valid_now = cd["valid"] & ~evicted
        eligible = valid_now & (job_prio - cd["prio"] >= 10)
        # static-length greedy on TPU (a dynamic-trip-count loop of tiny
        # (B, A) ops inside a scan step is per-iteration sync latency);
        # early-exit while_loop on CPU (the search usually needs only a
        # few picks, and full-A straight-line code costs more than the
        # saved dispatches there)
        import jax as _jax
        met, evict, freed_c, freed_m, freed_d, net_prio = \
            _preempt_search_core(
                cd["cpu"], cd["mem"], cd["disk"], cd["prio"], cd["maxp"],
                cd["grp"], valid_now, eligible, slot[:, _WPC_CC],
                slot[:, _WPC_CM], slot[:, _WPC_CD], counts,
                ask_cpu, ask_mem, ask_disk, dtype,
                static_iters=_jax.default_backend() == "tpu")
        fit2 = ((new_c - freed_c <= slot[:, _WPC_CC])
                & (new_m - freed_m <= slot[:, _WPC_CM])
                & (new_d - freed_d <= slot[:, _WPC_CD]))
        fit_p = feas_nonres & ~fit & met & fit2

        # scoring (mirrors _score_and_select_preempt on the slot axis)
        free_cpu = 1.0 - new_c / jnp.maximum(slot[:, _WPC_CC], 1e-9)
        free_mem = 1.0 - new_m / jnp.maximum(slot[:, _WPC_CM], 1e-9)
        binpack = _binpack_score(free_cpu, free_mem, spread_alg)
        free_cpu_p = 1.0 - (new_c - freed_c) / jnp.maximum(
            slot[:, _WPC_CC], 1e-9)
        free_mem_p = 1.0 - (new_m - freed_m) / jnp.maximum(
            slot[:, _WPC_CM], 1e-9)
        binpack_p = _binpack_score(free_cpu_p, free_mem_p, spread_alg)
        pscore = 1.0 / (1.0 + jnp.exp(
            PREEMPT_SCORE_RATE * (net_prio - PREEMPT_SCORE_ORIGIN)))
        return (fit, fit_p, binpack, binpack_p, pscore, evict,
                freed_c, freed_m, freed_d)

    def step(carry, xs):
        i, pen_i = xs
        j, slot, cd, evicted, cursor, counts, pending = carry

        (fit, fit_p, binpack, binpack_p, pscore, evict,
         freed_c, freed_m, freed_d) = option_state(
            slot, cd, j, evicted, counts)

        coll = slot[:, _WPC_PLACED] + j.astype(dtype)
        anti = jnp.where(
            coll > 0, -(coll + 1.0) / jnp.maximum(count, 1.0), 0.0)
        is_pen = (pen_i >= 0) & (slot[:, _WPC_POS] == pen_i.astype(dtype))
        resched = jnp.where(is_pen, -1.0, 0.0)
        affs = slot[:, _WPC_AFF]
        nscores = (1.0 + (coll > 0).astype(dtype)
                   + is_pen.astype(dtype) + (affs != 0.0).astype(dtype))
        other = anti + resched + affs
        final_plain = (binpack + other) / nscores
        final_pre = (binpack_p + other + pscore) / (nscores + 1.0)
        fit_c = fit | fit_p
        final = jnp.where(fit_p, final_pre, final_plain)

        low = fit_c & (final <= SKIP_THRESHOLD)
        skip_rank = jnp.cumsum(low.astype(jnp.int32))
        skipped = low & (skip_rank <= MAX_SKIP)
        counted = fit_c & ~skipped
        cpos = jnp.cumsum(counted.astype(jnp.int32))
        total_counted = cpos[-1]
        window = counted & (cpos <= L)
        deficit = jnp.maximum(0, L - jnp.minimum(total_counted, L))
        srank = jnp.cumsum(skipped.astype(jnp.int32))
        fallback = skipped & (srank <= deficit)
        yielded = window | fallback
        order = jnp.where(window, cpos, L + srank)
        eff = jnp.where(yielded, final, neg_inf)
        best = jnp.max(eff)
        is_best = yielded & (eff == best)
        border = jnp.min(jnp.where(is_best, order, big))
        w = jnp.argmax(is_best & (order == border))
        any_yield = jnp.any(yielded)
        do = (i < n_active) & any_yield
        oh_w = arangeB == w
        chosen = jnp.where(
            do,
            jnp.sum(jnp.where(oh_w, slot[:, _WPC_POS], 0.0))
            .astype(jnp.int32), -1)
        score_out = jnp.where(any_yield, best, neg_inf)
        ny = jnp.sum(yielded.astype(jnp.int32))

        # commit: the winner takes one copy; a preempting winner applies
        # its eviction row and bumps the per-group counts
        was_pre = jnp.any(oh_w & fit_p) & do
        evict_w = evict & oh_w[:, None] & was_pre
        evict_row_out = jnp.any(evict_w, axis=0)                # (A,)
        do_i = do.astype(jnp.int32)
        j2 = j + oh_w.astype(jnp.int32) * do_i
        evicted2 = evicted | evict_w
        grp_hot = ((jnp.arange(G, dtype=jnp.int32)[None, None, :]
                    == jnp.maximum(cd["grp"], 0)[:, :, None])
                   & (cd["grp"] >= 0)[:, :, None]
                   & evict_w[:, :, None])
        counts2 = counts + jnp.sum(grp_hot, axis=(0, 1)).astype(jnp.int32)

        # shift-out, DEFERRED one step: this step's search already gives
        # every slot's exact option status, and a committed winner's state
        # only changes at its commit -- so the PREVIOUS winner ("pending")
        # is a zombie iff it is not an option NOW. Deferring avoids a
        # second in-step search; at most one zombie occupies the buffer
        # for one step (never counted -- fit_c is False -- so the window
        # semantics are unaffected while B >= L + MAX_SKIP + 1). Entries
        # are exact options by the host's pristine predicate, so zombies
        # only ever arise from winners.
        z = jnp.maximum(pending, 0)
        oh_z = arangeB == z
        zomb = (pending >= 0) & ~jnp.any(oh_z & fit_c)
        if _wave_gather_dynslice():
            entry_row = jax.lax.dynamic_slice_in_dim(
                compact, jnp.clip(cursor, 0, C - 1), 1, axis=0)[0]
        else:
            oh_c = arangeC == jnp.clip(cursor, 0, C - 1)
            entry_row = jnp.sum(jnp.where(oh_c[:, None], compact, 0.0),
                                axis=0)
        entry_cd = {
            kk: jnp.sum(jnp.where(oh_c[:, None], vv,
                                  jnp.zeros((), dtype=vv.dtype)),
                        axis=0).astype(vv.dtype)
            for kk, vv in cand.items()}
        take_next = arangeB >= z
        is_last = arangeB == B - 1

        def shift1(cur, entry):
            return jnp.where(
                is_last.reshape((B,) + (1,) * (cur.ndim - 1)),
                entry[None], jnp.where(
                    take_next.reshape((B,) + (1,) * (cur.ndim - 1)),
                    jnp.roll(cur, -1, axis=0), cur))

        j_sh = shift1(j2, jnp.zeros((), dtype=jnp.int32))
        slot_sh = shift1(slot, entry_row)
        cd_sh = {kk: shift1(vv, entry_cd[kk]) for kk, vv in cd.items()}
        ev_sh = shift1(evicted2, jnp.zeros(A, dtype=bool))
        j3 = jnp.where(zomb, j_sh, j2)
        slot2 = jnp.where(zomb, slot_sh, slot)
        cd2 = {kk: jnp.where(zomb, cd_sh[kk], vv)
               for kk, vv in cd.items()}
        ev3 = jnp.where(zomb, ev_sh, evicted2)
        cursor2 = cursor + zomb.astype(jnp.int32)
        # next step's pending = this step's winner, index adjusted for the
        # zombie roll (w can never equal z: zombies are never yielded)
        w_adj = jnp.where(zomb & (w > z), w - 1, w)
        pending2 = jnp.where(do, w_adj.astype(jnp.int32), -1)
        return ((j3, slot2, cd2, ev3, cursor2, counts2, pending2),
                (chosen, score_out, ny, evict_row_out))

    carry0 = (j0, slot0, cand0, evict0, cursor0,
              counts0.astype(jnp.int32), jnp.int32(-1))
    _, (chosen, scores, n_yielded, evict_rows) = jax.lax.scan(
        step, carry0,
        (jnp.arange(P, dtype=jnp.int32), pen.astype(jnp.int32)),
        unroll=1)
    return chosen, scores, n_yielded, evict_rows


@_single_flight
@functools.lru_cache(maxsize=None)
def _wave_preempt_program(cm_shape, cd_shape, c0_shape,
                          spread_alg: bool, dtype_name: str,
                          batched: bool, B: int):
    """Per-shape-bucket factory for the windowed-preemption compact
    program. The shape keys don't feed the program body -- they pin one
    jitted callable per bucket so every callable's compile cache holds
    exactly one trace in steady state (jitcheck retrace discipline,
    same keys the old module dict used)."""
    inner = functools.partial(_solve_wave_preempt_impl, B=B,
                              spread_alg=spread_alg,
                              dtype_name=dtype_name)
    if batched:
        inner = jax.vmap(inner)

    @jax.jit
    def fn(cm, cd, sf, si, pn, c0):
        chosen, scores, ny, ev = inner(cm, cd, sf, si, pn, c0)
        return jnp.stack([chosen.astype(scores.dtype), scores,
                          ny.astype(scores.dtype)]), ev
    return fn


def solve_lane_wave_preempt(const, init, batch, ptab, pinit, *,
                            spread_alg: bool, dtype_name: str,
                            batched: bool = False, cache_version=None,
                            delta_src=None):
    """Windowed-preemption solve with host precompute + compact transfer;
    returns host numpy (chosen int64, scores, n_yielded int64,
    evict_rows (P, A) bool), shaped like solve_lane_fused's preempt
    outputs. Callers gate on wavefront_preempt_ok."""
    S_dim = np.asarray(const.spread_vidx).shape[1 if batched else 0]
    if S_dim:
        raise ValueError(
            "wave-preempt kernel carries no spread columns; spread lanes "
            "must stay dense (callers gate on wavefront_ok)")
    if batched:
        E = np.asarray(batch.ask_cpu).shape[0]
        P = int(np.asarray(batch.ask_cpu).shape[1])
        L = int(np.asarray(batch.limit)[0][0])
    else:
        P = int(np.asarray(batch.ask_cpu).shape[0])
        L = int(np.asarray(batch.limit)[0])
    B = wavefront_buffer_size(L)
    if B is None:
        raise ValueError(f"lane limit {L} exceeds every wavefront buffer "
                         "width (caller must gate on wavefront_preempt_ok)")
    p_pad = _wave_p_bucket(P)
    if batched:
        active_rows = np.asarray(batch.active).any(axis=1)

        def pack_one(e):
            pick = lambda a: jax.tree_util.tree_map(  # noqa: E731
                lambda x, e=e: x[e], a)
            return wavefront_preempt_compact_host(
                pick(const), pick(init), pick(batch), pick(ptab),
                pick(pinit), dtype_name, p_pad=p_pad, B=B)

        inert = None
        packs = []
        for e in range(E):
            if not active_rows[e]:
                if inert is None:
                    inert = pack_one(e)
                packs.append(inert)
            else:
                packs.append(pack_one(e))
        compact = np.stack([p[0] for p in packs])
        cand = {k: np.stack([p[1][k] for p in packs])
                for k in packs[0][1]}
        scal_f = np.stack([p[2] for p in packs])
        scal_i = np.stack([p[3] for p in packs])
        pen = np.stack([p[4] for p in packs])
        counts0 = np.stack([p[5] for p in packs])
    else:
        compact, cand, scal_f, scal_i, pen, counts0 = \
            wavefront_preempt_compact_host(const, init, batch, ptab, pinit,
                                           dtype_name, p_pad=p_pad, B=B)

    fn = _wave_preempt_program(compact.shape, cand["cpu"].shape,
                               counts0.shape, spread_alg, dtype_name,
                               batched, B)
    cm, cd, sf, si, pn, c0 = _put_eval_sharded(
        batched, compact.shape[0],
        (compact, cand, scal_f, scal_i, pen, counts0),
        cache_version=cache_version, tag="compact_preempt",
        delta_src=delta_src)
    out = fn(cm, cd, sf, si, pn, c0)
    with jitcheck.sanctioned_fetch("wave_preempt"):
        combined, ev = jax.device_get(out)
    from . import xferobs
    xferobs.note_fetch(xferobs.tree_nbytes((combined, ev)),
                       "wave_preempt")
    combined = combined[..., :P]
    ev = ev[..., :P, :]
    return (combined[0].astype(np.int64), combined[1],
            combined[2].astype(np.int64), np.asarray(ev))


def _put_eval_sharded(batched: bool, e_dim: int, trees,
                      cache_version=None, tag: str = "compact",
                      delta_src=None):
    """Device-put a tuple of (possibly nested) arrays, sharding the
    leading eval axis across ALL attached devices when it divides the
    device count (and NOMAD_TPU_MESH is not 0 -- the same master
    switch as the dense/LPQ mesh routes, so rollback to single-device
    is one knob). The fused eval axis is embarrassingly data-parallel:
    each chip runs its lanes' scans independently (no collectives;
    outputs gather on fetch). Shared by the wave and wave-preempt
    dispatch paths so their sharding gates can't diverge.

    The single-device path routes through the device-resident const
    cache (solver/constcache.py): compact tables that repeat across
    barrier generations of one snapshot ship once and stay pinned,
    keyed by content and tagged with ``cache_version`` (the packing
    snapshot's node_table_index). The sharded path ships fresh -- the
    cache stores unsharded buffers -- but still reports its bytes so
    ``nomad.solver.dispatch_bytes`` means one thing everywhere.
    ``tag`` is the transfer ledger's tree-group attribution for these
    tables (the wave transports ship merged compact tables that can't
    decompose into const/init/batch)."""
    from ..parallel.mesh import mesh_enabled
    from .constcache import device_put_cached

    if not (batched and mesh_enabled() and jax.device_count() > 1
            and e_dim % jax.device_count() == 0):
        leaves, treedef = jax.tree_util.tree_flatten(trees)
        # the compact tables are the wave packers' fresh np.stack
        # outputs, so the ISSUE-20 version chain can retain them as
        # frozen shadows and scatter only the changed elements into
        # the resident buffers (delta_src = the packing snapshot's
        # (store, index); eval-axis sharded puts below stay wholesale)
        buffers, _ = device_put_cached(leaves, version=cache_version,
                                       tags=[tag] * len(leaves),
                                       delta_src=delta_src)
        return jax.tree_util.tree_unflatten(treedef, buffers)
    # sharded route: the mesh factory, the PartitionSpec and the
    # NamedSharding put all live in parallel/mesh.py (the sharding-spec
    # registry; nomadlint's mesh-factory / no-implicit-put rules pin
    # the discipline)
    from ..parallel.mesh import shard_eval_axis
    return shard_eval_axis(trees, tag=tag)


@_single_flight
@functools.lru_cache(maxsize=None)
def _wave_compact_program(cm_shape, sp_shape, spread_alg: bool,
                          dtype_name: str, batched: bool, B: int,
                          use_block: bool):
    """Per-shape-bucket factory for the wavefront compact/block
    programs (the no-callsite-jit discipline: one jitted callable per
    bucket, constructed once behind this lru_cache). The two jit
    bodies differ statically: the block-merge kernel takes no spread
    tables (callers gate sp to zero-size)."""
    impl = (_solve_wave_block_impl if use_block
            else _solve_wave_compact_impl)
    inner = functools.partial(impl, spread_alg=spread_alg,
                              dtype_name=dtype_name, B=B)
    if use_block:
        k_blk, inner_blk = _wave_block_shape()
        inner = functools.partial(inner, K=k_blk, INNER=inner_blk)
    if batched:
        inner = jax.vmap(inner)

    if use_block:
        @jax.jit
        def fn(cm, sf, si, pn, spx):
            chosen, scores, ny = inner(cm, sf, si, pn)
            return jnp.stack([chosen.astype(scores.dtype), scores,
                              ny.astype(scores.dtype)])
    else:
        @jax.jit
        def fn(cm, sf, si, pn, spx):
            chosen, scores, ny = inner(cm, sf, si, pn, spx)
            return jnp.stack([chosen.astype(scores.dtype), scores,
                              ny.astype(scores.dtype)])
    return fn


def solve_lane_wave(const, init, batch, *, spread_alg: bool,
                    dtype_name: str, batched: bool = False,
                    cache_version=None, delta_src=None):
    """Wavefront solve with host precompute + compact transfer; returns
    host numpy (chosen int64, scores, n_yielded int64), shaped like
    solve_lane_fused's non-preempt outputs. The slot-buffer width B is
    picked from the lane's limit (WAVE_B for log2 windows, WAVE_B_WIDE
    for spread/affinity windows); callers guarantee it fits."""
    if batched:
        E = np.asarray(batch.ask_cpu).shape[0]
        P = int(np.asarray(batch.ask_cpu).shape[1])
        L = int(np.asarray(batch.limit)[0][0])
        B = wavefront_buffer_size(L)
        if B is None:
            raise ValueError(f"lane limit {L} exceeds every wavefront "
                             "buffer width (caller must gate on "
                             "wavefront_ok)")
        p_pad = _wave_p_bucket(P)
        # Deliberately a PER-LANE loop, not an (E, N) vectorized pass: a
        # batched numpy pack was built and measured 2x SLOWER at the
        # headline shape (60ms vs 32ms for 32 lanes x 10K nodes) -- the
        # per-lane arrays (~80KB) stay cache-resident while (E, N)
        # temporaries (~26MB apiece) thrash, and the fit-prefix
        # extraction needs a stable argsort batched vs a cheap nonzero
        # per lane. Inert padding lanes (active all-False, replicas of
        # lane 0 from the fuse path's E-bucket pinning) place nothing;
        # one precompute serves them all instead of E-e_real redundant
        # O(N) host folds.
        active_rows = np.asarray(batch.active).any(axis=1)

        def pack_one(e):
            return wavefront_compact_host(
                jax.tree_util.tree_map(lambda a: a[e], const),
                jax.tree_util.tree_map(lambda a: a[e], init),
                jax.tree_util.tree_map(lambda a: a[e], batch),
                dtype_name, p_pad=p_pad, B=B)

        inert_pack = None
        lanes = []
        for e in range(E):
            if not active_rows[e]:
                if inert_pack is None:
                    inert_pack = pack_one(e)
                lanes.append(inert_pack)
            else:
                lanes.append(pack_one(e))
        compact = np.stack([l[0] for l in lanes])
        scal_f = np.stack([l[1] for l in lanes])
        scal_i = np.stack([l[2] for l in lanes])
        pen = np.stack([l[3] for l in lanes])
        sp = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *[l[4] for l in lanes])
    else:
        P = int(np.asarray(batch.ask_cpu).shape[0])
        L = int(np.asarray(batch.limit)[0])
        B = wavefront_buffer_size(L)
        if B is None:
            raise ValueError(f"lane limit {L} exceeds every wavefront "
                             "buffer width (caller must gate on "
                             "wavefront_ok)")
        p_pad = _wave_p_bucket(P)
        compact, scal_f, scal_i, pen, sp = wavefront_compact_host(
            const, init, batch, dtype_name, p_pad=p_pad, B=B)

    # zero-size spread tables flow through uniformly: the kernel skips
    # spread work statically when S == 0. Lanes with no spreads and no
    # active reschedule penalties take the block-merge kernel (one chain
    # step per window event, ~10x fewer sequential steps -- see the
    # block comment at _solve_wave_block_impl); others take the
    # per-placement compact scan.
    use_block = (_wave_block_enabled()
                 and sp.counts.shape[-2] == 0
                 and bool((np.asarray(pen) < 0).all()))
    fn = _wave_compact_program(compact.shape, sp.counts.shape,
                               spread_alg, dtype_name, batched, B,
                               use_block)
    cm, sf, si, pn, spd = _put_eval_sharded(
        batched, compact.shape[0], (compact, scal_f, scal_i, pen, sp),
        cache_version=cache_version, delta_src=delta_src)
    out = fn(cm, sf, si, pn, spd)
    with jitcheck.sanctioned_fetch("wave"):
        combined = jax.device_get(out)
    from . import xferobs
    xferobs.note_fetch(xferobs.tree_nbytes(combined), "wave")
    # slice padded placement steps back off (outputs are [..., :p_pad])
    combined = combined[..., :P]
    return (combined[0].astype(np.int64), combined[1],
            combined[2].astype(np.int64))


def make_node_const(matrix, feasible: np.ndarray, affinity,
                    distinct_hosts: bool, spread_info, order: np.ndarray,
                    dtype=np.float32,
                    distinct_job_level: bool = False) -> NodeConst:
    """Assemble NodeConst in shuffled order (order[i] = original index of the
    node at shuffled position i)."""
    n_pad = matrix.n_pad
    perm = np.asarray(order, dtype=np.int64)
    cpu = matrix.cpu_cap[perm].astype(dtype)
    mem = matrix.mem_cap[perm].astype(dtype)
    disk = matrix.disk_cap[perm].astype(dtype)
    feas = (feasible & matrix.valid)[perm]
    aff = (affinity[perm].astype(dtype) if affinity is not None
           else np.zeros(n_pad, dtype=dtype))
    if spread_info is not None:
        vidx = spread_info.value_index[:, perm]
        desired = spread_info.desired.astype(dtype)
        has_t = spread_info.has_targets
        weights = spread_info.weights.astype(dtype)
        sum_w = np.asarray(spread_info.sum_weights, dtype=dtype)
        n_s = spread_info.n_spreads
    else:
        vidx = np.zeros((0, n_pad), dtype=np.int32)
        desired = np.zeros((0, 1), dtype=dtype)
        has_t = np.zeros(0, dtype=bool)
        weights = np.zeros(0, dtype=dtype)
        sum_w = np.asarray(0.0, dtype=dtype)
        n_s = 0
    # numpy-backed on purpose: lanes from many evals are np.stack'ed into
    # one (E, ...) batch before any device transfer (solver/batch.py)
    return NodeConst(
        cpu_cap=cpu, mem_cap=mem,
        disk_cap=disk, feasible=np.asarray(feas),
        affinity=aff,
        has_affinity=np.asarray(affinity is not None),
        distinct_hosts=np.asarray(bool(distinct_hosts)),
        distinct_job_level=np.asarray(bool(distinct_job_level)),
        spread_vidx=np.asarray(vidx), spread_desired=np.asarray(desired),
        spread_has_targets=np.asarray(has_t),
        spread_weights=np.asarray(weights),
        spread_sum_weights=np.asarray(sum_w),
        n_spreads=np.asarray(n_s, dtype=np.int32))


def make_node_state(usage, matrix, static_ports_free: np.ndarray,
                    order: np.ndarray, n_spreads: int, n_values: int,
                    spread_counts=None, dtype=np.float32) -> NodeState:
    perm = np.asarray(order, dtype=np.int64)
    counts = (spread_counts if spread_counts is not None
              else np.zeros((n_spreads, max(n_values, 1)), dtype=np.int32))
    return NodeState(
        used_cpu=usage.used_cpu[perm].astype(dtype),
        used_mem=usage.used_mem[perm].astype(dtype),
        used_disk=usage.used_disk[perm].astype(dtype),
        placed=np.asarray(usage.placed_jobtg[perm], dtype=np.int32),
        placed_job=np.asarray(usage.placed_job[perm], dtype=np.int32),
        static_free=np.asarray(static_ports_free[perm]),
        dyn_avail=(matrix.dyn_free - usage.dyn_used)[perm].astype(np.int32),
        spread_counts=np.asarray(counts))
