"""Accelerator backend guard: never let a wedged runtime stall scheduling.

A broken accelerator transport (observed live: the axon TPU tunnel left
with a stale device claim) can hang PJRT client init FOREVER -- not fail,
hang. A scheduler worker that walks into ``jax.device_count()`` then never
returns, evals pin at pending, and the cluster silently stops placing.
The reference never has this failure mode (its hot loop is host code);
the TPU-native design must degrade to the host oracle instead.

``backend_available()`` probes backend init ONCE per process in a daemon
thread with a hard deadline. A timed-out probe pins the answer False: the
leaked init thread cannot be cancelled, and any later jax call would hang
its caller the same way. Unlike rounds 3-4 this is no longer a one-way
trapdoor (VERDICT r4 weak #5):

  - ``state()`` exposes the guard for telemetry and /v1/agent/self;
  - every degraded dispatch is counted
    (``nomad.solver.host_fallback_dispatches``);
  - ``reprobe()`` (wired to POST /v1/operator/solver/reprobe) re-checks:
    if the original in-process probe thread finished late, the guard
    RECOVERS (ok=True -- the backend is genuinely usable from this
    process); otherwise a SUBPROCESS probe (own process group, hard
    timeout -- a wedged init can't hang the server) reports whether the
    transport itself is healthy again, in which case the process is
    still degraded but the operator knows a restart will recover it.
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Optional

_LOCK = threading.Lock()
_STATE = {
    "checked": False,
    "ok": False,
    "probe_started_at": None,      # epoch seconds
    "probe_timeout_s": None,
    "probe_timed_out": False,
    "recovered_late": False,
    "last_reprobe": None,          # dict, see reprobe()
}
_PROBE = {"done": None, "result": None}    # threading.Event / dict


def backend_available(timeout_s: float = 0.0) -> bool:
    # lock-free fast path for the steady healthy state: both flags are
    # only ever flipped under _LOCK, dict reads are atomic in CPython,
    # and a stale read here is benign (one extra locked check). The
    # degraded path still takes the lock for _maybe_recover_locked.
    if _STATE["checked"] and _STATE["ok"]:
        return True
    with _LOCK:
        if _STATE["checked"]:
            if not _STATE["ok"]:
                _maybe_recover_locked()
            return _STATE["ok"]
        timeout = timeout_s or float(
            os.environ.get("NOMAD_TPU_BACKEND_TIMEOUT", "30"))
        done = threading.Event()
        result = {"n": 0}
        _PROBE["done"] = done
        _PROBE["result"] = result

        def probe() -> None:
            try:
                import jax
                result["n"] = int(jax.device_count() or 0)
            except Exception:  # noqa: BLE001 -- any failure = no backend
                result["n"] = 0
            finally:
                done.set()

        t = threading.Thread(target=probe, daemon=True,
                             name="solver-backend-probe")
        _STATE["probe_started_at"] = time.time()
        _STATE["probe_timeout_s"] = timeout
        t.start()
        ok = done.wait(timeout) and result["n"] > 0
        _STATE["checked"] = True
        _STATE["ok"] = ok
        _STATE["probe_timed_out"] = not done.is_set()
        if not ok:
            from ..server.logbroker import log as _log
            from ..server.telemetry import metrics
            metrics.incr("nomad.solver.backend_unavailable")
            _log("error", "solver.guard",
                 "accelerator backend unavailable "
                 f"(init did not complete in {timeout:.0f}s); "
                 "scheduling falls back to the host oracle")
        return ok


def note_host_fallback() -> None:
    """Record one dispatch that degraded to the host oracle because the
    guard is down (observability: a silent permanent fallback was
    VERDICT r4 weak #5)."""
    from ..server.telemetry import metrics
    metrics.incr("nomad.solver.host_fallback_dispatches")


def _maybe_recover_locked() -> bool:
    """If the original in-process probe thread finished late with a
    live device count, the backend IS usable from this process: flip
    the guard back. Returns True on recovery."""
    done, result = _PROBE["done"], _PROBE["result"]
    if (done is not None and done.is_set()
            and result and result["n"] > 0 and not _STATE["ok"]):
        _STATE["ok"] = True
        _STATE["recovered_late"] = True
        from ..server.logbroker import log as _log
        from ..server.telemetry import metrics
        metrics.incr("nomad.solver.backend_recovered")
        _log("warn", "solver.guard",
             "accelerator backend recovered (late probe completion); "
             "dense scheduling re-enabled")
        return True
    return False


_SUBPROBE_SRC = (
    "import os\n"
    "os.environ.pop('JAX_PLATFORMS', None)\n"
    "import jax\n"
    "print('N:%d' % len(jax.devices()))\n"
)


def _subprocess_probe(timeout_s: float) -> dict:
    """Probe backend init in a THROWAWAY process (own process group,
    output to a temp file, hard kill of the group on timeout -- the
    bench.py pattern; a hung axon init forks helpers that inherit pipe
    ends, so pipes + communicate() can block past the timeout)."""
    import signal
    import tempfile

    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    with tempfile.TemporaryFile() as out:
        proc = subprocess.Popen(
            [sys.executable, "-c", _SUBPROBE_SRC],
            stdout=out, stderr=subprocess.DEVNULL,
            env=env, start_new_session=True)
        try:
            rc = proc.wait(timeout=timeout_s)
            timed_out = False
        except subprocess.TimeoutExpired:
            rc = None
            timed_out = True
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()     # reap; killpg makes this immediate
        out.seek(0)
        text = out.read().decode(errors="replace")
    n = 0
    if not timed_out and rc == 0:
        for line in text.splitlines():
            if line.startswith("N:"):
                n = int(line[2:])
    return {"timed_out": timed_out, "rc": rc, "devices": n}


def reprobe(timeout_s: Optional[float] = None) -> dict:
    """Operator-triggered recovery check. Never hangs the caller: the
    in-process check is a flag read; the transport check is a killable
    subprocess. Returns the guard state plus the probe report."""
    timeout = timeout_s or float(
        os.environ.get("NOMAD_TPU_REPROBE_TIMEOUT", "60"))
    with _LOCK:
        checked = _STATE["checked"]
    if not checked:
        # guard was never consulted: the authoritative answer is the
        # normal IN-PROCESS timed probe -- adopting a subprocess verdict
        # here would let a worker walk into an unguarded first jax init
        # (the exact hang the guard exists to prevent)
        ok = backend_available(timeout_s=min(timeout, 30.0))
        report = {"recovered": False, "subprocess": None,
                  "tunnel_ok_process_wedged": False,
                  "first_probe_ok": ok}
        with _LOCK:
            _STATE["last_reprobe"] = {
                "at": time.time(), "report": dict(report)}
        report["state"] = state()
        return report
    with _LOCK:
        recovered = _maybe_recover_locked()
    report = {"recovered": recovered, "subprocess": None,
              "tunnel_ok_process_wedged": False}
    if not recovered:
        sub = _subprocess_probe(timeout)
        report["subprocess"] = sub
        with _LOCK:
            report["tunnel_ok_process_wedged"] = (
                sub["devices"] > 0 and not _STATE["ok"]
                and _STATE["probe_timed_out"])
    with _LOCK:
        _STATE["last_reprobe"] = {"at": time.time(),
                                  "report": dict(report)}
    report["state"] = state()
    return report


def state() -> dict:
    """Guard snapshot for /v1/agent/self and telemetry dumps."""
    from ..server.telemetry import metrics
    with _LOCK:
        snap = {k: _STATE[k] for k in
                ("checked", "ok", "probe_started_at", "probe_timeout_s",
                 "probe_timed_out", "recovered_late", "last_reprobe")}
    counters = metrics.snapshot().get("counters", {})
    snap["backend_unavailable_total"] = counters.get(
        "nomad.solver.backend_unavailable", 0)
    snap["host_fallback_dispatches"] = counters.get(
        "nomad.solver.host_fallback_dispatches", 0)
    snap["recovered_total"] = counters.get(
        "nomad.solver.backend_recovered", 0)
    return snap


def _reset_for_tests() -> None:
    with _LOCK:
        _STATE.update(checked=False, ok=False, probe_started_at=None,
                      probe_timeout_s=None, probe_timed_out=False,
                      recovered_late=False, last_reprobe=None)
        _PROBE["done"] = None
        _PROBE["result"] = None
