"""Accelerator backend guard: never let a wedged runtime stall scheduling.

A broken accelerator transport (observed live: the axon TPU tunnel left
with a stale device claim) can hang PJRT client init FOREVER -- not fail,
hang. A scheduler worker that walks into ``jax.device_count()`` then never
returns, evals pin at pending, and the cluster silently stops placing.
The reference never has this failure mode (its hot loop is host code);
the TPU-native design must degrade to the host oracle instead.

Two layers of defense:

INIT GUARD -- ``backend_available()`` probes backend init ONCE per
process in a daemon thread with a hard deadline. A timed-out probe pins
the answer False: the leaked init thread cannot be cancelled, and any
later jax call would hang its caller the same way. Recovery paths:
``reprobe()`` (wired to POST /v1/operator/solver/reprobe) re-checks via
a late-thread flag read plus a killable SUBPROCESS probe.

DISPATCH BREAKER (round 6) -- init succeeding once proves nothing about
the tunnel staying up: round 5's wedge happened MID-ROUND, after the
guard had already said yes. So every device dispatch runs under a
watchdog deadline (``run_dispatch``, ``NOMAD_TPU_DISPATCH_TIMEOUT``);
a timeout or exception degrades that eval to the host oracle and feeds
a circuit breaker. ``NOMAD_TPU_BREAKER_THRESHOLD`` consecutive failures
trip the breaker OPEN (all dispatches skip straight to the host path);
a background recovery thread then reprobes with exponential backoff
(``NOMAD_TPU_BREAKER_BACKOFF`` .. ``_BACKOFF_MAX``, reusing the
killable subprocess probe) and auto-closes the breaker when a probe
passes -- no operator action needed, unlike the init guard. Breaker
state, trip/recovery counters and per-dispatch outcomes flow into
``state()`` -> /v1/agent/self, telemetry, and the bench artifacts
(benchkit.dispatch_health_stamp), so a wedged tunnel can never again
masquerade as a chip result.
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Optional, Tuple

_LOCK = threading.Lock()
_STATE = {
    "checked": False,
    "ok": False,
    "probe_started_at": None,      # epoch seconds
    "probe_timeout_s": None,
    "probe_timed_out": False,
    "recovered_late": False,
    "last_reprobe": None,          # dict, see reprobe()
}
# (checked, ok) replicated into ONE atomically-replaced tuple for the
# lock-free fast path: a single read can never observe a torn pair
# (ADVICE low #4). Only ever replaced under _LOCK via _set_flags_locked.
_FLAGS: Tuple[bool, bool] = (False, False)
_PROBE = {"done": None, "result": None}    # threading.Event / dict

# --- dispatch circuit breaker -----------------------------------------
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

_BREAKER = {
    "state": BREAKER_CLOSED,
    "consecutive_failures": 0,
    "trips": 0,
    "recoveries": 0,
    "last_trip_at": None,
    "last_failure": None,          # "timeout" | "error"
    "backoff_s": None,             # current recovery backoff
    "last_probe": None,            # {"at", "ok", "report"}
    "epoch": 0,                    # bumped on reset: stale threads exit
    "wake": None,                  # current recovery thread's Event
}


def _set_flags_locked(checked: bool, ok: bool) -> None:
    """Update both the rich state dict and the atomic fast-path tuple.
    Caller holds _LOCK."""
    global _FLAGS
    _STATE["checked"] = checked
    _STATE["ok"] = ok
    _FLAGS = (checked, ok)


def backend_available(timeout_s: float = 0.0) -> bool:
    # Lock-free fast path for the steady healthy state. ADVISORY ONLY:
    # both flags come from one atomically-replaced tuple so the pair is
    # never torn, but a reader racing a degradation flip may still see
    # one stale True -- callers use this to PREFER the dense path, never
    # for hard safety decisions (the dispatch watchdog is the hard
    # bound). The degraded path takes the lock for _maybe_recover_locked.
    checked, ok = _FLAGS
    if checked and ok:
        return True
    with _LOCK:
        if _STATE["checked"]:
            if not _STATE["ok"]:
                _maybe_recover_locked()
            return _STATE["ok"]
        timeout = timeout_s or float(
            os.environ.get("NOMAD_TPU_BACKEND_TIMEOUT", "30"))
        done = threading.Event()
        result = {"n": 0}
        _PROBE["done"] = done
        _PROBE["result"] = result

        def probe() -> None:
            try:
                import jax
                result["n"] = int(jax.device_count() or 0)
            except Exception:  # noqa: BLE001 -- any failure = no backend
                result["n"] = 0
            finally:
                done.set()

        t = threading.Thread(target=probe, daemon=True,
                             name="solver-backend-probe")
        _STATE["probe_started_at"] = time.time()
        _STATE["probe_timeout_s"] = timeout
        t.start()
        # the probe deadline is REAL time (schedcheck must not expire
        # it virtually early, or a healthy backend reads as down and
        # every eval silently degrades to the host oracle)
        from .. import schedcheck
        with schedcheck.real_time():
            ok = done.wait(timeout) and result["n"] > 0
        _set_flags_locked(True, ok)
        _STATE["probe_timed_out"] = not done.is_set()
        if not ok:
            from ..server.logbroker import log as _log
            from ..server.telemetry import metrics
            metrics.incr("nomad.solver.backend_unavailable")
            _log("error", "solver.guard",
                 "accelerator backend unavailable "
                 f"(init did not complete in {timeout:.0f}s); "
                 "scheduling falls back to the host oracle")
        return ok


def dispatch_allowed() -> bool:
    """Should the scheduler route this eval through the dense solver?
    False when backend init is down OR the dispatch breaker is open
    (including half-open: recovery is probe-driven, in-flight evals keep
    the host path until the breaker actually closes)."""
    if not backend_available():
        return False
    return _BREAKER["state"] == BREAKER_CLOSED


def note_host_fallback() -> None:
    """Record one dispatch that degraded to the host oracle because the
    guard/breaker is down (observability: a silent permanent fallback
    was VERDICT r4 weak #5)."""
    from ..server.telemetry import metrics
    metrics.incr("nomad.solver.host_fallback_dispatches")
    # pin the fallback onto the eval's trace: a degraded eval must be
    # attributable end-to-end, not just counted fleet-wide
    from ..server.tracing import tracer
    tracer.mark_degraded("host_fallback",
                         breaker=_BREAKER["state"],
                         backend_ok=_STATE["ok"])


# ----------------------------------------------------------------------
# Deadline-bounded dispatch


class DispatchFailed(RuntimeError):
    """One device dispatch timed out or raised; the eval must complete
    via the host oracle instead (parity-authoritative)."""

    def __init__(self, kind: str, msg: str):
        super().__init__(msg)
        self.kind = kind            # "timeout" | "error"


def dispatch_deadline_s() -> float:
    """Watchdog deadline per device dispatch; <= 0 disables the
    watchdog (dispatch runs inline, still breaker-accounted)."""
    return float(os.environ.get("NOMAD_TPU_DISPATCH_TIMEOUT", "30"))


def run_dispatch(fn, label: str = "solver.dispatch",
                 timeout_s: Optional[float] = None):
    """Run ONE device dispatch under the watchdog deadline.

    The dispatch executes on a daemon thread; if it neither returns nor
    raises within the deadline the caller gets DispatchFailed("timeout")
    immediately -- the stranded thread leaks (a hung XLA call cannot be
    cancelled) but the WORKER survives, which is the property round 5's
    wedge violated. The ``solver.dispatch`` fault point fires inside the
    watchdog so injected hangs exercise the timeout path for real.
    Outcomes feed the breaker: failures count toward a trip, success
    resets it.
    """
    from ..faultinject import faults
    from ..server.telemetry import metrics
    from ..server.tracing import tracer
    from .. import jitcheck, lockcheck, schedcheck

    if lockcheck._ACTIVE:
        # a dispatch can burn a full watchdog deadline; entering one
        # while holding locks starves every peer of those locks for the
        # same deadline (lockcheck held_across report)
        lockcheck.note_dispatch(label)
    if schedcheck._ACTIVE:
        # schedule-explorer interposition: dispatch entry is a
        # decision point (one module-attr read when off)
        schedcheck.yield_point("guard.run_dispatch")
    timeout = dispatch_deadline_s() if timeout_s is None else timeout_s
    box: dict = {}
    done = threading.Event()
    # explicit trace handoff: the dispatch executes on a fresh runner
    # thread, so the caller's eval/group ctx must travel with it or
    # every span recorded under the watchdog would be lost
    trace_ctx = tracer.current()
    eval_tag = ",".join(tracer.current_ids()) or "-"

    def runner() -> None:
        # jitcheck hot region: host syncs between here and the fn()
        # return are hot-path syncs (jitcheck.py check b). Gated on one
        # module-attr read when off, like the lockcheck hook above.
        hot = jitcheck._ACTIVE
        if hot:
            jitcheck.note_dispatch_begin(label)
        try:
            with tracer.activate(trace_ctx):
                faults.fire("solver.dispatch")
                box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 -- reported to caller
            box["error"] = e
        finally:
            if hot:
                jitcheck.note_dispatch_end()
            done.set()

    if timeout <= 0:
        runner()
    else:
        t = threading.Thread(target=runner, daemon=True,
                             name=f"dispatch-{label}")
        t.start()
        # the watchdog deadline is REAL time: under a schedcheck run
        # this wait must not be virtualized into an early timeout (a
        # falsely-expired deadline would degrade the eval to the host
        # oracle and break kill-switch parity)
        with schedcheck.real_time():
            expired = not done.wait(timeout)
        if expired:
            metrics.incr("nomad.solver.dispatch_timeout")
            record_dispatch_failure("timeout")
            tracer.mark_degraded("watchdog_timeout", ctx=trace_ctx,
                                 label=label, deadline_s=timeout)
            from ..server.logbroker import log as _log
            _log("error", "solver.guard",
                 f"eval={eval_tag} {label} exceeded its "
                 f"{timeout:.1f}s deadline; eval degrades to the host "
                 "oracle (dispatch thread abandoned)")
            raise DispatchFailed(
                "timeout", f"{label} exceeded {timeout:.1f}s deadline")
    if "error" in box:
        metrics.incr("nomad.solver.dispatch_error")
        record_dispatch_failure("error")
        err = box["error"]
        tracer.mark_degraded("dispatch_error", ctx=trace_ctx,
                             label=label, error=type(err).__name__)
        from ..server.logbroker import log as _log
        _log("error", "solver.guard",
             f"eval={eval_tag} {label} failed "
             f"({type(err).__name__}: {err}); eval degrades to the "
             "host oracle")
        raise DispatchFailed(
            "error", f"{label} failed: {type(err).__name__}: {err}"
        ) from err
    metrics.incr("nomad.solver.dispatch_ok")
    record_dispatch_success()
    return box["result"]


# ----------------------------------------------------------------------
# Circuit breaker


def _invalidate_pack_layer(reason: str) -> None:
    """Drop the host-side pack caches + fused-stack arena alongside the
    const cache on a breaker edge. Resolved via sys.modules so a guard
    used without the pack stack never imports it; correctness does not
    depend on this (the caches are version/snapshot-keyed) -- it
    guarantees nothing derived before a wedge survives past recovery."""
    import sys as _sys
    tp = _sys.modules.get("nomad_tpu.tensor.pack")
    if tp is not None:
        tp.invalidate_pack_caches(reason)
    bt = _sys.modules.get("nomad_tpu.solver.batch")
    if bt is not None:
        bt.arena_clear(reason)


def _breaker_threshold() -> int:
    return max(1, int(os.environ.get("NOMAD_TPU_BREAKER_THRESHOLD", "3")))


def record_dispatch_failure(kind: str) -> None:
    """One dispatch timed out or errored. Trips the breaker at
    NOMAD_TPU_BREAKER_THRESHOLD consecutive failures and starts the
    background recovery loop."""
    with _LOCK:
        _BREAKER["consecutive_failures"] += 1
        _BREAKER["last_failure"] = kind
        if (_BREAKER["state"] == BREAKER_CLOSED
                and _BREAKER["consecutive_failures"]
                >= _breaker_threshold()):
            _trip_locked(kind)


def record_dispatch_success() -> None:
    with _LOCK:
        _BREAKER["consecutive_failures"] = 0
        # a real dispatch landed: the flap-damping backoff can relax
        _BREAKER["backoff_s"] = None


def _trip_locked(kind: str) -> None:
    _BREAKER["state"] = BREAKER_OPEN
    _BREAKER["trips"] += 1
    _BREAKER["last_trip_at"] = time.time()
    epoch = _BREAKER["epoch"]
    wake = threading.Event()       # fresh per thread: a stale set() from
    _BREAKER["wake"] = wake        # an earlier reset must not skip the
    from ..server.logbroker import log as _log      # first backoff
    from ..server.telemetry import metrics
    metrics.incr("nomad.solver.breaker_trips")
    # drop device-resident const buffers: whatever wedged the transport
    # may have invalidated them, and nothing should dispatch against
    # them until a recovery probe passes anyway
    from .constcache import invalidate_all
    invalidate_all("breaker trip")
    _invalidate_pack_layer("breaker trip")
    # every in-flight eval is now degraded, not just the dispatch that
    # tripped the breaker: stamp all active traces so each one is
    # retained and attributable
    from ..server.tracing import tracer
    tracer.broadcast_event("breaker.trip",
                           degraded_reason="breaker_open", kind=kind)
    _log("error", "solver.guard",
         f"dispatch breaker OPEN after "
         f"{_BREAKER['consecutive_failures']} consecutive {kind}s; "
         "dense dispatch disabled, background recovery probing starts")
    t = threading.Thread(target=_run_recovery, args=(epoch, wake),
                         daemon=True, name="solver-breaker-recovery")
    t.start()


def _run_recovery(epoch: int, wake: threading.Event) -> None:
    """Background half-open loop: exponential backoff between probes;
    the first passing probe closes the breaker (auto-recovery -- round
    5 required a manual operator reprobe())."""
    initial = float(os.environ.get("NOMAD_TPU_BREAKER_BACKOFF", "1.0"))
    mx = float(os.environ.get("NOMAD_TPU_BREAKER_BACKOFF_MAX", "60.0"))
    with _LOCK:
        # persist backoff across flaps: a probe-pass -> dispatch-fail ->
        # re-trip cycle resumes where it left off instead of hammering
        backoff = _BREAKER["backoff_s"] or initial
        _BREAKER["backoff_s"] = backoff
    while True:
        wake.wait(backoff)
        wake.clear()
        with _LOCK:
            if (_BREAKER["epoch"] != epoch
                    or _BREAKER["state"] == BREAKER_CLOSED):
                return
            _BREAKER["state"] = BREAKER_HALF_OPEN
        ok, report = _breaker_probe()
        with _LOCK:
            if (_BREAKER["epoch"] != epoch
                    or _BREAKER["state"] == BREAKER_CLOSED):
                return
            _BREAKER["last_probe"] = {"at": time.time(), "ok": ok,
                                      "report": report}
            if ok:
                _close_breaker_locked("recovery probe passed")
                return
            _BREAKER["state"] = BREAKER_OPEN
            backoff = min(backoff * 2.0, mx)
            _BREAKER["backoff_s"] = backoff


def _close_breaker_locked(why: str) -> None:
    _BREAKER["state"] = BREAKER_CLOSED
    _BREAKER["consecutive_failures"] = 0
    _BREAKER["recoveries"] += 1
    from ..server.logbroker import log as _log
    from ..server.telemetry import metrics
    metrics.incr("nomad.solver.breaker_recoveries")
    # re-open with a clean slate: buffers uploaded through the
    # pre-wedge transport are not trusted across a recovery
    from .constcache import invalidate_all
    invalidate_all("breaker recovery")
    _invalidate_pack_layer("breaker recovery")
    _log("warn", "solver.guard",
         f"dispatch breaker CLOSED ({why}); dense dispatch re-enabled")


def _breaker_probe() -> Tuple[bool, dict]:
    """Is the backend healthy enough to close the breaker? Order:
      1. the ``solver.probe`` fault point (chaos tests hold the breaker
         open through this; unarmed it costs one attribute read);
      2. late in-process init recovery (free flag read);
      3. init still down -> fail (the INIT guard owns that recovery);
      4. the killable subprocess probe: verifies the TRANSPORT can
         still bring a backend up -- the mid-round tunnel wedge fails
         exactly here while the in-process client still looks alive.
    """
    from ..faultinject import faults
    report: dict = {}
    try:
        faults.fire("solver.probe")
    except Exception as e:  # noqa: BLE001 -- injected faults vary
        return False, {"fault_injected": f"{type(e).__name__}: {e}"}
    with _LOCK:
        recovered = _maybe_recover_locked()
        in_ok = _STATE["checked"] and _STATE["ok"]
    report["in_process_ok"] = bool(in_ok or recovered)
    if not (in_ok or recovered):
        return False, report
    # CPU backend: there is no external transport that can wedge, so
    # in-process health is authoritative; the subprocess probe would
    # probe the RAW platform (it strips JAX_PLATFORMS to test the real
    # accelerator transport) and on a CPU-pinned deployment that can
    # spin in TPU-plugin discovery forever.
    try:
        import jax                   # init already completed (in_ok)
        if jax.default_backend() == "cpu":
            report["cpu_backend"] = True
            return True, report
    except Exception:  # noqa: BLE001 -- fall through to the subprocess
        pass
    timeout = float(os.environ.get(
        "NOMAD_TPU_BREAKER_PROBE_TIMEOUT",
        os.environ.get("NOMAD_TPU_REPROBE_TIMEOUT", "60")))
    sub = _subprocess_probe(timeout)
    report["subprocess"] = sub
    return (not sub["timed_out"] and sub["devices"] > 0), report


def reset_breaker() -> None:
    """Close the breaker and invalidate any recovery thread (operator
    reprobe recovery, tests)."""
    with _LOCK:
        _BREAKER["epoch"] += 1
        if _BREAKER["state"] != BREAKER_CLOSED:
            _close_breaker_locked("operator reset")
        _BREAKER["consecutive_failures"] = 0
        _BREAKER["backoff_s"] = None
        wake = _BREAKER["wake"]
    if wake is not None:
        wake.set()               # stale recovery thread exits promptly


def breaker_state() -> dict:
    with _LOCK:
        return {k: _BREAKER[k] for k in
                ("state", "consecutive_failures", "trips", "recoveries",
                 "last_trip_at", "last_failure", "backoff_s",
                 "last_probe")}


# ----------------------------------------------------------------------
# Init-guard recovery (rounds 5-): late-thread flag + subprocess probe


def _maybe_recover_locked() -> bool:
    """If the original in-process probe thread finished late with a
    live device count, the backend IS usable from this process: flip
    the guard back. Returns True on recovery."""
    done, result = _PROBE["done"], _PROBE["result"]
    if (done is not None and done.is_set()
            and result and result["n"] > 0 and not _STATE["ok"]):
        _set_flags_locked(True, True)
        _STATE["recovered_late"] = True
        from ..server.logbroker import log as _log
        from ..server.telemetry import metrics
        metrics.incr("nomad.solver.backend_recovered")
        _log("warn", "solver.guard",
             "accelerator backend recovered (late probe completion); "
             "dense scheduling re-enabled")
        return True
    return False


_SUBPROBE_SRC = (
    "import os\n"
    "os.environ.pop('JAX_PLATFORMS', None)\n"
    "import jax\n"
    "print('N:%d' % len(jax.devices()))\n"
)


def _subprocess_probe(timeout_s: float) -> dict:
    """Probe backend init in a THROWAWAY process (own process group,
    output to a temp file, hard kill of the group on timeout -- the
    bench.py pattern; a hung axon init forks helpers that inherit pipe
    ends, so pipes + communicate() can block past the timeout)."""
    import signal
    import tempfile

    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    with tempfile.TemporaryFile() as out:
        proc = subprocess.Popen(
            [sys.executable, "-c", _SUBPROBE_SRC],
            stdout=out, stderr=subprocess.DEVNULL,
            env=env, start_new_session=True)
        try:
            rc = proc.wait(timeout=timeout_s)
            timed_out = False
        except subprocess.TimeoutExpired:
            rc = None
            timed_out = True
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()     # reap; killpg makes this immediate
        out.seek(0)
        text = out.read().decode(errors="replace")
    n = 0
    if not timed_out and rc == 0:
        for line in text.splitlines():
            if line.startswith("N:"):
                n = int(line[2:])
    return {"timed_out": timed_out, "rc": rc, "devices": n}


def reprobe(timeout_s: Optional[float] = None) -> dict:
    """Operator-triggered recovery check. Never hangs the caller: the
    in-process check is a flag read; the transport check is a killable
    subprocess. Returns the guard state plus the probe report. A
    recovery here also resets the dispatch breaker -- the operator just
    verified the backend, stale trip state must not keep degrading."""
    timeout = timeout_s or float(
        os.environ.get("NOMAD_TPU_REPROBE_TIMEOUT", "60"))
    with _LOCK:
        checked = _STATE["checked"]
    if not checked:
        # guard was never consulted: the authoritative answer is the
        # normal IN-PROCESS timed probe -- adopting a subprocess verdict
        # here would let a worker walk into an unguarded first jax init
        # (the exact hang the guard exists to prevent)
        ok = backend_available(timeout_s=min(timeout, 30.0))
        report = {"recovered": False, "subprocess": None,
                  "tunnel_ok_process_wedged": False,
                  "first_probe_ok": ok}
        with _LOCK:
            _STATE["last_reprobe"] = {
                "at": time.time(), "report": dict(report)}
        report["state"] = state()
        return report
    with _LOCK:
        recovered = _maybe_recover_locked()
    report = {"recovered": recovered, "subprocess": None,
              "tunnel_ok_process_wedged": False}
    if not recovered:
        sub = _subprocess_probe(timeout)
        report["subprocess"] = sub
        with _LOCK:
            report["tunnel_ok_process_wedged"] = (
                sub["devices"] > 0 and not _STATE["ok"]
                and _STATE["probe_timed_out"])
    if recovered:
        reset_breaker()
    with _LOCK:
        _STATE["last_reprobe"] = {"at": time.time(),
                                  "report": dict(report)}
    report["state"] = state()
    return report


def state() -> dict:
    """Guard snapshot for /v1/agent/self, telemetry dumps, and bench
    artifacts. ``degraded`` is the one-glance verdict: True whenever ANY
    layer is routing evals to the host oracle."""
    from ..server.telemetry import metrics
    with _LOCK:
        snap = {k: _STATE[k] for k in
                ("checked", "ok", "probe_started_at", "probe_timeout_s",
                 "probe_timed_out", "recovered_late", "last_reprobe")}
        breaker = {k: _BREAKER[k] for k in
                   ("state", "consecutive_failures", "trips",
                    "recoveries", "last_trip_at", "last_failure",
                    "backoff_s", "last_probe")}
    _msnap = metrics.snapshot()
    counters = _msnap.get("counters", {})
    snap["backend_unavailable_total"] = counters.get(
        "nomad.solver.backend_unavailable", 0)
    snap["host_fallback_dispatches"] = counters.get(
        "nomad.solver.host_fallback_dispatches", 0)
    snap["recovered_total"] = counters.get(
        "nomad.solver.backend_recovered", 0)
    snap["breaker"] = breaker
    snap["dispatch"] = {
        "ok": counters.get("nomad.solver.dispatch_ok", 0),
        "timeout": counters.get("nomad.solver.dispatch_timeout", 0),
        "error": counters.get("nomad.solver.dispatch_error", 0),
        "bytes_total": counters.get(
            "nomad.solver.dispatch_bytes_total", 0),
    }
    # transfer layer: device-resident const cache + async pipeline
    # (lazy imports -- state() must stay callable without pulling the
    # dispatch stack into light callers)
    from .constcache import stats as _cc_stats
    snap["const_cache"] = _cc_stats()
    try:
        from .batch import pipeline_state
        snap["dispatch_pipeline"] = pipeline_state()
    except Exception:  # noqa: BLE001 -- status must never fail the agent
        snap["dispatch_pipeline"] = {"depth": 1, "in_flight": 0,
                                     "active": False}
    # host-side pack layer: snapshot-scoped pack caches + fused-stack
    # arena (ISSUE 4) -- same one-glance surface as the const cache
    try:
        from ..tensor.pack import pack_cache_stats
        snap["pack_cache"] = pack_cache_stats()
    except Exception:  # noqa: BLE001 -- status must never fail the agent
        snap["pack_cache"] = {}
    try:
        from .batch import arena_state
        snap["pack_arena"] = arena_state()
    except Exception:  # noqa: BLE001 -- status must never fail the agent
        snap["pack_arena"] = {}
    snap["pack"] = {
        "ms": _msnap.get("samples", {}).get("nomad.solver.pack_ms", {}),
        "cache_hit": counters.get("nomad.solver.pack_cache_hit", 0),
        "cache_miss": counters.get("nomad.solver.pack_cache_miss", 0),
    }
    # mesh execution (ISSUE 19): knob + picked grid + dispatch counters
    try:
        from .service import mesh_status
        snap["mesh"] = mesh_status()
    except Exception:  # noqa: BLE001 -- status must never fail the agent
        snap["mesh"] = {}
    snap["degraded"] = bool(
        (snap["checked"] and not snap["ok"])
        or breaker["state"] != BREAKER_CLOSED)
    return snap


def _reset_for_tests() -> None:
    with _LOCK:
        _set_flags_locked(False, False)
        _STATE.update(probe_started_at=None,
                      probe_timeout_s=None, probe_timed_out=False,
                      recovered_late=False, last_reprobe=None)
        _PROBE["done"] = None
        _PROBE["result"] = None
        _BREAKER["epoch"] += 1
        wake = _BREAKER["wake"]
        _BREAKER.update(state=BREAKER_CLOSED, consecutive_failures=0,
                        trips=0, recoveries=0, last_trip_at=None,
                        last_failure=None, backoff_s=None,
                        last_probe=None, wake=None)
    if wake is not None:
        wake.set()
