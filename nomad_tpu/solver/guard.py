"""Accelerator backend guard: never let a wedged runtime stall scheduling.

A broken accelerator transport (observed live: the axon TPU tunnel left
with a stale device claim) can hang PJRT client init FOREVER -- not fail,
hang. A scheduler worker that walks into ``jax.device_count()`` then never
returns, evals pin at pending, and the cluster silently stops placing.
The reference never has this failure mode (its hot loop is host code);
the TPU-native design must degrade to the host oracle instead.

``backend_available()`` probes backend init ONCE per process in a daemon
thread with a hard deadline. A timed-out probe pins the answer False for
the process lifetime: the leaked init thread can never be cancelled, and
any later jax call would hang its caller the same way. All dense-path
entry points consult it before touching jax.
"""
from __future__ import annotations

import os
import threading

_STATE = {"checked": False, "ok": False}
_LOCK = threading.Lock()


def backend_available(timeout_s: float = 0.0) -> bool:
    with _LOCK:
        if _STATE["checked"]:
            return _STATE["ok"]
        timeout = timeout_s or float(
            os.environ.get("NOMAD_TPU_BACKEND_TIMEOUT", "30"))
        done = threading.Event()
        result = {"n": 0}

        def probe() -> None:
            try:
                import jax
                result["n"] = jax.device_count()
            except Exception:  # noqa: BLE001 -- any failure = no backend
                result["n"] = 0
            finally:
                done.set()

        t = threading.Thread(target=probe, daemon=True,
                             name="solver-backend-probe")
        t.start()
        ok = done.wait(timeout) and result["n"] > 0
        _STATE["checked"] = True
        _STATE["ok"] = ok
        if not ok:
            from ..server.telemetry import metrics
            metrics.incr("nomad.solver.backend_unavailable")
            import sys
            print("[nomad-tpu] accelerator backend unavailable "
                  f"(init did not complete in {timeout:.0f}s); "
                  "scheduling falls back to the host oracle",
                  file=sys.stderr)
        return ok


def _reset_for_tests() -> None:
    with _LOCK:
        _STATE["checked"] = False
        _STATE["ok"] = False
