"""TPU solver: dense vmapped placement engine (the north-star component)."""
from .binpack import (  # noqa: F401
    NodeConst, NodeState, PlacementBatch, make_node_const, make_node_state,
    solve_placements,
)
from .service import TpuPlacement, TpuPlacementService, tg_solver_eligible  # noqa: F401
