"""Persistent XLA compilation cache for the solver's jitted programs.

The solver compiles one program per (signature, E-bucket, P-bucket, N)
shape variant; each dense-kernel compile costs seconds (CPU backend) to
tens of seconds (first TPU compile). In-process jax caching already
dedupes within one server lifetime; this enables jax's on-disk cache so
restarts, test runs and bench processes skip recompiling variants any
prior process already built. Opt-out with NOMAD_TPU_COMPILE_CACHE=0;
override the location with NOMAD_TPU_COMPILE_CACHE=<dir>.

The reference has no analog (its hot loop is host Go); this is purely a
TPU-runtime concern, the moral equivalent of its compiled binary being
reusable across restarts.
"""
from __future__ import annotations

import os
import tempfile
import threading

_LOCK = threading.Lock()
_DONE = False


def enable_compile_cache() -> None:
    """Idempotent; safe to call before every solver dispatch."""
    global _DONE
    with _LOCK:
        if _DONE:
            return
        _DONE = True
        raw = os.environ.get("NOMAD_TPU_COMPILE_CACHE", "")
        if raw == "0":
            return
        # uid-suffixed: a fixed path in the shared tmp dir would let
        # another user pre-create it (silent recompiles) or pre-plant
        # cache entries that get deserialized into this process
        path = raw or os.path.join(
            tempfile.gettempdir(),
            f"nomad_tpu_xla_cache_{os.getuid()}")
        try:
            os.makedirs(path, exist_ok=True)
            import jax
            jax.config.update("jax_compilation_cache_dir", path)
            # the dense kernels compile in 1-10s; cache everything
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.5)
        except Exception:  # noqa: BLE001 -- cache is best-effort
            pass
